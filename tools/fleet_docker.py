#!/usr/bin/env python
"""Bootstrap and exercise a real-SSH worker fleet in local docker
containers (docker/compose.yml: two sshd nodes with the repo
bind-mounted read-only at /repo).

Usage::

    python tools/fleet_docker.py up       # keygen + build + wait for sshd
    python tools/fleet_docker.py run      # campaign across both nodes
    python tools/fleet_docker.py workers  # print the --workers spec
    python tools/fleet_docker.py down     # tear the fleet down

Exit codes: 0 success, 1 the step failed (campaign incomplete, a cell
without a true outcome, unsynced artifacts, fleetlint errors), 2
docker/compose unavailable.

``run`` goes through ``fleet.dispatch.run_fleet`` directly (not the
CLI) because the workers' repo lives at a DIFFERENT path than the
coordinator's (/repo in-container vs the checkout on the host), so
the dispatcher needs explicit ``cwd="/repo"`` / ``python="python3"``.
Everything else is the stock fleet path: leases journaled to
cells.jsonl, results over stdin/stdout, artifact sync over real scp
with manifest verification, clock skew normalized from the lease
handshake stamps.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DOCKER_DIR = os.path.join(REPO, "docker")
KEYS_DIR = os.path.join(DOCKER_DIR, ".keys")
PRIVATE_KEY = os.path.join(KEYS_DIR, "id_ed25519")

#: (worker id, mapped loopback port) for each compose service
NODES = (("node1", 2221), ("node2", 2222))

#: where each worker container writes its runs (its OWN filesystem:
#: artifact sync must move bytes over scp to get them home)
WORKER_STORE = "/tmp/jepsen-fleet-store"


def workers_spec():
    """The ``--workers`` string for the compose fleet."""
    return ",".join(f"{wid}=127.0.0.1:{port}" for wid, port in NODES)


def ssh_spec():
    """The conn-spec mapping ``dispatch.parse_workers`` consumes."""
    return {"username": "root", "private-key-path": PRIVATE_KEY,
            "strict-host-key-checking": False}


def compose_argv():
    """A usable `docker compose` invocation, or None."""
    exe = shutil.which("docker")
    if exe is None:
        return None
    probe = subprocess.run([exe, "compose", "version"],
                           capture_output=True, text=True)
    if probe.returncode == 0:
        return [exe, "compose"]
    legacy = shutil.which("docker-compose")
    return [legacy] if legacy else None


def compose(args, check=True):
    argv = compose_argv()
    if argv is None:
        print("fleet_docker: docker compose is not available", flush=True)
        sys.exit(2)
    return subprocess.run(argv + ["-f",
                                  os.path.join(DOCKER_DIR, "compose.yml")]
                          + args, check=check)


def ensure_keys():
    """Generate the fleet keypair once (docker/.keys/, gitignored)."""
    if os.path.exists(PRIVATE_KEY):
        return
    os.makedirs(KEYS_DIR, exist_ok=True)
    subprocess.run(["ssh-keygen", "-t", "ed25519", "-N", "", "-q",
                    "-C", "jepsen-fleet", "-f", PRIVATE_KEY], check=True)
    print(f"fleet_docker: generated {PRIVATE_KEY}", flush=True)


def wait_for_sshd(timeout_s=120.0):
    """Poll ``ssh ... true`` on every node until the fleet answers."""
    pending = dict(NODES)
    deadline = time.monotonic() + timeout_s
    while pending and time.monotonic() < deadline:
        for wid, port in list(pending.items()):
            res = subprocess.run(
                ["ssh", "-o", "BatchMode=yes",
                 "-o", "StrictHostKeyChecking=no",
                 "-o", "UserKnownHostsFile=/dev/null",
                 "-o", "ConnectTimeout=3",
                 "-p", str(port), "-i", PRIVATE_KEY,
                 "root@127.0.0.1", "true"],
                capture_output=True, text=True)
            if res.returncode == 0:
                print(f"fleet_docker: {wid} (port {port}) is up",
                      flush=True)
                del pending[wid]
        if pending:
            time.sleep(2)
    if pending:
        print(f"fleet_docker: sshd never answered on {sorted(pending)}",
              flush=True)
        return False
    return True


def up():
    ensure_keys()
    compose(["up", "-d", "--build"])
    return 0 if wait_for_sshd() else 1


def down():
    compose(["down", "--volumes", "--remove-orphans"])
    return 0


def run_campaign(campaign_id="docker-fleet", time_limit=2):
    """A 2x2 register campaign across the container fleet, asserting
    the remote path end to end: completion, outcomes, synced +
    manifest-verified artifacts, clean fleetlint audit."""
    from jepsen_tpu import campaign, store
    from jepsen_tpu.fleet import dispatch

    cells = campaign.plan.expand(
        {"axes": {"workload": ["register"], "seed": [0, 1]}})
    workers = dispatch.parse_workers(workers_spec(), ssh=ssh_spec())
    base = {"nodes": ["n1"], "concurrency": 2,
            "ssh": {"dummy?": True},       # in-worker DB nodes stay dummy
            "time-limit": time_limit, "workload": "register"}
    report = dispatch.run_fleet(
        cells, workers, campaign_id=campaign_id,
        builder="jepsen_tpu.demo:demo_test", base_options=base,
        python="python3", cwd="/repo",
        env={"JAX_PLATFORMS": "cpu"},
        worker_store_dir=WORKER_STORE,
        lease_s=300, sync_timeout_s=120)

    failures = []
    meta = json.load(open(store.campaign_path(campaign_id,
                                              "campaign.json")))
    if meta.get("status") != "complete":
        failures.append(f"campaign status {meta.get('status')!r}")
    recs = {str(r.get("cell")): r
            for r in store.latest_campaign_records(campaign_id)}
    for c in cells:
        rec = recs.get(c["id"])
        if rec is None or rec.get("outcome") is not True:
            failures.append(f"cell {c['id']}: outcome "
                            f"{(rec or {}).get('outcome')!r}")
        elif not rec.get("synced"):
            failures.append(f"cell {c['id']}: artifacts not synced "
                            f"({rec.get('sync-error')})")
        elif rec.get("path") and not os.path.isdir(str(rec["path"])):
            failures.append(f"cell {c['id']}: synced run dir missing "
                            f"{rec['path']}")
    fa_path = store.campaign_path(campaign_id, "fleet_analysis.json")
    try:
        fa = json.load(open(fa_path))
        counts = fa.get("counts") or {}
        if counts.get("error"):
            failures.append(f"fleetlint: {counts['error']} error(s)")
    except (OSError, json.JSONDecodeError) as e:
        failures.append(f"no readable fleet_analysis.json: {e}")
    print(f"fleet_docker: campaign {campaign_id}: "
          f"{len(report.get('results') or [])} results, "
          f"{len(failures)} failure(s)", flush=True)
    for f in failures:
        print(f"fleet_docker: FAIL {f}", flush=True)
    return 1 if failures else 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="tools/fleet_docker.py")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("up", help="keygen + compose up + wait for sshd")
    sub.add_parser("down", help="compose down")
    sub.add_parser("workers", help="print the --workers spec")
    runp = sub.add_parser("run", help="campaign across the fleet")
    runp.add_argument("--campaign-id", default="docker-fleet")
    runp.add_argument("--time-limit", type=int, default=2)
    ns = p.parse_args(argv)
    if ns.cmd == "up":
        return up()
    if ns.cmd == "down":
        return down()
    if ns.cmd == "workers":
        print(workers_spec())
        return 0
    return run_campaign(campaign_id=ns.campaign_id,
                        time_limit=ns.time_limit)


if __name__ == "__main__":
    sys.exit(main())
