"""Summarize a test run's observability artifacts from its store dir.

Reads ``trace.jsonl`` + ``metrics.json`` (written by jepsen_tpu.store
next to results.json) and prints:

* per-lifecycle-phase wall time (the ``X`` spans with cat=lifecycle),
* op-latency quantiles (p50/p90/p99) from the interpreter's op spans,
  falling back to the metrics histogram when the trace has no op spans,
* op counts by f/type and the WGL search telemetry (states explored,
  chunk count, dedup-table load) from metrics.json,
* the streaming monitor's telemetry (ops consumed, chunk checks,
  detection latency) plus its violation instant from the trace.

Usage::

    python tools/trace_summary.py [STORE_DIR]

STORE_DIR defaults to ``store/latest``. Accepts either a run directory
(containing trace.jsonl) or anything with those two files in it.
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_trace(path):
    from jepsen_tpu.obs import load_trace
    return load_trace(path)


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    # nearest-rank: smallest index covering a q fraction of the sample
    # (int(q*len) would bias high -- p50 of 2 samples must be the lower)
    i = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[i]


def _fmt_s(us):
    return f"{us / 1e6:10.3f}s"


def summarize(store_dir):
    """Render the summary for one run directory; returns the text."""
    lines = [f"== {store_dir} =="]
    trace_path = os.path.join(store_dir, "trace.jsonl")
    metrics_path = os.path.join(store_dir, "metrics.json")

    events = []
    if os.path.exists(trace_path):
        events = _load_trace(trace_path)

    # -- per-phase wall time -------------------------------------------
    phases = [e for e in events
              if e.get("ph") == "X" and e.get("cat") == "lifecycle"]
    if phases:
        lines.append("\n-- lifecycle phases (wall time) --")
        for e in sorted(phases, key=lambda e: e["ts"]):
            lines.append(f"{_fmt_s(e.get('dur', 0.0))}  {e['name']}")

    # -- op latency quantiles ------------------------------------------
    op_durs_us = sorted(e.get("dur", 0.0) for e in events
                        if e.get("ph") == "X" and e.get("cat") == "op")
    if op_durs_us:
        lines.append(f"\n-- op latency ({len(op_durs_us)} ops, "
                     "from trace spans) --")
        for q in (0.5, 0.9, 0.99):
            v = _quantile(op_durs_us, q)
            lines.append(f"p{int(q * 100):<3} {v / 1e3:10.3f} ms")

    metrics = None
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            metrics = json.load(f)

    if metrics:
        if not op_durs_us:
            h = metrics.get("histograms", {}) \
                .get("interpreter.op_latency_s")
            if h and h.get("count"):
                lines.append(f"\n-- op latency ({h['count']} ops, "
                             "from metrics histogram) --")
                lines.append(
                    f"mean {h['sum'] / h['count'] * 1e3:10.3f} ms   "
                    f"max {h['max'] * 1e3:10.3f} ms")
        counters = metrics.get("counters", {})
        ops = {k: v for k, v in sorted(counters.items())
               if k.startswith("interpreter.ops_completed")}
        if ops:
            lines.append("\n-- op counts --")
            for k, v in ops.items():
                lines.append(f"{v:8d}  {k}")
        wgl = {k: v for k, v in sorted(counters.items())
               if k.startswith("wgl.")}
        wgl.update({k: v for k, v in
                    sorted(metrics.get("gauges", {}).items())
                    if k.startswith("wgl.")})
        if wgl:
            lines.append("\n-- WGL search telemetry --")
            for k, v in wgl.items():
                lines.append(f"{v!s:>12}  {k}")

        mon = {k: v for k, v in sorted(counters.items())
               if k.startswith("monitor.")}
        mon.update({k: v for k, v in
                    sorted(metrics.get("gauges", {}).items())
                    if k.startswith("monitor.")})
        mh = metrics.get("histograms", {}).get("monitor.check_s")
        if mon or mh:
            lines.append("\n-- streaming monitor --")
            for k, v in mon.items():
                lines.append(f"{v!s:>12}  {k}")
            if mh and mh.get("count"):
                lines.append(
                    f"check wall: mean "
                    f"{mh['sum'] / mh['count'] * 1e3:.1f} ms   "
                    f"max {mh['max'] * 1e3:.1f} ms over {mh['count']} "
                    "check(s)")

    # the monitor's violation instant, if the run recorded one
    violations = [e for e in events
                  if e.get("ph") == "i"
                  and e.get("name") == "monitor.violation"]
    for e in violations:
        args = e.get("args") or {}
        lines.append(
            f"\n!! monitor violation at history index "
            f"{args.get('detected_at_index')} "
            f"(detection latency {args.get('detection_latency_s')}s)")

    if len(lines) == 1:
        lines.append("(no trace.jsonl / metrics.json found)")
    return "\n".join(lines)


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    store_dir = argv[0] if argv else os.path.join("store", "latest")
    store_dir = os.path.realpath(store_dir)
    if not os.path.isdir(store_dir):
        print(f"not a directory: {store_dir}", file=sys.stderr)
        return 1
    print(summarize(store_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
