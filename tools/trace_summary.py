"""Summarize a test run's observability artifacts from its store dir.

Reads ``trace.jsonl`` + ``metrics.json`` (written by jepsen_tpu.store
next to results.json) and prints:

* per-lifecycle-phase wall time (the ``X`` spans with cat=lifecycle),
* op-latency quantiles (p50/p90/p99) from the interpreter's op spans,
  falling back to the metrics histogram when the trace has no op spans,
* op counts by f/type and the WGL search telemetry (states explored,
  chunk count, dedup-table load) from metrics.json,
* the streaming monitor's telemetry (ops consumed, chunk checks,
  detection latency) plus its violation instant from the trace.

Usage::

    python tools/trace_summary.py [STORE_DIR]
    python tools/trace_summary.py --campaign [CAMPAIGN_DIR_OR_ID]

STORE_DIR defaults to ``store/latest``. Accepts either a run directory
(containing trace.jsonl) or anything with those two files in it.

``--campaign`` reads a campaign directory's merged
``campaign_trace.jsonl`` (one Perfetto timeline, one process lane per
worker, clocks skew-normalized — written by the fleet dispatcher via
``jepsen_tpu.obs.merge``) plus ``metrics.json``/``report.json`` and
prints the campaign view: per-worker lanes with their clock offsets,
makespan vs summed cell wall (achieved parallelism), per-worker
utilization and exec/search/sync breakdown, device-slot wait,
fleet lease/steal/sync/chaos counters, and the critical-path cells.
The argument may be a campaign directory or a campaign id (resolved
under ``store/campaigns/``); default: the most recent campaign.
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_trace(path):
    from jepsen_tpu.obs import load_trace
    return load_trace(path)


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    # nearest-rank: smallest index covering a q fraction of the sample
    # (int(q*len) would bias high -- p50 of 2 samples must be the lower)
    i = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q * len(sorted_vals)) - 1))
    return sorted_vals[i]


def _fmt_s(us):
    return f"{us / 1e6:10.3f}s"


def summarize(store_dir):
    """Render the summary for one run directory; returns the text."""
    lines = [f"== {store_dir} =="]
    trace_path = os.path.join(store_dir, "trace.jsonl")
    metrics_path = os.path.join(store_dir, "metrics.json")

    events = []
    if os.path.exists(trace_path):
        events = _load_trace(trace_path)

    # -- per-phase wall time -------------------------------------------
    phases = [e for e in events
              if e.get("ph") == "X" and e.get("cat") == "lifecycle"]
    if phases:
        lines.append("\n-- lifecycle phases (wall time) --")
        for e in sorted(phases, key=lambda e: e["ts"]):
            lines.append(f"{_fmt_s(e.get('dur', 0.0))}  {e['name']}")

    # -- op latency quantiles ------------------------------------------
    op_durs_us = sorted(e.get("dur", 0.0) for e in events
                        if e.get("ph") == "X" and e.get("cat") == "op")
    if op_durs_us:
        lines.append(f"\n-- op latency ({len(op_durs_us)} ops, "
                     "from trace spans) --")
        for q in (0.5, 0.9, 0.99):
            v = _quantile(op_durs_us, q)
            lines.append(f"p{int(q * 100):<3} {v / 1e3:10.3f} ms")

    metrics = None
    if os.path.exists(metrics_path):
        with open(metrics_path) as f:
            metrics = json.load(f)

    def _series(section, name):
        """The first series matching ``name`` exactly or with labels
        appended (``name{...}``) — campaign/fleet runs stamp their
        obs-context as default labels into every snapshot key."""
        for k, v in sorted((section or {}).items()):
            if k == name or k.startswith(name + "{"):
                return v
        return None

    if metrics:
        if not op_durs_us:
            h = _series(metrics.get("histograms"),
                        "interpreter.op_latency_s")
            if h and h.get("count"):
                lines.append(f"\n-- op latency ({h['count']} ops, "
                             "from metrics histogram) --")
                lines.append(
                    f"mean {h['sum'] / h['count'] * 1e3:10.3f} ms   "
                    f"max {h['max'] * 1e3:10.3f} ms")
        counters = metrics.get("counters", {})
        ops = {k: v for k, v in sorted(counters.items())
               if k.startswith("interpreter.ops_completed")}
        if ops:
            lines.append("\n-- op counts --")
            for k, v in ops.items():
                lines.append(f"{v:8d}  {k}")
        wgl = {k: v for k, v in sorted(counters.items())
               if k.startswith("wgl.")}
        wgl.update({k: v for k, v in
                    sorted(metrics.get("gauges", {}).items())
                    if k.startswith("wgl.")})
        if wgl:
            lines.append("\n-- WGL search telemetry --")
            for k, v in wgl.items():
                lines.append(f"{v!s:>12}  {k}")

        # per-bucket padding waste + device duty cycle (the run's
        # whole-trace wall is the duty denominator)
        run_wall_s = None
        xs = [e for e in events if e.get("ph") == "X"]
        if xs:
            run_wall_s = (max(e.get("ts", 0.0) + e.get("dur", 0.0)
                              for e in xs)
                          - min(e.get("ts", 0.0) for e in xs)) / 1e6
        lines += _introspection_lines(metrics, run_wall_s)

    if events:
        try:
            from jepsen_tpu.obs.bubbles import fold_events
            lines += _bubble_lines(fold_events(events))
        except Exception:  # noqa: BLE001 - the summary must print
            pass

    if metrics:
        counters = metrics.get("counters", {})

        mon = {k: v for k, v in sorted(counters.items())
               if k.startswith("monitor.")}
        mon.update({k: v for k, v in
                    sorted(metrics.get("gauges", {}).items())
                    if k.startswith("monitor.")})
        mh = _series(metrics.get("histograms"), "monitor.check_s")
        if mon or mh:
            lines.append("\n-- streaming monitor --")
            for k, v in mon.items():
                lines.append(f"{v!s:>12}  {k}")
            if mh and mh.get("count"):
                lines.append(
                    f"check wall: mean "
                    f"{mh['sum'] / mh['count'] * 1e3:.1f} ms   "
                    f"max {mh['max'] * 1e3:.1f} ms over {mh['count']} "
                    "check(s)")
            lines += _stream_lines(mon)

    # the monitor's violation instant, if the run recorded one
    violations = [e for e in events
                  if e.get("ph") == "i"
                  and e.get("name") == "monitor.violation"]
    for e in violations:
        args = e.get("args") or {}
        lines.append(
            f"\n!! monitor violation at history index "
            f"{args.get('detected_at_index')} "
            f"(detection latency {args.get('detection_latency_s')}s)")

    # -- proof-carrying verdict (analysis/certify.py) -------------------
    lines += _certificate_lines(store_dir)

    if len(lines) == 1:
        lines.append("(no trace.jsonl / metrics.json found)")
    return "\n".join(lines)


def _stream_lines(mon):
    """The streamlin digest: frontier size + the per-chunk fold cost
    that MAKES the O(window) claim observable (mirrors the txn
    monitor's ``closure_rebuilds`` contract -- the claim is checked in
    counters, not asserted in wall clock). ``mon`` is the merged
    monitor.* counter/gauge map already printed above; this derives
    the per-fold averages those raw totals hide."""
    seals = mon.get("monitor.seal_folds", 0)
    probes = mon.get("monitor.probe_folds", 0)
    folds = seals + probes
    if not folds:
        return []
    out = []
    fs = mon.get("monitor.frontier_size")
    fp = mon.get("monitor.frontier_peak")
    if fs is not None or fp is not None:
        out.append(f"frontier: {fs if fs is not None else '?'} "
                   f"config(s) live (peak "
                   f"{fp if fp is not None else '?'})")
    cells = mon.get("monitor.fold_cells", 0)
    out.append(f"fold cost: {cells / folds:.1f} cells/fold over "
               f"{folds} fold(s) ({seals} seal / {probes} probe) -- "
               "flat across the stream when re-checks are O(window)")
    flats = mon.get("monitor.stream_flat_checks", 0)
    if flats:
        out.append(f"!! {flats} flat fall-back check(s) "
                   "(degraded streams re-search the prefix)")
    mism = mon.get("monitor.stream_confirm_mismatches", 0)
    if mism:
        out.append(f"!! {mism} frontier suspicion(s) NOT confirmed "
                   "offline (fingerprint collisions; verdicts "
                   "unaffected)")
    return out


def _certificate_lines(store_dir):
    """The run's certificate.json at a glance: the verdict it
    certifies, the checks that ran (witness replay, segment
    re-certification, cross-check, differential), and any VC
    findings; [] for uncertified runs."""
    try:
        with open(os.path.join(store_dir, "certificate.json")) as f:
            cert = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(cert, dict):
        return []
    lines = ["\n-- verdict certificate --"]
    counts = cert.get("counts") or {}
    verdict = "clean" if not counts.get("error") else "FAILED"
    lines.append(f"{verdict}: verdict {cert.get('verdict')!r} "
                 f"(engine {cert.get('engine')}), "
                 f"{cert.get('rows', '?')} row(s); "
                 f"{counts.get('error', 0)} error(s), "
                 f"{counts.get('info', 0)} info")
    for c in (cert.get("checks") or [])[:8]:
        detail = {k: v for k, v in c.items() if k != "name"}
        lines.append(f"  {c.get('name')}: {detail}")
    for d in (cert.get("diagnostics") or [])[:8]:
        loc = f" {d.get('location')}" if d.get("location") else ""
        lines.append(f"  {str(d.get('severity', '?')).upper()} "
                     f"{d.get('code')}{loc}: {d.get('message')}")
    return lines


def _introspection_lines(metrics_like, wall_s=None):
    """The padding-waste table + duty-cycle lines from any metrics
    snapshot/fold dict; [] when the run recorded no padding
    accounting (pre-introspection artifacts)."""
    from jepsen_tpu.obs.merge import introspection_summary
    summary = introspection_summary(metrics_like, makespan_s=wall_s)
    lines = []
    if summary.get("padding"):
        lines.append("\n-- padding waste (per n-bucket) --")
        lines.append(f"{'bucket':>8}  {'real':>10}  {'padded':>10}  "
                     "waste")
        for b, st in summary["padding"].items():
            lines.append(f"{b:>8}  {st['real']:>10}  "
                         f"{st['padded']:>10}  "
                         f"{st['waste_frac'] * 100:5.1f}%")
    busy = summary.get("device_busy_s") or {}
    chunk = summary.get("chunk_s") or {}
    if busy:
        lines.append("\n-- device duty cycle --")
        for eng, s in busy.items():
            extra = ""
            if eng in chunk and chunk[eng] > 0:
                extra = (f"   of {chunk[eng]:.3f}s chunk wall "
                         f"({s / chunk[eng] * 100:.1f}%)")
            lines.append(f"{s:10.3f}s  busy ({eng}){extra}")
        if summary.get("duty_cycle") is not None:
            lines.append(f"{summary['duty_cycle'] * 100:9.1f}%  "
                         "duty cycle (busy / wall; >100% = "
                         "overlapping searches across workers)")
        elif wall_s is None:
            lines.append("(no trace wall to compute the duty cycle "
                         "against)")
    phase_s = summary.get("phase_s") or {}
    if phase_s:
        lines.append("\n-- where the time goes (per-dispatch "
                     "phases) --")
        for eng, per in phase_s.items():
            total = sum(per.values()) or 1.0
            lines.append(f"{eng}:")
            for p, s in sorted(per.items(), key=lambda kv: -kv[1]):
                lines.append(f"{s:10.3f}s  {p:<8} "
                             f"({s / total * 100:5.1f}%)")
    return lines


def _bubble_lines(ledger):
    """The idle-bubble section from a bubble ledger dict
    (obs.bubbles); [] when the trace carried no phase spans."""
    if not ledger or not ledger.get("episodes"):
        return []
    lines = ["\n-- idle bubbles (makespan minus device-compute) --"]
    lines.append(f"{ledger['device_s']:10.3f}s  device-compute "
                 f"({ledger['lanes']} lane(s), "
                 f"{ledger['episodes']} episode(s))")
    lines.append(f"{ledger['idle_s']:10.3f}s  idle, "
                 f"{ledger['attribution_frac'] * 100:.1f}% attributed")
    idle = ledger.get("idle_s") or 0.0
    for p, s in sorted((ledger.get("phases") or {}).items(),
                       key=lambda kv: -kv[1]):
        if p == "device" or s <= 0:
            continue
        pct = f" ({s / idle * 100:5.1f}% of idle)" if idle else ""
        lines.append(f"{s:10.3f}s  {p:<8}{pct}")
    if ledger.get("residual_s"):
        lines.append(f"{ledger['residual_s']:10.3f}s  (unattributed "
                     "residual)")
    if ledger.get("inter_episode_s"):
        lines.append(f"{ledger['inter_episode_s']:10.3f}s  between "
                     "episodes (outside the dispatch pipeline)")
    return lines


def _store_rooted_at(campaign_dir):
    """Context manager: point jepsen_tpu.store at the store that owns
    ``campaign_dir`` (…/store/campaigns/<id> → …/store) and restore
    it — the one place both in-process fallbacks (the fleetlint audit
    and the metrics fold) mutate the module global."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        from jepsen_tpu import store
        old = store.base_dir
        store.base_dir = os.path.dirname(
            os.path.dirname(campaign_dir))
        try:
            yield os.path.basename(campaign_dir)
        finally:
            store.base_dir = old

    return scope()


def _resolve_campaign_dir(arg):
    """A campaign directory from a path, a campaign id, or (None) the
    most recent campaign under store/campaigns/."""
    if arg and os.path.isdir(arg):
        return os.path.realpath(arg)
    base = os.path.join("store", "campaigns")
    if arg:
        p = os.path.join(base, arg)
        return os.path.realpath(p) if os.path.isdir(p) else None
    if not os.path.isdir(base):
        return None
    cands = sorted(e for e in os.listdir(base)
                   if os.path.isdir(os.path.join(base, e)))
    return os.path.realpath(os.path.join(base, cands[-1])) \
        if cands else None


def _span_sum(events, pred):
    return sum(e.get("dur", 0.0) for e in events
               if e.get("ph") == "X" and pred(e))


def summarize_campaign(campaign_dir):
    """Render the campaign view of a merged trace; returns the text."""
    lines = [f"== campaign {campaign_dir} =="]
    trace_path = os.path.join(campaign_dir, "campaign_trace.jsonl")
    events = []
    if not os.path.exists(trace_path):
        # keep going: the report-based sections (capacity oracle,
        # metrics fold, fleetlint audit) don't need the merged trace
        lines.append("(no campaign_trace.jsonl — run the fleet with "
                     "trace merge enabled, or merge with "
                     "jepsen_tpu.obs.merge.merge_campaign)")
    else:
        events = _load_trace(trace_path)

    report = {}
    try:
        with open(os.path.join(campaign_dir, "report.json")) as f:
            report = json.load(f)
    except (OSError, ValueError):
        pass
    metrics = {}
    try:
        with open(os.path.join(campaign_dir, "metrics.json")) as f:
            metrics = json.load(f)
    except (OSError, ValueError):
        pass

    # -- lanes ----------------------------------------------------------
    makespan_s = None
    lanes = {int(e["pid"]): (e.get("args") or {}).get("name", "?")
             for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    winfo = (report.get("trace") or {}).get("workers") or {}
    if events:
        lines.append(f"\n-- lanes ({len(events)} events) --")
    for pid in sorted(lanes):
        name = lanes[pid]
        extra = ""
        w = name[len("worker "):] if name.startswith("worker ") else None
        if w in winfo:
            extra = (f"   cells {winfo[w].get('cells')}, clock offset "
                     f"{winfo[w].get('offset_s', 0.0):+.6f}s")
        lines.append(f"lane {pid}: {name}{extra}")

    # -- makespan vs summed cell wall -----------------------------------
    xs = [e for e in events if e.get("ph") == "X"]
    if xs:
        t_lo = min(e.get("ts", 0.0) for e in xs)
        t_hi = max(e.get("ts", 0.0) + e.get("dur", 0.0) for e in xs)
        makespan_us = t_hi - t_lo
        makespan_s = makespan_us / 1e6
        # the coordinator's fleet.cell spans cover lease exec end to
        # end; runs merged from worker lanes carry jepsen.run
        cell_spans = [e for e in xs if e.get("name") == "fleet.cell"] \
            or [e for e in xs if e.get("name") == "jepsen.run"]
        cell_sum_us = sum(e.get("dur", 0.0) for e in cell_spans)
        lines.append("\n-- makespan --")
        lines.append(f"{_fmt_s(makespan_us)}  campaign makespan")
        lines.append(f"{_fmt_s(cell_sum_us)}  summed cell wall "
                     f"({len(cell_spans)} cells)")
        if makespan_us > 0 and cell_sum_us > 0:
            lines.append(f"{cell_sum_us / makespan_us:10.2f}x "
                         " achieved parallelism")

        # -- per-worker utilization + breakdown -------------------------
        lines.append("\n-- per-worker (exec / search / sync) --")
        for pid in sorted(lanes):
            name = lanes[pid]
            lane_evs = [e for e in xs if e.get("pid") == pid]
            if name == "coordinator":
                # the coordinator's view of each worker, keyed by the
                # span's worker arg: exec occupancy + sync wall
                by_worker = {}
                for e in lane_evs:
                    w = (e.get("args") or {}).get("worker")
                    if w is None:
                        continue
                    st = by_worker.setdefault(str(w),
                                              {"exec": 0.0, "sync": 0.0})
                    if e.get("name") == "fleet.cell":
                        st["exec"] += e.get("dur", 0.0)
                    elif e.get("name") == "fleet.artifact_sync":
                        st["sync"] += e.get("dur", 0.0)
                for w, st in sorted(by_worker.items()):
                    busy = st["exec"] / makespan_us * 100 \
                        if makespan_us else 0.0
                    lines.append(
                        f"{w:>16}  exec {st['exec'] / 1e6:8.3f}s "
                        f"({busy:5.1f}% of makespan)   sync "
                        f"{st['sync'] / 1e6:8.3f}s")
            else:
                run_us = _span_sum(lane_evs,
                                   lambda e: e.get("name") == "jepsen.run")
                search_us = _span_sum(lane_evs,
                                      lambda e: e.get("name") == "analyze")
                if run_us or search_us:
                    lines.append(
                        f"{name:>16}  run {run_us / 1e6:8.3f}s   "
                        f"search/analyze {search_us / 1e6:8.3f}s")

        # -- critical path: the longest cells ---------------------------
        longest = sorted(cell_spans, key=lambda e: -e.get("dur", 0.0))
        if longest:
            lines.append("\n-- critical path (longest cells) --")
            for e in longest[:5]:
                args = e.get("args") or {}
                lines.append(
                    f"{_fmt_s(e.get('dur', 0.0))}  "
                    f"{args.get('cell', e.get('name'))} "
                    f"(worker {args.get('worker', '?')})")

    # -- device-slot wait -----------------------------------------------
    dw = (metrics.get("histograms") or {}).get("campaign.device_wait_s")
    if dw and dw.get("count"):
        lines.append("\n-- device-slot wait --")
        lines.append(f"mean {dw['sum'] / dw['count'] * 1e3:10.3f} ms   "
                     f"max {dw['max'] * 1e3:10.3f} ms over "
                     f"{dw['count']} check(s)")

    # -- fleet counters (leases, steals, syncs, chaos) ------------------
    counters = metrics.get("counters") or {}
    fleet = {k: v for k, v in sorted(counters.items())
             if k.startswith(("fleet.", "chaos."))}
    if fleet:
        lines.append("\n-- fleet counters --")
        for k, v in fleet.items():
            lines.append(f"{v!s:>12}  {k}")

    # -- device introspection: per-bucket padding waste + duty cycle ----
    # (metrics_fold.json is the per-cell fold run_fleet writes at
    # finalize; fold in process when it is missing — read-only)
    fold = None
    try:
        with open(os.path.join(campaign_dir,
                               "metrics_fold.json")) as f:
            fold = json.load(f)
    except (OSError, ValueError):
        pass
    if fold is None:
        try:
            from jepsen_tpu.obs.merge import fold_campaign_metrics
            with _store_rooted_at(campaign_dir) as cid:
                fold = fold_campaign_metrics(cid, persist=False)
        except Exception:  # noqa: BLE001 - the summary must print
            fold = None
    if fold is not None:
        lines += _introspection_lines(fold, makespan_s)

    # -- idle-bubble ledger: where the non-device time went -------------
    # (bubble_ledger.json is the fold run_fleet writes at finalize;
    # fold the merged trace in process when it is missing)
    ledger = None
    try:
        with open(os.path.join(campaign_dir,
                               "bubble_ledger.json")) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        pass
    if ledger is None and events:
        try:
            from jepsen_tpu.obs.bubbles import fold_events
            ledger = fold_events(events)
        except Exception:  # noqa: BLE001 - the summary must print
            ledger = None
    if ledger is not None:
        lines += _bubble_lines(ledger)

    # -- capacity plan: predicted vs actual compile shapes --------------
    lines += _capacity_lines(campaign_dir, report)

    # -- control-plane audit (analysis.fleetlint) -----------------------
    fa = _fleet_audit(campaign_dir)
    if fa is None:
        lines.append("\n-- fleetlint audit --\n(no fleet_analysis."
                     "json and the audit could not run)")
    else:
        c = fa.get("counts") or {}
        checks = fa.get("checks") or {}
        lines.append("\n-- fleetlint audit --")
        verdict = "clean" if not c.get("error") else "FAILED"
        lines.append(
            f"{verdict}: {c.get('error', 0)} error(s), "
            f"{c.get('warning', 0)} warning(s), {c.get('info', 0)} "
            f"info over {checks.get('records', '?')} journal "
            f"records / {checks.get('runs_audited', '?')} run "
            "traces")
        for d in (fa.get("diagnostics") or [])[:8]:
            loc = f" {d.get('location')}" if d.get("location") else ""
            lines.append(f"  {str(d.get('severity', '?')).upper()} "
                         f"{d.get('code')}{loc}: {d.get('message')}")

    # -- sampled verdict certification (analysis/certify.py) ------------
    certn = (report or {}).get("certification")
    if certn:
        c = certn.get("counts") or {}
        verdict = "clean" if not c.get("error") else "FAILED"
        lines.append("\n-- verdict certification (sampled) --")
        lines.append(
            f"{verdict}: {certn.get('sampled', 0)}/{certn.get('of', 0)}"
            f" run(s) re-certified; {c.get('error', 0)} error(s), "
            f"{c.get('info', 0)} info"
            + (f"; codes {certn.get('codes')}" if certn.get("codes")
               else ""))
        for r in (certn.get("runs") or [])[:8]:
            rc = r.get("counts") or {}
            state = "ok" if not rc.get("error") else \
                f"FAILED {r.get('codes')}"
            lines.append(f"  {r.get('path')}: {state}")

    return "\n".join(lines)


def _capacity_lines(campaign_dir, report):
    """The capacity planner's predicted-vs-actual bucket error for a
    planned campaign (report.json["capacity"], the capplan prediction
    oracle); [] when the campaign was never planned."""
    cap = (report or {}).get("capacity")
    if not cap and os.path.exists(os.path.join(campaign_dir,
                                               "capacity_plan.json")):
        cap = {"oracle": None}
    if not cap:
        return []
    lines = ["\n-- capacity plan (predicted vs actual) --"]
    oracle = cap.get("oracle")
    if not oracle:
        lines.append("(capacity_plan.json present but no oracle in "
                     "report.json -- campaign not finalized?)")
        return lines
    pred = {tuple(k) for k in oracle.get("predicted") or []}
    act = {tuple(k) for k in oracle.get("actual") or []}
    lines.append(f"{'model':<20} {'bucket':>7}  predicted  actual")
    for m, b in sorted(pred | act):
        lines.append(f"{m:<20} {b:>7}  "
                     f"{'yes' if (m, b) in pred else 'no':>9}  "
                     f"{'yes' if (m, b) in act else 'no'}")
    lines.append(f"prediction error: {oracle.get('error_frac')} "
                 f"({len(oracle.get('missed') or [])} missed, "
                 f"{len(oracle.get('unplanned') or [])} unplanned)")
    rec = cap.get("recommendation")
    if rec:
        lines.append(f"recommendation: set_n_floor("
                     f"{rec['set_n_floor']}) -> "
                     f"{rec['distinct_after']} shape(s) "
                     f"(from {rec['distinct_before']})")
    return lines


def _fleet_audit(campaign_dir):
    """The campaign's fleetlint report: the persisted
    fleet_analysis.json when present, else a fresh in-process audit
    (read-only -- nothing is written), else None."""
    p = os.path.join(campaign_dir, "fleet_analysis.json")
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        pass
    try:
        from jepsen_tpu.analysis import fleetlint
        with _store_rooted_at(campaign_dir) as cid:
            report, _diags = fleetlint.audit(cid, persist=False)
        return report
    except Exception:  # noqa: BLE001 - the summary must still print
        return None


def main(argv=None):
    argv = list(argv if argv is not None else sys.argv[1:])
    if argv and argv[0] == "--campaign":
        cdir = _resolve_campaign_dir(argv[1] if len(argv) > 1 else None)
        if cdir is None:
            print("no campaign directory found", file=sys.stderr)
            return 1
        print(summarize_campaign(cdir))
        return 0
    store_dir = argv[0] if argv else os.path.join("store", "latest")
    store_dir = os.path.realpath(store_dir)
    if not os.path.isdir(store_dir):
        print(f"not a directory: {store_dir}", file=sys.stderr)
        return 1
    print(summarize(store_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
