"""Capture and summarize a jax.profiler trace of the WGL search kernel.

Reproduces the numbers in PROFILE.md: runs a rung-2-style multi-key
batch, the rung-5 single key (--rung 5), or the rung-0 maxlen shape
(--rung 0: large n, high point-concurrency -- the primary-metric
workload) under ``jax.profiler.trace``, then parses the TensorBoard
trace JSON into a per-op device-time table with HLO source attribution
(the trace events carry ``source`` args pointing at jax_wgl.py lines,
which is how the round-3 and round-4 bottlenecks were found).

Usage::

    python tools/profile_kernel.py [--rung 0|2|5] [--keys 256] [--out DIR]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(out_dir, rung, keys):
    import jax

    from jepsen_tpu.checker import jax_wgl
    from jepsen_tpu.models import cas_register_spec
    from jepsen_tpu.parallel import check_batch_encoded
    from jepsen_tpu.simulate import corrupt, random_history

    spec = cas_register_spec
    rng = random.Random(45100)
    if rung == 2:
        hists = []
        for k in range(keys):
            h = random_history(rng, "cas-register", n_procs=8, n_ops=200,
                               crash_p=0.02)
            hists.append(corrupt(rng, h) if k % 8 == 7 else h)
        pairs = [spec.encode(h) for h in hists]
        check_batch_encoded(spec, pairs)          # compile warmup
        with jax.profiler.trace(out_dir):
            check_batch_encoded(spec, pairs)
    elif rung == 5:
        hist = random_history(rng, "cas-register", n_procs=64,
                              n_ops=10_000, crash_p=0.05)
        e, st = spec.encode(hist)
        jax_wgl.check_encoded(spec, e, st)        # compile warmup
        with jax.profiler.trace(out_dir):
            jax_wgl.check_encoded(spec, e, st)
    else:
        # rung 0: the maxlen primary-metric shape (large n, high C)
        hist = random_history(random.Random(77000 + 80000),
                              "cas-register", n_procs=64, n_ops=80_000,
                              crash_p=0.05)
        e, st = spec.encode(hist)
        jax_wgl.check_encoded(spec, e, st, max_configs=1)   # warmup
        with jax.profiler.trace(out_dir):
            jax_wgl.check_encoded(spec, e, st, timeout_s=120,
                                  chunk_iters=32)


def summarize(out_dir, top=15):
    paths = sorted(glob.glob(
        os.path.join(out_dir, "plugins/profile/*/*.trace.json.gz")))
    if not paths:
        raise SystemExit(f"no trace under {out_dir}")
    with gzip.open(paths[-1]) as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    pids = {ev["pid"]: ev["args"].get("name", "")
            for ev in events
            if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    dev = {p for p, name in pids.items() if "TPU" in name or "GPU" in name}
    tot, cnt, src = (collections.Counter(), collections.Counter(), {})
    span = [None, None]
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in dev:
            continue
        name, dur = ev["name"], ev.get("dur", 0)
        tot[name] += dur
        cnt[name] += 1
        if name not in src and ev.get("args", {}).get("source"):
            src[name] = ev["args"]["source"]
        ts = ev["ts"]
        span[0] = ts if span[0] is None else min(span[0], ts)
        span[1] = ts + dur if span[1] is None else max(span[1], ts + dur)
    # top-level jit spans nest everything; report leaves only
    leaves = {n: d for n, d in tot.items()
              if not n.startswith(("jit_", "while."))}
    wall = (span[1] - span[0]) / 1e6 if span[0] is not None else 0.0
    print(f"trace: {paths[-1]}")
    print(f"device span: {wall:.3f}s; leaf-op busy: "
          f"{sum(leaves.values()) / 1e6:.3f}s")
    print(f"{'total_s':>9} {'calls':>7}  {'op':<22} source")
    for name, d in sorted(leaves.items(), key=lambda kv: -kv[1])[:top]:
        print(f"{d / 1e6:9.3f} {cnt[name]:7d}  {name:<22} "
              f"{src.get(name, '')}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rung", type=int, default=2, choices=(0, 2, 5))
    ap.add_argument("--keys", type=int, default=256)
    ap.add_argument("--out", default="/tmp/jepsen_tpu_profile")
    ap.add_argument("--parse-only", action="store_true")
    args = ap.parse_args()
    if not args.parse_only:
        capture(args.out, args.rung, args.keys)
    summarize(args.out)


if __name__ == "__main__":
    main()
