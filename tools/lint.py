#!/usr/bin/env python
"""Framework lint driver: codelint (the AST thread-safety pass) plus
ruff (style/correctness), one exit code.

Usage::

    python tools/lint.py                 # lint jepsen_tpu/ tools/ tests/
    python tools/lint.py path [path...]  # lint specific files/dirs
    python tools/lint.py --json          # machine-readable diagnostics
    python tools/lint.py --no-ruff       # codelint only
    python tools/lint.py --campaign [ID] # fleetlint a stored campaign
    python tools/lint.py --matrix FILE  # capplan a campaign matrix
    python tools/lint.py --certify [RUN] # re-certify a stored run

Exit codes: 0 clean (warnings allowed), 1 error-severity codelint
diagnostics or ruff violations, 2 internal error. ruff is optional at
runtime (the container may not ship it); when absent it is skipped
with a notice -- CI installs it, so the workflow gets both passes.

``--campaign`` switches the driver into the control-plane audit mode:
instead of linting source, it replays a stored campaign's artifacts
(``store/campaigns/<ID>/``; default: the most recent campaign)
through ``analysis.fleetlint``, persists ``fleet_analysis.json``, and
exits 1 on FL error diagnostics -- the CI chaos-soak oracle.

``--matrix FILE`` dry-runs the capacity planner (analysis.capplan)
over a campaign matrix JSON (``{"base": {...}, "axes": {...}}``):
prints the capacity table -- per-cell compile shapes, HBM footprints,
int32-wall proximity -- plus the CP001-CP008 diagnostics, and exits 1
on CP errors. ``--device-mem-budget BYTES`` enables the HBM half.
Nothing runs, nothing is written.

``--certify [RUN]`` re-certifies a stored run directory (default:
``store/latest``) purely from its persisted artifacts: the
certificate.json witness is replayed through the pure CPU model
against the re-encoded history.jsonl and cross-checked against
results.json (analysis.certify, VC001-VC012). Exits 1 on VC errors
-- a tampered witness, a flipped verdict, or a certificate that
disagrees with the results it rode along with. 2 = no such run.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jepsen_tpu import analysis  # noqa: E402
from jepsen_tpu.analysis import codelint  # noqa: E402

DEFAULT_PATHS = ("jepsen_tpu", "tools", "tests")


def run_codelint(paths, package_root):
    return analysis.run_analyzer(
        "codelint", codelint.lint_paths, paths,
        package_root=package_root)


def ruff_argv():
    """A usable ruff invocation, or None when ruff is unavailable."""
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    try:
        import ruff  # noqa: F401
    except ImportError:
        return None
    return [sys.executable, "-m", "ruff"]


def run_ruff(paths):
    """Run ruff check; returns (exit_code, output) or (None, reason)."""
    argv = ruff_argv()
    if argv is None:
        return None, "ruff not installed; skipping style pass"
    proc = subprocess.run(argv + ["check", *paths], cwd=REPO,
                          capture_output=True, text=True)
    return proc.returncode, (proc.stdout + proc.stderr).strip()


def run_campaign_audit(campaign_id, as_json=False):
    """fleetlint a stored campaign; returns the exit code (0 clean /
    warnings, 1 FL errors, 2 unknown campaign)."""
    from jepsen_tpu import store
    from jepsen_tpu.analysis import fleetlint
    cid = campaign_id
    if cid in (None, "", "latest"):
        cid = store.latest_campaign()
        if cid is None:
            print("no campaign found in the store", file=sys.stderr)
            return 2
    try:
        report, diags = fleetlint.audit(cid)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(analysis.render_text(
            diags, title=f"fleetlint audit: {cid}"))
        print(f"report: {report.get('path')}")
    return 1 if analysis.errors(diags) else 0


def run_certify(run, budget=None, as_json=False):
    """Re-certify a stored run directory from its persisted artifacts;
    returns the exit code (0 clean / info, 1 VC errors, 2 no run)."""
    from jepsen_tpu import store
    from jepsen_tpu.analysis import certify
    path = run
    if path in (None, "", "latest"):
        path = os.path.join(store.base_dir, "latest")
    path = os.path.realpath(path)
    if not os.path.isdir(path):
        print(f"no run directory at {path!r}", file=sys.stderr)
        return 2
    summary, diags = certify.certify_run(path, budget=budget)
    if summary is None and not diags:
        print(f"{path}: no results.json to certify against",
              file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(summary if summary is not None
                         else analysis.to_json(diags),
                         indent=1, sort_keys=True))
    else:
        print(analysis.render_text(diags, title=f"certify: {path}"))
        if summary is not None and summary.get("certified"):
            print(f"verdict {summary.get('verdict')!r} "
                  f"(engine {summary.get('engine')}), "
                  f"{len(summary.get('checks') or [])} check(s)")
        elif summary is not None:
            print("no certificate.json: nothing replayed")
    return 1 if analysis.errors(diags) else 0


def run_matrix_plan(path, device_mem_budget=None, as_json=False):
    """capplan a campaign matrix file; returns the exit code (0 clean
    / warnings, 1 CP errors, 2 unreadable matrix)."""
    from jepsen_tpu.analysis import capplan
    try:
        with open(path) as f:
            matrix = json.load(f)
    except (OSError, ValueError) as e:
        print(f"couldn't read matrix {path!r}: {e}", file=sys.stderr)
        return 2
    if not isinstance(matrix, dict):
        print(f"matrix {path!r} is not a JSON object", file=sys.stderr)
        return 2
    plan, diags = capplan.build_plan(
        matrix, device_mem_budget=device_mem_budget)
    if as_json:
        print(json.dumps(plan, indent=1, sort_keys=True))
    else:
        print(capplan.render_table(plan))
        print(analysis.render_text(diags,
                                   title=f"capacity plan: {path}"))
    return 1 if analysis.errors(diags) else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true",
                    help="emit diagnostics as JSON")
    ap.add_argument("--no-ruff", action="store_true",
                    help="skip the ruff style pass")
    ap.add_argument("--package-root", default=None,
                    help="package dir for thread-reachability ranking "
                         "(default: jepsen_tpu when linted)")
    ap.add_argument("--campaign", nargs="?", const="latest",
                    default=None, metavar="ID",
                    help="audit a stored campaign's control-plane "
                         "artifacts with fleetlint instead of linting "
                         "source (default ID: the latest campaign); "
                         "exit 1 on FL errors")
    ap.add_argument("--certify", nargs="?", const="latest",
                    default=None, metavar="RUN",
                    help="re-certify a stored run directory's verdict "
                         "from its certificate.json + history.jsonl "
                         "(default RUN: store/latest); exit 1 on VC "
                         "errors")
    ap.add_argument("--budget", default=None, type=int,
                    help="cross-check config budget for --certify "
                         "(default: the certificate's recorded "
                         "budget)")
    ap.add_argument("--matrix", default=None, metavar="FILE",
                    help="dry-run the capacity planner (capplan) over "
                         "a campaign matrix JSON: print the capacity "
                         "table + CP diagnostics; exit 1 on CP errors")
    ap.add_argument("--device-mem-budget", default=None,
                    metavar="BYTES",
                    help="usable device HBM in bytes for --matrix "
                         "(K/M/G/T suffixes accepted)")
    opts = ap.parse_args(argv)

    if opts.campaign is not None:
        return run_campaign_audit(opts.campaign, as_json=opts.json)
    if opts.certify is not None:
        return run_certify(opts.certify, budget=opts.budget,
                           as_json=opts.json)
    if opts.matrix is not None:
        budget = None
        if opts.device_mem_budget is not None:
            from jepsen_tpu.cli import parse_bytes
            budget = parse_bytes(opts.device_mem_budget)
        return run_matrix_plan(opts.matrix, device_mem_budget=budget,
                               as_json=opts.json)

    paths = list(opts.paths) or [os.path.join(REPO, p)
                                 for p in DEFAULT_PATHS
                                 if os.path.isdir(os.path.join(REPO, p))]
    package_root = opts.package_root
    if package_root is None:
        for p in paths:
            if os.path.basename(os.path.normpath(p)) == "jepsen_tpu":
                package_root = p
                break

    diags = run_codelint(paths, package_root)
    failed = bool(analysis.errors(diags))

    ruff_code, ruff_out = (None, "skipped (--no-ruff)") if opts.no_ruff \
        else run_ruff(paths)
    if ruff_code not in (None, 0):
        failed = True

    if opts.json:
        report = analysis.to_json(diags)
        report["ruff"] = {"exit_code": ruff_code, "output": ruff_out}
        report["failed"] = failed
        print(json.dumps(report, indent=1))
    else:
        print(analysis.render_text(diags, title="codelint:"))
        print(f"ruff: {ruff_out or 'clean'}"
              if ruff_code in (None, 0)
              else f"ruff FAILED (exit {ruff_code}):\n{ruff_out}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
