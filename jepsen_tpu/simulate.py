"""Simulated concurrent histories for tests and benchmarks.

Runs a randomized concurrent schedule against a real sequential object
(register/cas-register/mutex/fifo-queue), recording invoke/ok/fail events,
with a tunable probability of lost completions (info ops). The histories are
linearizable by construction unless ``corrupt`` flips a read; this is the
same role the reference's simulated-time generator harness plays for its
tests (jepsen/src/jepsen/generator/test.clj) and what BASELINE.json's config
ladder is measured on.
"""

from __future__ import annotations

import random

from . import history as h


def random_history(rng: random.Random, spec_name: str, n_procs: int,
                   n_ops: int, crash_p: float = 0.1):
    """Generate an (indexed) event history for ``spec_name``."""
    hist = []
    if spec_name in ("register", "cas-register"):
        state = {"v": None}

        def gen_invoke(p):
            f = rng.choice(["read", "write", "cas"]
                           if spec_name == "cas-register"
                           else ["read", "write"])
            if f == "read":
                return h.invoke_op(p, "read", None)
            if f == "write":
                return h.invoke_op(p, "write", rng.randrange(4))
            return h.invoke_op(p, "cas", (rng.randrange(4), rng.randrange(4)))

        def apply(inv):
            f, v = inv["f"], inv["value"]
            if f == "read":
                return True, state["v"]
            if f == "write":
                state["v"] = v
                return True, v
            old, new = v
            if state["v"] == old:
                state["v"] = new
                return True, v
            return False, v
    elif spec_name == "mutex":
        state = {"locked": False}

        def gen_invoke(p):
            return h.invoke_op(p, rng.choice(["acquire", "release"]), None)

        def apply(inv):
            if inv["f"] == "acquire":
                if state["locked"]:
                    return False, None
                state["locked"] = True
                return True, None
            if not state["locked"]:
                return False, None
            state["locked"] = False
            return True, None
    elif spec_name in ("fifo-queue", "unordered-queue"):
        state = {"q": [], "next": 0}

        def gen_invoke(p):
            if rng.random() < 0.5:
                state["next"] += 1
                return h.invoke_op(p, "enqueue", state["next"])
            return h.invoke_op(p, "dequeue", None)

        def apply(inv):
            if inv["f"] == "enqueue":
                state["q"].append(inv["value"])
                return True, inv["value"]
            if state["q"]:
                i = (0 if spec_name == "fifo-queue"
                     else rng.randrange(len(state["q"])))
                return True, state["q"].pop(i)
            return False, None
    else:
        raise ValueError(f"unknown spec {spec_name!r}")

    outstanding = {}
    ops_done = 0
    while ops_done < n_ops or outstanding:
        free = [p for p in range(n_procs) if p not in outstanding]
        if free and ops_done < n_ops and (not outstanding
                                          or rng.random() < .6):
            p = rng.choice(free)
            inv = gen_invoke(p)
            outstanding[p] = inv
            hist.append(inv)
            ops_done += 1
        else:
            p = rng.choice(list(outstanding))
            inv = outstanding.pop(p)
            took_effect, res = apply(inv)
            if rng.random() < crash_p:
                hist.append(h.info_op(p, inv["f"], inv["value"]))
            elif took_effect:
                v = res if inv["f"] in ("read", "dequeue") else inv["value"]
                hist.append(h.ok_op(p, inv["f"], v))
            else:
                hist.append(h.fail_op(p, inv["f"], inv["value"]))
    return h.index(hist)


def corrupt(rng: random.Random, hist):
    """Flip one read/dequeue completion value to (probably) break
    linearizability."""
    hist = [h.Op(o) for o in hist]
    cands = [i for i, o in enumerate(hist)
             if o["type"] == "ok" and o["f"] in ("read", "dequeue")
             and o.get("value") is not None]
    if not cands:
        return hist
    i = rng.choice(cands)
    hist[i]["value"] = (hist[i]["value"] or 0) + rng.randrange(1, 5)
    return hist
