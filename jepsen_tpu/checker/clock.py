"""Clock-skew analysis: plots the ``clock_offsets`` maps the clock
nemesis embeds in its completions (reference
jepsen/src/jepsen/checker/clock.clj, 75 LoC)."""

from __future__ import annotations

from .core import Checker
from .perf import _out_path, shade_nemeses


def history_datasets(history) -> dict:
    """node -> [(t_seconds, offset_seconds), ...] from ops carrying
    clock_offsets (clock.clj:13-34); each series is extended to the end
    of the history so step plots don't cut off."""
    final_time = (history[-1].get("time", 0) / 1e9) if history else 0
    series: dict = {}
    for op in history:
        offsets = op.get("clock_offsets")
        if not offsets:
            continue
        t = op.get("time", 0) / 1e9
        for node, offset in offsets.items():
            series.setdefault(node, []).append((t, offset))
    for node, points in series.items():
        points.append((final_time, points[-1][1]))
    return series


def short_node_names(nodes) -> list:
    """Shorten node names by stripping common trailing domain components
    (clock.clj:37-45)."""
    parts = [str(n).split(".")[::-1] for n in nodes]
    if len(parts) > 1:
        depth = 0
        while all(len(p) > depth + 1 for p in parts) and \
                len({p[depth] for p in parts}) == 1:
            depth += 1
        parts = [p[depth:] for p in parts]
    return [".".join(p[::-1]) for p in parts]


def plot(test, history, opts=None):
    """Render clock-skew.png; returns the path or None without data
    (clock.clj:47-73)."""
    opts = opts or {}
    datasets = history_datasets(history)
    if not datasets:
        return None
    path = _out_path(test, opts, "clock-skew.png")
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, ax = plt.subplots(figsize=(9, 4))
    try:
        ax.set_title(f"{test.get('name')} clock skew")
        ax.set_xlabel("Time (s)")
        ax.set_ylabel("Skew (s)")
        nodes = sorted(datasets)
        for node, name in zip(nodes, short_node_names(nodes)):
            pts = datasets[node]
            ax.step([t for t, _ in pts], [o for _, o in pts],
                    where="post", label=name)
        shade_nemeses(ax, history,
                      opts.get("nemeses") or (test.get("plot") or {})
                      .get("nemeses"))
        ax.legend(loc="upper left", bbox_to_anchor=(1.01, 1), fontsize=7)
        fig.tight_layout()
        fig.savefig(path, dpi=100)
    finally:
        plt.close(fig)
    return path


class _ClockPlot(Checker):
    """Always valid; exists for its plot side effect
    (checker.clj:831-837)."""

    def check(self, test, history, opts=None):
        try:
            plot(test, history, opts)
        except Exception:  # noqa: BLE001 - plotting must not affect the
            import logging  # verdict (the checker's contract is valid)
            logging.getLogger(__name__).warning(
                "couldn't render clock-skew.png", exc_info=True)
        return {"valid": True, "valid?": True}


def clock_plot():
    return _ClockPlot()
