"""Just-in-time linearization (knossos.linear's algorithm,
reconstructed from Lowe's "Testing for linearizability" description;
the reference dispatches to it via checker.clj:199-202).

Where WGL searches depth-first over whole linearization orders, JIT
linearization sweeps the history's *events* in time order, maintaining
the set of all configurations (linearized-bitset, model state)
consistent with the prefix seen so far:

* at an invocation, nothing changes (the op merely becomes available);
* at a return of op i, every configuration must catch up: it may first
  linearize any sequence of currently-open ops, but must end up with i
  linearized — configurations that can't are discarded; if the set
  empties, the history is not linearizable, with the return event as
  the witness;
* info ops never return, so they are never forced; at the end the
  history is linearizable iff any configuration survived (every ok op
  was forced by its own return event).

The config-set stays small on low-contention histories (each return
usually extends every config by a handful of ops), which is exactly
when this algorithm beats WGL — and why the reference's competition
races both: on long, low-concurrency, crash-free histories this engine
decides in seconds where the device search pays per-iteration W*n work
(BENCH rung 6 races them and linear wins on its home turf). Crashed
(info) ops are its weakness — they stay open forever, so every return's
closure explores subsets of all open infos; the budgets below turn
that blowup into "unknown" quickly instead of burning CPU.

Two bounds, both knossos-spirited (memory AND time), either overflow
returning unknown rather than ever mis-deciding:

* ``max_configs`` bounds the per-event configuration SET (memory);
* ``max_steps`` bounds TOTAL model steps across the sweep (round 3
  bounded only per-event sets, so a history with many open infos could
  grind for minutes inside one event while "budgeted" — advisor-class
  bug found while benchmarking: 13.4M steps on a nominally 200k-config
  run).
"""

from __future__ import annotations

import numpy as np

from ..history import INF_TIME


def check_encoded(spec, e, init_state, max_configs=100_000,
                  max_steps=5_000_000, cancel=None):
    """JIT-linearization over an EncodedHistory. Returns
    {"valid": True|False|"unknown", "configs_explored", "engine",
    "op"/... witness fields on failure}."""
    n = len(e)
    if n == 0 or e.n_ok == 0:
        return {"valid": True, "configs_explored": 0, "engine": "linear"}

    invoke = e.invoke_idx
    ret_t = e.return_idx
    step = spec.step
    f, args, rets = e.f, e.args, e.ret

    # events in time order: (t, kind, op); returns processed at their
    # time; invokes only open the op
    events = sorted(
        [(int(invoke[i]), 0, i) for i in range(n)]
        + [(int(ret_t[i]), 1, i) for i in range(n)
           if ret_t[i] < INF_TIME])

    init = np.asarray(init_state, np.int32)
    # config: (bitset int, state bytes); states interned to arrays
    states = {init.tobytes(): init}
    configs = {(0, init.tobytes())}
    open_ops: list[int] = []
    explored = 0

    overflow = "max-configs-exceeded"

    def expand_until(target, configs):
        """Closure: linearize sequences of open ops until `target` is
        linearized in every surviving config; returns the set of
        configs with target linearized (deduped), or None on
        overflow (``overflow`` names which budget tripped)."""
        nonlocal explored, overflow
        done = set()
        frontier = set()
        seen = set(configs)
        for c in configs:
            (done if (c[0] >> target) & 1 else frontier).add(c)
        while frontier:
            nxt = set()
            for lin, skey in frontier:
                st = states[skey]
                for j in open_ops:
                    if (lin >> j) & 1:
                        continue
                    st2, ok = step(st, f[j], args[j], rets[j], np)
                    explored += 1
                    if explored > max_steps:
                        overflow = "max-steps-exceeded"
                        return None
                    if not ok:
                        continue
                    st2 = np.asarray(st2, np.int32)
                    key2 = st2.tobytes()
                    if key2 not in states:
                        states[key2] = st2
                    c2 = (lin | (1 << j), key2)
                    if c2 in seen:
                        continue
                    seen.add(c2)
                    if (c2[0] >> target) & 1:
                        done.add(c2)
                    else:
                        nxt.add(c2)
                    if len(seen) > max_configs:
                        overflow = "max-configs-exceeded"
                        return None
            frontier = nxt
        return done

    for t, kind, i in events:
        if kind == 0:
            open_ops.append(i)
            continue
        if cancel is not None and cancel.is_set():
            return {"valid": "unknown", "error": "cancelled",
                    "configs_explored": explored, "engine": "linear"}
        # return of op i: every config must have i linearized by now
        got = expand_until(i, configs)
        if got is None:
            return {"valid": "unknown", "error": overflow,
                    "configs_explored": explored, "engine": "linear"}
        open_ops.remove(i)
        if not got:
            result = {"valid": False, "configs_explored": explored,
                      "engine": "linear"}
            # knossos-parity witness fields from the deepest surviving
            # prefix, shaped like the other engines' (checker/witness.py
            # -- competition callers must get the same artifact set no
            # matter which engine wins the race)
            if configs:
                from . import witness
                lin, skey = max(configs,
                                key=lambda c: bin(c[0]).count("1"))
                linearized = np.asarray(
                    [(lin >> k) & 1 == 1 for k in range(n)], bool)
                witness.attach(result, spec, e, linearized,
                               states[skey], init)
            if "op" not in result and e.ops is not None:
                inv, comp = e.ops[i]
                result["op"] = dict(comp if comp is not None else inv)
            return result
        configs = got
    return {"valid": True, "configs_explored": explored,
            "engine": "linear"}


def check_history(spec, history, **kw):
    e, init_state = spec.encode(history)
    return check_encoded(spec, e, init_state, **kw)
