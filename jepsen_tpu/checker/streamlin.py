"""Device-resident incremental linearizability: the streaming frontier
fold.

``checker/linear.py`` sweeps a history's *events* in time order,
maintaining the set of configurations (linearized-bitset, model state)
consistent with the prefix so far; an empty set at a return event is
the violation. That formulation is already the papers' online
monitoring algorithm ("Efficient Decrease-and-Conquer Linearizability
Monitoring", arXiv 2410.04581; "Efficient Linearizability Monitoring",
arXiv 2509.17795): the config set after a prefix is a *frontier* that
later events only ever extend. This module ports the sweep into one
batched jitted step:

    fold :: (frontier tensor, newly encoded cells) -> extended frontier

so the monitor can keep the frontier ON DEVICE across chunk boundaries
(``monitor/wgl_stream.py`` owns the seal/probe split that makes the
carry sound) and re-check a live stream in O(window) instead of
re-searching the O(prefix) encoding every chunk.

Device layout (all pow-2, ledger-hitting shapes):

* ``lin``   (F, B) uint32  -- per-config linearized bitset over window
                              SLOTS (B = NW/32 words; a slot, not a
                              history index, so the window can recycle)
* ``st``    (F, S) int32   -- per-config model state (fixed-width
                              models only; dynamic sizes fall back)
* ``live``  (F,)   bool    -- which frontier rows are real configs
* ``open_w``(B,)   uint32  -- the open-op slot set as a bitset
* events    (E,)   kind/slot -- 1 = invoke (opens the slot),
                              2 = return (forces the closure)

At a return event the kernel runs the same BFS closure as the CPU
sweep: every not-yet-done config expands by every open op through the
branch-free ``spec.step`` (vmapped over F*C candidates), the pool
dedups by a 64-bit multiply-shift fingerprint pair (sort + adjacent
compare, the jax_wgl dedup idiom -- a collision can only DROP a
config, shrinking the frontier, so it can cause a spurious violation
which the caller confirms offline, never a missed one), and the
surviving set compacts back into the F rows. ``n_keep > F`` or more
than C simultaneously-open slots flags overflow (status 2): the caller
pow-2-grows the capacity through ``compile_cache.bucket_for`` and
retries, or falls back to the flat engines -- statuses never silently
truncate, so the engine can never flip a verdict.

``check_encoded`` is the offline face: one fold over a whole encoded
history, returning the same verdict names as ``linear.check_encoded``
(True / False / "unknown" with ``max-configs-exceeded``). The
coalescer-facing half (``fold_lane_spec`` / ``FoldJob`` /
``batch_fold``) lets hundreds of monitored streams ride one vmapped
dispatch per ``(model, event bucket)`` group, exactly like ``/api/check``
tenants share ``keyshard.check_batch_encoded`` batches.
"""

from __future__ import annotations

import functools
import logging
import threading
import time

import numpy as np

from ..history import INF_TIME
from ..obs import search as obs_search

logger = logging.getLogger(__name__)

__all__ = ["DEFAULT_FRONTIER_CAP", "FRONTIER_CAP_MAX",
           "DEFAULT_WINDOW_CAP", "WINDOW_FLOOR", "OPEN_FLOOR",
           "EVENT_FLOOR", "STREAM_LANE_PREFIX", "FoldJob",
           "fold_lane_spec", "fresh_frontier", "solo_fold",
           "batch_fold", "check_encoded"]

#: default / hard maximum frontier capacity (configs). The default is
#: generous for low-contention streams (the config set usually stays
#: tiny); planlint PL026 rejects caps outside (0, FRONTIER_CAP_MAX].
DEFAULT_FRONTIER_CAP = 4096
FRONTIER_CAP_MAX = 65536

#: initial frontier capacity floor. Growth rides the campaign-wide
#: ``compile_cache.bucket_for`` ladder (a RAISED op-count floor
#: coarsens frontier shapes too, fewer compiles), but the op-count
#: knob must never shrink the starting frontier to 1 config -- a
#: floor tuned low for tiny histories says nothing about how many
#: consistent configurations a sweep holds live.
FRONTIER_FLOOR = 64

#: window slot capacity: unsealed + forever-open (info) rows live in
#: slots; past the cap the stream degrades to flat re-checks (counted,
#: contained -- crash-heavy histories are the CPU sweep's weakness too)
DEFAULT_WINDOW_CAP = 4096
WINDOW_FLOOR = 64

#: pow-2 floors for the open-op candidate axis and the event axis
OPEN_FLOOR = 8
EVENT_FLOOR = 64

#: the coalescer lane's model-name prefix: monitor folds queue per
#: ("streamlin:<model>", pow-2 event bucket) like WGL tenants queue
#: per (model, op bucket)
STREAM_LANE_PREFIX = "streamlin:"

#: positional order of FoldJob.arrays as the kernel wants them
_ARRAY_ORDER = ("lin", "st", "live", "open_w", "ev_kind", "ev_slot",
                "w_f", "w_args", "w_ret", "clear_w")


def _bucket(x, lo=1):
    from ..campaign import compile_cache
    return compile_cache.bucket(x, lo)


def _note(engine, key):
    """Compile-reuse ledger note, contained (the ledger is telemetry,
    never verdict-bearing)."""
    try:
        from ..campaign import compile_cache
        return compile_cache.note(engine, key)
    except Exception:  # noqa: BLE001 - telemetry-grade only
        return None


@functools.lru_cache(maxsize=128)
def _build_fold(step, K, F, B, S, C, E, A):
    """Compile the fold for one shape. ``step`` is the model's
    branch-free transition (hashable: ModelSpec.step functions are
    module-level); K streams ride one vmapped dispatch (K=1 skips the
    vmap so lax.cond stays a real branch, not a select)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    NW = B * 32
    vstep = jax.vmap(lambda stv, fv, av, rv: step(stv, fv, av, rv, jnp))

    def fingerprints(lin_w, st_w):
        # two independent 32-bit multiply-shift sums over the config's
        # words, position salted by per-column constants (the jax_wgl
        # incremental-fingerprint idiom)
        w = jnp.concatenate(
            [lin_w, lax.bitcast_convert_type(st_w, jnp.uint32)], axis=1)
        idx = jnp.arange(B + S, dtype=jnp.uint32)

        def mix(c_mul, c_add):
            c = idx * jnp.uint32(c_mul) + jnp.uint32(c_add)
            t = w * c[None, :]
            t = t ^ (t >> 15)
            t = t * jnp.uint32(0x2C1B3C6D)
            t = t ^ (t >> 12)
            return t.sum(axis=1, dtype=jnp.uint32)

        return (mix(0x9E3779B1, 0x85EBCA6B),
                mix(0xC2B2AE35, 0x27D4EB2F))

    def fold_one(lin, st, live, open_w, ev_kind, ev_slot,
                 w_f, w_args, w_ret, clear_w):
        slot_ids = jnp.arange(NW, dtype=jnp.int32)

        def ev_body(carry, ev):
            lin, st, live, open_w, status, viol, passes, steps = carry
            kind, slot = ev
            s = jnp.clip(slot, 0, NW - 1)
            word = s // 32
            bit = jnp.uint32(1) << jnp.uint32(s % 32)
            act = status == 0
            # invoke: the op merely becomes available
            inv_w = open_w.at[word].set(open_w[word] | bit)
            open_w = jnp.where(act & (kind == 1), inv_w, open_w)

            def tbit(lin_w):
                return ((lin_w[:, word] >> jnp.uint32(s % 32))
                        & jnp.uint32(1)) == 1

            def closure(op):
                # return of slot s: every config must linearize
                # sequences of open ops until s is linearized; configs
                # that can't are discarded (linear.py expand_until)
                lin, st, live, open_w, status, viol, passes, steps = op
                bits = ((open_w[:, None]
                         >> jnp.arange(32, dtype=jnp.uint32)[None, :])
                        & jnp.uint32(1)).astype(jnp.int32).reshape(NW)
                n_open = jnp.sum(bits)
                # open slot ids, padded with NW (sort-based: vmappable)
                oidx = jnp.sort(jnp.where(bits > 0, slot_ids, NW))[:C]
                j_valid = oidx < NW
                jc = jnp.minimum(oidx, NW - 1)
                f_j = w_f[jc]
                a_j = w_args[jc]
                r_j = w_ret[jc]
                j_word = jc // 32
                j_sh = jnp.uint32(jc % 32)
                j_bit = jnp.uint32(1) << j_sh
                add_mask = jnp.where(
                    jnp.arange(B)[None, :] == j_word[:, None],
                    j_bit[:, None], jnp.uint32(0))        # (C, B)

                def w_cond(stt):
                    _l, _s, _seen, work, p, _stp, ovf = stt
                    return jnp.any(work) & ~ovf & (p < C + 1)

                def w_body(stt):
                    lin, st, seen, work, p, stp, ovf = stt
                    pst = jnp.broadcast_to(
                        st[:, None, :], (F, C, S)).reshape(F * C, S)
                    pf = jnp.broadcast_to(
                        f_j[None, :], (F, C)).reshape(F * C)
                    pa = jnp.broadcast_to(
                        a_j[None, :, :], (F, C, A)).reshape(F * C, A)
                    pr = jnp.broadcast_to(
                        r_j[None, :, :], (F, C, A)).reshape(F * C, A)
                    st2, ok = vstep(pst, pf, pa, pr)
                    st2 = jnp.asarray(st2, jnp.int32).reshape(F * C, S)
                    ok = jnp.asarray(ok, bool).reshape(F * C)
                    already = ((lin[:, j_word] >> j_sh[None, :])
                               & jnp.uint32(1)) == 1       # (F, C)
                    parent_ok = (work[:, None] & j_valid[None, :]
                                 & ~already)
                    stp = stp + jnp.sum(parent_ok.astype(jnp.int32))
                    cand_valid = parent_ok.reshape(F * C) & ok
                    cand_lin = (lin[:, None, :]
                                | add_mask[None, :, :]).reshape(F * C, B)
                    # dedup pool: the F survivors-so-far + all F*C
                    # candidates; old entries sort first among equal
                    # fingerprints so the established config wins
                    pool_lin = jnp.concatenate([lin, cand_lin], 0)
                    pool_st = jnp.concatenate([st, st2], 0)
                    pool_v = jnp.concatenate([seen, cand_valid], 0)
                    pool_o = jnp.concatenate(
                        [jnp.ones(F, bool), jnp.zeros(F * C, bool)], 0)
                    h1, h2 = fingerprints(pool_lin, pool_st)
                    order = jnp.lexsort((
                        (~pool_o).astype(jnp.uint32), h2, h1,
                        (~pool_v).astype(jnp.uint32)))
                    sl = pool_lin[order]
                    ss = pool_st[order]
                    sv = pool_v[order]
                    so = pool_o[order]
                    sh1 = h1[order]
                    sh2 = h2[order]
                    dup = jnp.concatenate([
                        jnp.zeros(1, bool),
                        (sh1[1:] == sh1[:-1]) & (sh2[1:] == sh2[:-1])
                        & sv[1:] & sv[:-1]])
                    keep = sv & ~dup
                    n_keep = jnp.sum(keep.astype(jnp.int32))
                    ovf = ovf | (n_keep > F)
                    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
                    pos = jnp.where(keep, jnp.minimum(pos, F), F)
                    nlin = jnp.zeros((F + 1, B),
                                     jnp.uint32).at[pos].set(sl)[:F]
                    nst = jnp.zeros((F + 1, S),
                                    jnp.int32).at[pos].set(ss)[:F]
                    nseen = jnp.zeros(F + 1,
                                      bool).at[pos].set(keep)[:F]
                    nold = jnp.zeros(F + 1,
                                     bool).at[pos].set(so & keep)[:F]
                    # fresh configs without the target go back to work;
                    # expanded (old) ones retire to dedup-only ghosts
                    nwork = nseen & ~tbit(nlin) & ~nold
                    return nlin, nst, nseen, nwork, p + 1, stp, ovf

                work0 = live & ~tbit(lin)
                lin2, st2, seen2, _w, local_p, steps2, ovf = \
                    lax.while_loop(
                        w_cond, w_body,
                        (lin, st, live, work0, jnp.int32(0), steps,
                         n_open > C))
                final = seen2 & tbit(lin2)
                violated = ~jnp.any(final) & ~ovf
                open_w2 = open_w.at[word].set(open_w[word] & ~bit)
                status2 = jnp.where(
                    ovf, jnp.int32(2),
                    jnp.where(violated, jnp.int32(1), status))
                viol2 = jnp.where(violated, s, viol)
                return (lin2, st2, final, open_w2, status2, viol2,
                        passes + local_p, steps2)

            carry2 = lax.cond(
                act & (kind == 2), closure, lambda op: op,
                (lin, st, live, open_w, status, viol, passes, steps))
            return carry2, None

        carry, _ = lax.scan(
            ev_body,
            (lin, jnp.asarray(st, jnp.int32), live, open_w,
             jnp.int32(0), jnp.int32(-1), jnp.int32(0), jnp.int32(0)),
            (ev_kind, ev_slot))
        lin, st, live, open_w, status, viol, passes, steps = carry
        # recycle fully-sealed slots: their bit is set in EVERY live
        # config (the return event forced it), so clearing is uniform
        lin = lin & ~clear_w[None, :]
        return (lin, st, live, open_w, status, viol, passes, steps,
                jnp.sum(live.astype(jnp.int32)))

    fn = fold_one if K == 1 else jax.vmap(fold_one)
    return jax.jit(fn)


def fresh_frontier(F, B, S, init_state):
    """The singleton frontier {(nothing linearized, init_state)} as
    host arrays shaped for the fold."""
    lin = np.zeros((F, B), np.uint32)
    st = np.zeros((F, S), np.int32)
    st[0] = np.asarray(init_state, np.int32)
    live = np.zeros(F, bool)
    live[0] = True
    open_w = np.zeros(B, np.uint32)
    return lin, st, live, open_w


class FoldJob:
    """One stream's frontier-extension step, packaged for the solo
    path or a coalesced batch. ``arrays`` follow ``_ARRAY_ORDER``;
    ``len(job)`` is the REAL event count (the coalescer's bucketing
    measure). Event arrays must be host numpy (batch padding); the
    frontier/window tensors may be device-resident jax arrays."""

    __slots__ = ("spec", "C", "arrays", "n_events")

    def __init__(self, spec, C, arrays, n_events):
        self.spec = spec
        self.C = int(C)
        self.arrays = arrays
        self.n_events = int(n_events)

    def __len__(self):
        return self.n_events

    @property
    def F(self):
        return int(self.arrays["lin"].shape[0])

    @property
    def B(self):
        return int(self.arrays["lin"].shape[1])

    @property
    def S(self):
        return int(self.arrays["st"].shape[1])

    @property
    def E(self):
        return int(self.arrays["ev_kind"].shape[0])

    @property
    def A(self):
        return int(self.arrays["w_args"].shape[1])

    def shape_key(self):
        return (self.spec.name, self.F, self.B, self.S, self.C, self.A)


class _FoldLaneSpec:
    """The coalescer's stand-in "model" for stream frontier folds:
    monitored streams queue per (``streamlin:<model>``, pow-2 event
    bucket) exactly like WGL tenants queue per (model, op bucket), and
    one vmapped fold answers the whole batch (``batch_fold``)."""

    __slots__ = ("name", "spec")

    def __init__(self, spec):
        self.spec = spec
        self.name = STREAM_LANE_PREFIX + spec.name


_lane_lock = threading.Lock()
_lane_specs: dict = {}


def fold_lane_spec(spec):
    """The interned coalescer lane spec for a model (one per model so
    every stream of that model shares the lane)."""
    with _lane_lock:
        lane = _lane_specs.get(spec.name)
        if lane is None:
            lane = _lane_specs[spec.name] = _FoldLaneSpec(spec)
        return lane


def _scalars(job_or_key, out, idx=None):
    import jax
    status, viol, passes, steps, n_live = jax.device_get(
        (out[4], out[5], out[6], out[7], out[8]))
    if idx is not None:
        status, viol, passes, steps, n_live = (
            status[idx], viol[idx], passes[idx], steps[idx], n_live[idx])
    return (int(status), int(viol), int(passes), int(steps),
            int(n_live))


def solo_fold(job):
    """Run one FoldJob locally (the containment path when no
    coalescer is live, a batch failed, or a deadline passed). Returns
    the fold result dict; the frontier tensors stay device-resident
    jax arrays for the caller to re-commit."""
    fn = _build_fold(job.spec.step, 1, job.F, job.B, job.S, job.C,
                     job.E, job.A)
    _note("streamlin", (job.spec.name, 1, job.F, job.B, job.S, job.C,
                        job.E, job.A))
    t0 = time.monotonic()
    out = fn(*(job.arrays[k] for k in _ARRAY_ORDER))
    status, viol, passes, steps, n_live = _scalars(job, out)
    return {"engine": "streamlin", "status": status,
            "viol_slot": viol, "passes": passes, "steps": steps,
            "n_live": n_live, "lin": out[0], "st": out[1],
            "live": out[2], "open_w": out[3],
            "device_s": time.monotonic() - t0}


def _pad_events(a, E):
    a = np.asarray(a, np.int32)
    if a.shape[0] == E:
        return a
    return np.pad(a, (0, E - a.shape[0]))


def batch_fold(jobs, owners=None, e_bucket=None):
    """Run many FoldJobs as vmapped device batches, grouped by full
    shape key (the lane name only pins the model; a defensive regroup
    here means a mixed batch can never mis-stack). Frontier-extension
    steps from strangers' streams ride one compiled dispatch; K pads
    to a pow-2 with inert (zero-event) members. Returns one result
    dict per job, in order."""
    import jax
    import jax.numpy as jnp

    results = [None] * len(jobs)
    groups: dict = {}
    for i, job in enumerate(jobs):
        groups.setdefault(job.shape_key(), []).append(i)
    t0 = time.monotonic()
    for key, idxs in groups.items():
        members = [jobs[i] for i in idxs]
        if len(members) == 1:
            results[idxs[0]] = dict(solo_fold(members[0]), batch=1)
            continue
        spec = members[0].spec
        _name, F, B, S, C, A = key
        E = _bucket(max(max(m.E for m in members), int(e_bucket or 1)),
                    EVENT_FLOOR)
        K = _bucket(len(members), 1)
        fn = _build_fold(spec.step, K, F, B, S, C, E, A)
        _note("streamlin-batch", (spec.name, K, F, B, S, C, E, A))
        stacks = []
        for name in _ARRAY_ORDER:
            parts = []
            for m in members:
                a = m.arrays[name]
                if name in ("ev_kind", "ev_slot"):
                    a = _pad_events(a, E)
                parts.append(jnp.asarray(a))
            # pad members are member 0 with no events: a fold over
            # zero events is the identity, so the lane is inert
            for _ in range(K - len(members)):
                parts.append(jnp.zeros(E, jnp.int32)
                             if name in ("ev_kind", "ev_slot")
                             else parts[0])
            stacks.append(jnp.stack(parts))
        out = fn(*stacks)
        for pos, i in enumerate(idxs):
            status, viol, passes, steps, n_live = _scalars(
                members[pos], out, pos)
            results[i] = {"engine": "streamlin", "status": status,
                          "viol_slot": viol, "passes": passes,
                          "steps": steps, "n_live": n_live,
                          "lin": out[0][pos], "st": out[1][pos],
                          "live": out[2][pos], "open_w": out[3][pos],
                          "batch": len(members)}
    dt = time.monotonic() - t0
    try:
        so = obs_search.capture()
        n_owners = len(set(owners)) if owners else 1
        so.plan("streamlin-batch",
                _bucket(max((len(j) for j in jobs), default=1),
                        EVENT_FLOOR),
                sum(len(j) for j in jobs),
                sum(j.E for j in jobs), keys=len(jobs),
                owners=n_owners)
        so.heartbeat("streamlin-batch", iteration=1, chunk_s=dt,
                     device_s=dt, frontier=max(
                         (r["n_live"] for r in results if r), default=0))
    except Exception:  # noqa: BLE001 - telemetry-grade only
        logger.warning("streamlin batch telemetry failed", exc_info=True)
    return results


def check_encoded(spec, e, init_state, max_configs=DEFAULT_FRONTIER_CAP,
                  cancel=None):
    """The offline face: one frontier fold over a whole encoded
    history. Same verdict names as ``linear.check_encoded`` (True /
    False with the violating ``op`` / "unknown" with
    ``max-configs-exceeded``); ``configs_explored`` counts model-step
    evaluations. On False the streaming monitor re-confirms through a
    flat engine for the witness artifact set -- this face reports the
    violating op only."""
    n = len(e)
    if n == 0 or e.n_ok == 0:
        return {"valid": True, "configs_explored": 0,
                "engine": "streamlin"}
    init = np.asarray(init_state, np.int32)
    S = max(1, int(init.shape[0]))
    A = int(spec.arg_width)
    NW = _bucket(n, WINDOW_FLOOR)
    B = NW // 32
    events = sorted(
        [(int(e.invoke_idx[i]), 1, i) for i in range(n)]
        + [(int(e.return_idx[i]), 2, i) for i in range(n)
           if e.return_idx[i] < INF_TIME])
    c_now = c_max = 0
    for _t, kind, _i in events:
        c_now += 1 if kind == 1 else -1
        c_max = max(c_max, c_now)
    C = min(NW, _bucket(max(1, c_max), OPEN_FLOOR))
    E = _bucket(len(events), EVENT_FLOOR)
    ev_kind = np.zeros(E, np.int32)
    ev_slot = np.zeros(E, np.int32)
    for k, (_t, kind, i) in enumerate(events):
        ev_kind[k] = kind
        ev_slot[k] = i
    w_f = np.zeros(NW, np.int32)
    w_args = np.zeros((NW, A), np.int32)
    w_ret = np.zeros((NW, A), np.int32)
    w_f[:n] = e.f
    w_args[:n] = np.asarray(e.args, np.int32).reshape(n, A)
    w_ret[:n] = np.asarray(e.ret, np.int32).reshape(n, A)
    cap = min(_bucket(max(1, int(max_configs))), FRONTIER_CAP_MAX)
    from ..campaign import compile_cache
    F = min(cap, max(FRONTIER_FLOOR, compile_cache.bucket_for(1)))
    steps = 0
    while True:
        if cancel is not None and cancel.is_set():
            return {"valid": "unknown", "error": "cancelled",
                    "configs_explored": steps, "engine": "streamlin"}
        lin, st, live, open_w = fresh_frontier(F, B, S, init)
        job = FoldJob(spec, C, {
            "lin": lin, "st": st, "live": live, "open_w": open_w,
            "ev_kind": ev_kind, "ev_slot": ev_slot, "w_f": w_f,
            "w_args": w_args, "w_ret": w_ret,
            "clear_w": np.zeros(B, np.uint32)}, len(events))
        r = solo_fold(job)
        steps += r["steps"]
        if r["status"] == 2 and F < cap:
            F = min(cap, F * 2)
            continue
        break
    if r["status"] == 2:
        return {"valid": "unknown", "error": "max-configs-exceeded",
                "configs_explored": steps, "engine": "streamlin",
                "frontier_cap": F}
    if r["status"] == 1:
        out = {"valid": False, "configs_explored": steps,
               "engine": "streamlin"}
        i = r["viol_slot"]
        if e.ops is not None and 0 <= i < len(e.ops):
            inv, comp = e.ops[i]
            out["op"] = dict(comp if comp is not None else inv)
        return out
    return {"valid": True, "configs_explored": steps,
            "engine": "streamlin", "frontier": r["n_live"]}
