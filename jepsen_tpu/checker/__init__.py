"""Checkers: history -> verdict maps (reference jepsen/src/jepsen/checker.clj).

Core protocol and combinators live in checker.core; the linearizability
engines in checker.wgl (CPU oracle) and checker.jax_wgl (batched TPU search).
"""

from .core import *  # noqa: F401,F403
from .core import __all__ as _core_all

__all__ = list(_core_all)
