"""Checker protocol + combinators (reference jepsen/src/jepsen/checker.clj).

A checker examines a history and returns a map with a ``valid`` key:
True, False, or "unknown" (couldn't decide). Validity merges with
False > "unknown" > True (checker.clj:29-50).
"""

from __future__ import annotations

import logging
import threading
import traceback

from .. import history as h
from .. import obs
from ..util import real_pmap

__all__ = ["Checker", "check", "check_safe", "compose", "concurrency_limit",
           "noop", "unbridled_optimism", "merge_valid", "valid_prio",
           "lint_history", "plan_history", "certify_verdict"]

logger = logging.getLogger(__name__)


def valid_prio(v):
    """Validity severity: false dominates, then unknown, then true
    (checker.clj:29-39)."""
    if v is False:
        return 0
    if v == "unknown" or v is None:
        return 1
    return 2


def merge_valid(valids):
    """Merge a collection of validity values (checker.clj:41-50)."""
    out = True
    for v in valids:
        if valid_prio(v) < valid_prio(out):
            out = v
    return out


class Checker:
    """check(test, history, opts) -> {"valid": ..., ...} (checker.clj:52-67).

    opts is a map like {"history-file": ..., "subdirectory": ...} used by
    checkers that write files.
    """

    def check(self, test, hist, opts=None):  # pragma: no cover - interface
        raise NotImplementedError

    def __call__(self, test, hist, opts=None):
        return self.check(test, hist, opts or {})


class FnChecker(Checker):
    def __init__(self, fn, name=None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "checker")

    def check(self, test, hist, opts=None):
        return self.fn(test, hist, opts or {})

    def __repr__(self):
        return f"<checker {self.name}>"


def as_checker(c) -> Checker:
    if isinstance(c, Checker):
        return c
    if callable(c):
        return FnChecker(c)
    raise TypeError(f"not a checker: {c!r}")


def checker_name(checker):
    """Human-readable checker name for spans/metrics."""
    return getattr(checker, "name", None) or type(checker).__name__


_lint_lock = threading.Lock()


def lint_history(test, hist):
    """Run histlint over ``hist`` once per test map, before checkers see
    it: diagnostics land in ``test["analysis"]["history"]``
    (store.write_analysis persists them as analysis.json) and error
    findings are logged. Opt out per test with ``test["analysis?"] =
    False``. Runs at most once per test dict -- Compose fans every
    subchecker back through check(), and the history doesn't change.

    Lint failures are contained: a bug in the analyzer must never
    change a verdict."""
    if not isinstance(test, dict) or not test.get("analysis?", True):
        return
    with _lint_lock:
        if test.get("analysis-done?"):
            return
        test["analysis-done?"] = True
    try:
        from .. import analysis
        diags = analysis.run_analyzer(
            "histlint", analysis.lint_test_history, test, hist)
        report = analysis.to_json(diags)
        test.setdefault("analysis", {})["history"] = report
        errs = analysis.errors(diags)
        if errs:
            logger.warning(
                "%s", analysis.render_text(
                    errs, title="history lint found structural "
                                "defects; the verdict below may not "
                                "be trustworthy:"))
    except Exception:  # noqa: BLE001 - telemetry, never verdict-bearing
        logger.warning("history lint crashed", exc_info=True)


def plan_history(test, hist):
    """Run the search planner over ``hist`` once per test map, next to
    histlint: the SearchPlan's SP/JX007 diagnostics land in
    ``test["analysis"]["searchplan"]`` (persisted as analysis.json)
    with the plan summary alongside. The *executing* checkers
    (Linearizable, independent's batched path) re-derive their own
    segments — this hook is the report of record, and like histlint
    it is contained: a planner bug must never change a verdict. Opt
    out per test with ``test["searchplan?"] = False`` (or
    ``test["analysis?"] = False`` for all analyzers)."""
    if not isinstance(test, dict) or not test.get("analysis?", True):
        return
    from ..analysis import searchplan
    if not searchplan.enabled(test):
        return
    with _lint_lock:
        if test.get("searchplan-done?"):
            return
        test["searchplan-done?"] = True
    try:
        from .. import analysis
        holder = {}

        def build():
            plan = searchplan.build_plan(test, hist)
            if plan is None:
                return []
            holder["summary"] = plan.summary()
            return plan.diagnostics

        diags = analysis.run_analyzer("searchplan", build)
        summary = holder.get("summary")
        if summary is not None:
            report = analysis.to_json(diags)
            report["summary"] = summary
            test.setdefault("analysis", {})["searchplan"] = report
    except Exception:  # noqa: BLE001 - telemetry, never verdict-bearing
        logger.warning("search planning crashed", exc_info=True)


def certify_verdict(checker, test, hist, result, key=None):
    """Certify a decided Linearizable verdict from its own artifacts,
    after the checker returns: replay the witness through the pure CPU
    model (VC001-VC003), cross-check invalid verdicts through an
    independent engine (VC008), and run the sampled differential
    harness (VC010). Findings land in ``test["analysis"]["certify"]``
    and the full proof in ``test["certificate"]`` (persisted as
    certificate.json); error findings are logged. Opt out per test
    with ``test["certify?"] = False``. Runs at most once per test
    dict — Compose fans every subchecker back through check(), and
    only the Linearizable call carries a certifiable result.

    Certification is contained exactly like histlint/searchplan: a
    certifier bug must NEVER flip a verdict or exit code."""
    if not isinstance(result, dict) \
            or result.get("valid") not in (True, False):
        return
    try:
        from ..analysis import certify
        if not certify.enabled(test):
            return
        from .checkers import Linearizable
        if not isinstance(checker, Linearizable):
            return
        with _lint_lock:
            if test.get("certify-done?"):
                return
            test["certify-done?"] = True
        from .. import analysis
        cfg = certify.config(test)
        client = checker.prepare_history(h.client_ops(hist))
        holder = {}

        def build():
            cert, diags = certify.certify_with_diagnostics(
                checker.spec, client, result, test=test,
                samples=cfg["samples"], budget=cfg["budget"],
                init_ops=checker.init_ops, key=key)
            holder["cert"] = cert
            return diags

        diags = analysis.run_analyzer("certify", build)
        cert = holder.get("cert")
        if cert is not None:
            report = analysis.to_json(diags)
            report["summary"] = {"verdict": cert["verdict"],
                                 "engine": cert["engine"],
                                 "checks": cert["checks"]}
            test.setdefault("analysis", {})["certify"] = report
            test["certificate"] = cert
        errs = analysis.errors(diags)
        if obs.enabled():
            obs.inc("analysis.certify.runs",
                    verdict=str(result.get("valid")))
            if errs:
                obs.inc("analysis.certify.vc_errors", len(errs))
        if errs:
            logger.warning(
                "%s", analysis.render_text(
                    errs, title="verdict certification FAILED; the "
                                "verdict above does not replay from "
                                "its own witness:"))
    except Exception:  # noqa: BLE001 - contained, never verdict-bearing
        logger.warning("verdict certification crashed", exc_info=True)


def check(checker, test, hist, opts=None):
    hist = h.ensure_indexed(hist)
    lint_history(test, hist)
    plan_history(test, hist)
    result = as_checker(checker).check(test, hist, opts or {})
    certify_verdict(checker, test, hist, result)
    return result


def check_safe(checker, test, hist, opts=None):
    """Like check, but exceptions become {"valid": "unknown"}
    (checker.clj:74-85). Every (sub)checker run — Compose fans out
    through here too — gets a trace span + latency observation."""
    name = checker_name(checker)
    t0 = obs.now_ns()
    try:
        result = check(checker, test, hist, opts)
    except Exception:  # noqa: BLE001 - mirrors reference behavior
        result = {"valid": "unknown",
                  "error": traceback.format_exc()}
    if obs.enabled():
        dur = obs.now_ns() - t0
        obs.complete(f"checker.{name}", t0, dur, cat="checker",
                     valid=str(result.get("valid")))
        obs.observe("checker.check_s", dur / 1e9, checker=name)
        obs.inc("checker.checks", checker=name,
                valid=str(result.get("valid")))
    return result


class Compose(Checker):
    """Map of name -> checker, run in parallel; result map of name -> result
    with merged validity (checker.clj:87-99)."""

    def __init__(self, checker_map):
        self.checker_map = {k: as_checker(c) for k, c in checker_map.items()}

    def check(self, test, hist, opts=None):
        items = list(self.checker_map.items())
        results = real_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, hist, opts)), items)
        rmap = dict(results)
        return {"valid": merge_valid([r.get("valid") for r in rmap.values()]),
                **rmap}


def compose(checker_map):
    return Compose(checker_map)


class _Noop(Checker):
    def check(self, test, hist, opts=None):
        return {"valid": True}


def noop():
    return _Noop()


class _Optimism(Checker):
    def check(self, test, hist, opts=None):
        return {"valid": True, "everything-looks-good?": "definitely"}


def unbridled_optimism():
    """Everything is awesome! (checker.clj:118-122)"""
    return _Optimism()


_limit_semaphores = {}
_limit_lock = threading.Lock()


class ConcurrencyLimit(Checker):
    """At most ``limit`` concurrent executions of this checker across
    threads, keyed by ``key`` -- memory governance for expensive checkers
    (checker.clj:101-116)."""

    def __init__(self, limit, checker, key=None):
        self.checker = as_checker(checker)
        self.key = key if key is not None else id(self)
        with _limit_lock:
            if self.key not in _limit_semaphores:
                _limit_semaphores[self.key] = threading.Semaphore(limit)
        self.sem = _limit_semaphores[self.key]

    def check(self, test, hist, opts=None):
        with self.sem:
            return self.checker.check(test, hist, opts)


def concurrency_limit(limit, checker, key=None):
    return ConcurrencyLimit(limit, checker, key)
