"""Performance plots: latency and throughput graphs rendered with
matplotlib (reference jepsen/src/jepsen/checker/perf.clj, which drives
gnuplot).

Produces the same artifacts: ``latency-raw.png`` (raw per-op latency
points by f and outcome), ``latency-quantiles.png`` (0.5/0.95/0.99/1
quantiles over time), ``rate.png`` (completion throughput), all with
shaded nemesis activity regions (perf.clj:184-324).
"""

from __future__ import annotations

import logging

from .. import history as h
from .core import Checker

logger = logging.getLogger(__name__)

#: seconds per quantile bucket (perf.clj:516)
QUANTILE_DT = 30
#: seconds per rate bucket (perf.clj:561)
RATE_DT = 10

QUANTILES = (0.5, 0.95, 0.99, 1.0)

TYPES = ("ok", "info", "fail")

TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}

DEFAULT_NEMESIS_COLOR = "#cccccc"
NEMESIS_ALPHA = 0.6


# ---------------------------------------------------------------------------
# history -> latency points

def history_latencies(history):
    """Pairs of (invoke-op, latency-ms) for completed client ops
    (util/history->latencies)."""
    out = []
    open_by_process = {}
    for op in history:
        p = op.get("process")
        if not isinstance(p, int):
            continue
        if h.invoke(op):
            open_by_process[p] = op
        else:
            inv = open_by_process.pop(p, None)
            if inv is not None:
                lat = (op.get("time", 0) - inv.get("time", 0)) / 1e6
                out.append((inv, op, lat))
    return out


def latency_points_by_f_type(history):
    """{f: {type: [(t_secs, latency_ms)]}} (perf.clj invokes-by-f-type)."""
    datasets = {}
    for inv, comp, lat in history_latencies(history):
        f = inv.get("f")
        t = comp.get("type")
        datasets.setdefault(f, {}).setdefault(t, []).append(
            (inv.get("time", 0) / 1e9, max(lat, 1e-3)))
    return datasets


def latencies_to_quantiles(dt, qs, points):
    """Bucket (t, latency) points into dt-second windows and compute
    quantiles per window (perf.clj:63-80). Returns {q: [(t, latency)]}."""
    buckets = {}
    for t, lat in points:
        buckets.setdefault(int(t // dt), []).append(lat)
    out = {q: [] for q in qs}
    for b in sorted(buckets):
        lats = sorted(buckets[b])
        mid_t = b * dt + dt / 2
        n = len(lats)
        for q in qs:
            idx = min(n - 1, int(q * n))
            out[q].append((mid_t, lats[idx]))
    return out


# ---------------------------------------------------------------------------
# nemesis activity

def nemesis_intervals(ops, spec=None):
    """Pairs nemesis (invoke, complete) event pairs into [start, stop]
    activity intervals; multiple starts are closed by the same stops
    (util.clj:736-787). Ops lacking a stop pair with None."""
    spec = spec or {}
    start_fs = set(spec.get("start", {"start"}))
    stop_fs = set(spec.get("stop", {"stop"}))
    # group into (invoke, completion) pairs
    pairs = []
    for i in range(0, len(ops) - 1, 2):
        a, b = ops[i], ops[i + 1]
        if a.get("f") == b.get("f"):
            pairs.append((a, b))
    intervals = []
    starts = []
    for a, b in pairs:
        f = a.get("f")
        if _f_matches(f, start_fs):
            starts.append((a, b))
        elif _f_matches(f, stop_fs):
            for s1, s2 in starts:
                intervals.append([s1, a])
                intervals.append([s2, b])
            starts = []
    for s1, s2 in starts:
        intervals.append([s1, None])
        intervals.append([s2, None])
    return intervals


def _f_matches(f, fs):
    if f in fs:
        return True
    return isinstance(f, str) and any(
        isinstance(x, str) and x in f for x in fs)


def nemesis_ops(nemeses, history):
    """Partition nemesis ops in history among the nemesis specs
    (perf.clj:184-216); unmatched ops fall to a default "nemesis" spec."""
    # nemesis packages store perf specs as frozen item tuples so they can
    # live in sets (nemesis/combined._perf); accept those alongside dicts
    nemeses = [dict(s) if isinstance(s, tuple) else s
               for s in (nemeses or [])]
    index = {}
    for spec in nemeses:
        for f in (list(spec.get("start", ["start"]))
                  + list(spec.get("stop", ["stop"]))
                  + list(spec.get("fs", []))):
            index[f] = spec["name"]
    by_name = {}
    for op in history:
        if op.get("process") != "nemesis":
            continue
        by_name.setdefault(index.get(op.get("f")), []).append(op)
    out = []
    for spec in nemeses:
        ops = by_name.get(spec["name"])
        if ops:
            out.append({**spec, "ops": ops})
    if by_name.get(None):
        out.append({"name": "nemesis", "ops": by_name[None]})
    return out


def nemesis_activity(nemeses, history):
    """Nemesis specs + their ops + [start stop] intervals
    (perf.clj:218-230)."""
    out = []
    for spec in nemesis_ops(nemeses, history):
        out.append({**spec,
                    "intervals": nemesis_intervals(spec["ops"], spec)})
    return out


def shade_nemeses(ax, history, nemeses=None):
    """Shade nemesis activity intervals and draw event lines onto a
    matplotlib axis (perf.clj nemesis-regions + nemesis-lines)."""
    activity = nemesis_activity(nemeses, history)
    t_max = max((op.get("time", 0) for op in history), default=0) / 1e9
    for i, n in enumerate(activity):
        color = n.get("fill-color") or n.get("color") \
            or DEFAULT_NEMESIS_COLOR
        # divide the vertical space into twelfths (perf.clj:254-260)
        height = 0.0834
        bot = 1 - height * (i + 1)
        for start, stop in n["intervals"]:
            t0 = start.get("time", 0) / 1e9
            t1 = stop.get("time", 0) / 1e9 if stop else t_max
            ax.axvspan(t0, t1, ymin=bot + 0.006,
                       ymax=bot + height - 0.006,
                       color=color, alpha=1 - NEMESIS_ALPHA, lw=0,
                       label=None)
        for op in n["ops"]:
            ax.axvline(op.get("time", 0) / 1e9, color=color,
                       lw=n.get("line-width", 1), alpha=0.7)
        # legend proxy
        ax.plot([], [], color=color, lw=6, label=str(n["name"]))


# ---------------------------------------------------------------------------
# the three graphs

def _f_markers(fs):
    markers = ["+", "x", "*", "s", "o", "^", "v", "D", "p", "1", "2", "3"]
    return {f: markers[i % len(markers)] for i, f in enumerate(sorted(
        fs, key=repr))}


def _out_path(test, opts, filename):
    """Resolve the output path BEFORE building any figure, so a missing
    store directory can't leak matplotlib figures."""
    from .. import store
    return store.make_path(test, (opts or {}).get("subdirectory"), filename)


def _axes(title, ylabel, logy=False):
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    fig, ax = plt.subplots(figsize=(9, 4))
    ax.set_title(title)
    ax.set_xlabel("Time (s)")
    ax.set_ylabel(ylabel)
    if logy:
        ax.set_yscale("log")
    return fig, ax


def point_graph(test, history, opts=None):
    """latency-raw.png: raw latency points by f and outcome
    (perf.clj:484-511)."""
    opts = opts or {}
    datasets = latency_points_by_f_type(history)
    if not datasets:
        return None
    path = _out_path(test, opts, "latency-raw.png")
    import matplotlib.pyplot as plt
    fig, ax = _axes(f"{test.get('name')} latency", "Latency (ms)",
                    logy=True)
    try:
        markers = _f_markers(datasets.keys())
        for f, by_type in sorted(datasets.items(),
                                 key=lambda kv: repr(kv[0])):
            for t in TYPES:
                pts = by_type.get(t)
                if not pts:
                    continue
                ax.scatter([p[0] for p in pts], [p[1] for p in pts],
                           c=TYPE_COLORS[t], marker=markers[f], s=16,
                           label=f"{f} {t}")
        shade_nemeses(ax, history,
                      opts.get("nemeses") or (test.get("plot") or {})
                      .get("nemeses"))
        ax.legend(loc="upper left", bbox_to_anchor=(1.01, 1), fontsize=7)
        fig.tight_layout()
        fig.savefig(path, dpi=100)
    finally:
        plt.close(fig)
    return path


def quantiles_graph(test, history, opts=None):
    """latency-quantiles.png: latency quantiles by f over time
    (perf.clj:513-550)."""
    opts = opts or {}
    datasets = {}
    for inv, comp, lat in history_latencies(history):
        datasets.setdefault(inv.get("f"), []).append(
            (inv.get("time", 0) / 1e9, max(lat, 1e-3)))
    if not datasets:
        return None
    path = _out_path(test, opts, "latency-quantiles.png")
    import matplotlib.pyplot as plt
    fig, ax = _axes(f"{test.get('name')} latency", "Latency (ms)",
                    logy=True)
    try:
        markers = _f_markers(datasets.keys())
        q_colors = {0.5: "#6DB6FE", 0.95: "#FFAA26", 0.99: "#FEB5DA",
                    1.0: "#FF1E90"}
        for f, pts in sorted(datasets.items(), key=lambda kv: repr(kv[0])):
            qmap = latencies_to_quantiles(QUANTILE_DT, QUANTILES, pts)
            for q in QUANTILES:
                data = qmap[q]
                if not data:
                    continue
                ax.plot([p[0] for p in data], [p[1] for p in data],
                        marker=markers[f], ms=4,
                        color=q_colors.get(q, "#888888"),
                        label=f"{f} {q}")
        shade_nemeses(ax, history,
                      opts.get("nemeses") or (test.get("plot") or {})
                      .get("nemeses"))
        ax.legend(loc="upper left", bbox_to_anchor=(1.01, 1), fontsize=7)
        fig.tight_layout()
        fig.savefig(path, dpi=100)
    finally:
        plt.close(fig)
    return path


def rate_graph(test, history, opts=None):
    """rate.png: completion throughput by f and type in RATE_DT buckets
    (perf.clj:559-599)."""
    opts = opts or {}
    datasets = {}
    t_max = 0.0
    for op in history:
        if h.invoke(op) or not isinstance(op.get("process"), int):
            continue
        t = op.get("time", 0) / 1e9
        t_max = max(t_max, t)
        b = int(t // RATE_DT) * RATE_DT
        key = (op.get("f"), op.get("type"))
        datasets[key] = datasets.get(key, {})
        datasets[key][b] = datasets[key].get(b, 0) + 1 / RATE_DT
    if not datasets:
        return None
    path = _out_path(test, opts, "rate.png")
    import matplotlib.pyplot as plt
    fig, ax = _axes(f"{test.get('name')} rate", "Throughput (hz)")
    try:
        markers = _f_markers({f for f, _ in datasets})
        buckets = [b * RATE_DT for b in range(int(t_max // RATE_DT) + 1)]
        for (f, t), m in sorted(datasets.items(),
                                key=lambda kv: repr(kv[0])):
            ys = [m.get(b, 0) for b in buckets]
            ax.plot(buckets, ys, marker=markers[f], ms=4, c=TYPE_COLORS[t],
                    label=f"{f} {t}")
        shade_nemeses(ax, history,
                      opts.get("nemeses") or (test.get("plot") or {})
                      .get("nemeses"))
        ax.legend(loc="upper left", bbox_to_anchor=(1.01, 1), fontsize=7)
        fig.tight_layout()
        fig.savefig(path, dpi=100)
    finally:
        plt.close(fig)
    return path


# ---------------------------------------------------------------------------
# checkers (checker.clj:797-829)

class _LatencyGraph(Checker):
    def __init__(self, opts=None):
        self.opts = opts or {}

    def check(self, test, hist, opts=None):
        o = {**self.opts, **(opts or {})}
        try:
            point_graph(test, hist, o)
            quantiles_graph(test, hist, o)
            return {"valid": True}
        except AssertionError:
            return {"valid": True, "skipped": "no store directory"}


class _RateGraph(Checker):
    def __init__(self, opts=None):
        self.opts = opts or {}

    def check(self, test, hist, opts=None):
        o = {**self.opts, **(opts or {})}
        try:
            rate_graph(test, hist, o)
            return {"valid": True}
        except AssertionError:
            return {"valid": True, "skipped": "no store directory"}


def latency_graph(opts=None):
    """Renders latency-raw.png + latency-quantiles.png
    (checker.clj:797-808)."""
    return _LatencyGraph(opts)


def rate_graph_checker(opts=None):
    """Renders rate.png (checker.clj:810-820)."""
    return _RateGraph(opts)


def perf(opts=None):
    """Composes both latency and rate graphs (checker.clj:822-829)."""
    from .core import compose
    return compose({"latency-graph": latency_graph(opts),
                    "rate-graph": _RateGraph(opts)})
