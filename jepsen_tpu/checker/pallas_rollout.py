"""Fused greedy-rollout kernel for the single-key WGL search (Pallas/TPU).

The single-key search is latency-bound end to end: PROFILE.md's round-4
profile measured ~8 ms of leaf busy against ~60 ms of wall per
iteration, most of the gap being the rollout ``lax.scan`` -- R=256
sequential micro-steps, each a handful of tiny fused ops whose
dispatch dependencies the XLA scheduler cannot overlap (~26 us busy vs
~175 us wall per micro-step). This module collapses the whole chain
into ONE Pallas kernel: the R-step loop runs inside the kernel with
the chains' eligibility masks and model states resident in VMEM, so a
micro-step costs its compute, not its dispatch.

Scope (VERDICT r4 #1): single-key searches (K=1) on models whose step
function is *plane-broadcastable* and whose padded state is small --
register/cas/mutex, where S is a word or two. The FIFO search (S up
to 8k after pad_state, gather-based step) falls back to the scan, as
does any shape that would not fit the kernel's VMEM budget; the K>1
batch path keeps the scan too (it pins NS=1 and is throughput-bound
on the key axis, not latency-bound on the chain -- PROFILE.md).

Mosaic-shaped design notes (each constraint below was hit for real):

* Instead of the packed lin bitset, the kernel keeps an **unpacked
  per-op "unlinearized" mask** (NCH, NS, CH) u32 resident in VMEM,
  aliased input->output so it mutates in place. The first fused
  design unpacked the bitset per chunk per step (32 shifted concats,
  ~64 per micro-step); at n_pad=131k that made the kernel SLOWER than
  the scan it replaced (~187 ms vs ~57 ms per search iteration).
  With the mask resident, eligibility is one ref read, and the
  per-step flip is a single masked full-tensor multiply.
* No reshapes, no vmap, no rank-1 values, no bool carries or bool
  minor-dim inserts, no dynamic_slice on values: Mosaic rejects or
  miscompiles each (shape casts, i1 scf.for carries, i1 concats ->
  invalid vreg bitcasts, rank-1 layouts). Everything in the kernel is
  a rank-3 tensor; per-seed scalars ride as (1, NS, 1); chunk sweeps
  are ``fori_loop``s over dynamic-sublane ref slices (a
  Python-unrolled sweep kept every chunk's temporaries live at once
  and blew the scoped-VMEM stack at n_pad=131k).
* The model step is invoked ONCE per chunk on broadcastable planes
  instead of vmap: ``state[s]`` is a (1, NS, 1) column, ``f``/
  ``args[i]``/``ret[i]`` are (1, CH) rows, so the register/cas/mutex
  step bodies (pure ``xp.where`` arithmetic) vectorize to
  (1, NS, CH) with zero batching machinery. A numpy dry-run at build
  time proves the model's step really is plane-broadcastable (and
  rejects e.g. the FIFO's gather-based step), falling back to the
  scan otherwise.

Contract: the kernel returns, per seed chain, the op index chosen at
every step (``-1`` once the chain wedges) and the model state after
every step. The caller reconstructs the full per-step bitsets and
incremental fingerprint sums OUTSIDE the kernel with wide parallel
ops (an associative bitwise-or scan over one-hot word masks) -- those
tensors are (NS, R, B) and would blow VMEM, but XLA chews through
them at HBM bandwidth in a fixed number of large fused ops, which is
exactly what the sequential scan could not do. The reconstruction is
bit-identical to the ``lax.scan`` path (same greedy rule: first
eligible op in priority order whose model step succeeds; same WGL
eligibility ``unlinearized & invoke < min unlinearized return``).

Reference anchor: this replaces the hot loop of the engine the
reference outsources to knossos (jepsen/src/jepsen/checker.clj:199).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is optional at import time: the scan path never needs it
    from jax.experimental import pallas as pl
except Exception:  # noqa: BLE001 - pragma: no cover
    pl = None

INF32 = np.int32(2**31 - 1)

#: per-chunk op count for the in-kernel sweeps over the n ops; 16384
#: i32 lanes keep every (1, NS, CH) temporary at 512 KB while halving
#: the chunk-loop trip count vs 8192
CHUNK = 16384

#: conservative VMEM budget for resident inputs + temporaries; the
#: core is ~16 MB and Mosaic needs headroom for double buffering
VMEM_BUDGET = 11 << 20


class _KernelXP:
    """jnp facade handed to model step functions inside the kernel.
    Overrides ``where`` to rewrite bool-branch selects into mask
    algebra: ``where(c, a, b)`` with boolean branches (the mutex ok
    formula) otherwise lowers through an i8 vector Mosaic cannot
    truncate to i1 ("Unsupported target bitwidth for truncation")."""

    def __getattr__(self, name):
        return getattr(jnp, name)

    @staticmethod
    def where(c, x, y):
        xb = getattr(x, "dtype", None) == jnp.bool_ or isinstance(x, bool)
        yb = getattr(y, "dtype", None) == jnp.bool_ or isinstance(y, bool)
        if xb or yb:
            xm = x if xb else jnp.asarray(x) != 0
            ym = y if yb else jnp.asarray(y) != 0
            return (c & xm) | (~c & ym)
        return jnp.where(c, x, y)


_kernel_xp = _KernelXP()


class _Planes:
    """Indexable stand-in for a state/args/ret vector whose components
    are broadcastable planes: ``planes[i]`` is component i as a
    (1, NS, 1) or (1, CH) tensor. Step functions index components
    (``state[0]``, ``args[1]``) and read ``state.dtype``; nothing
    else is supported -- models that need more fail the build-time
    dry-run and keep the scan path."""

    def __init__(self, planes, dtype):
        self._planes = list(planes)
        self.dtype = dtype

    def __getitem__(self, i):
        return self._planes[i]

    def __len__(self):
        return len(self._planes)


def _fits_ch(NS, R, n, S, A, ch):
    resident = n * (3 + 2 * A) * 4          # invoke/ret/fop + args/rets
    mask = NS * n * 4                       # unpacked eligibility mask
    temps = NS * ch * (S + 6) * 4           # step planes + chunk masks
    outs = R * (128 + S * 128) * 4          # lane-padded output tiles
    return resident + mask + temps + outs <= VMEM_BUDGET


def _pick_chunk(NS, R, n, S, A):
    """Largest chunk whose temporaries fit alongside the resident
    arrays (smaller chunks trade loop-trip overhead for VMEM); None
    when even the smallest doesn't fit."""
    for ch in (CHUNK, CHUNK // 2, CHUNK // 4):
        ch = min(n, ch)
        if n % ch == 0 and ch % 32 == 0 and _fits_ch(NS, R, n, S, A,
                                                     ch):
            return ch
    return None


def _broadcastable_step(step_fn, S, A):
    """Numpy dry-run: does the model's step vectorize over broadcast
    planes with the right output shapes? (register/cas/mutex do --
    pure xp.where arithmetic; the FIFO's gather-based step does
    not.)"""
    ns, ch = 3, 8
    try:
        st = _Planes([np.zeros((1, ns, 1), np.int32) for _ in range(S)],
                     np.int32)
        f = np.zeros((1, ch), np.int32)
        a = _Planes([np.zeros((1, ch), np.int32) for _ in range(A)],
                    np.int32)
        r = _Planes([np.zeros((1, ch), np.int32) for _ in range(A)],
                    np.int32)
        st2, ok = step_fn(st, f, a, r, np)
        st2 = np.asarray(st2)
        ok = np.asarray(ok)
        if st2.shape[0] != S:
            return False
        np.broadcast_to(ok, (1, ns, ch))
        for i in range(S):
            np.broadcast_to(np.asarray(st2[i]), (1, ns, ch))
        return True
    except Exception:  # noqa: BLE001 - any failure means "not this path"
        return False


def build_fused_rollout(step_fn, NS, R, n, B, S, A, interpret=False):
    """Compile the fused rollout for one shape bundle, or return None
    when the shape/model cannot use it (caller keeps the scan path).

    Returns ``(prep, run)``:

        prep(invoke (n,), ret (n,), fop (n,), args (n,A), rets (n,A))
            -> opaque tuple of device columns (call ONCE per dispatch,
               outside the search while_loop)
        run(seed_lin (NS,B) u32, seed_st (NS,S) i32, seed_ok (NS,)
            bool, *prepped) -> (j (NS,R) i32, st (NS,R,S) i32)

    where ``j[s,t]`` is the op linearized by chain ``s`` at step ``t``
    (-1 from the step the chain wedges onward; dead-step states repeat
    the last live state, mirroring the scan's frozen carries).
    """
    if pl is None or n % 32 or B != n // 32:
        return None
    CH = _pick_chunk(NS, R, n, S, A)
    if CH is None:
        return None
    if not _broadcastable_step(step_fn, S, A):
        return None
    NCH = n // CH

    def prep(invoke, ret, fop, args, rets):
        pv = lambda x: x.reshape(NCH, CH)  # noqa: E731
        return ((pv(invoke), pv(ret), pv(fop))
                + tuple(pv(args[:, i]) for i in range(A))
                + tuple(pv(rets[:, i]) for i in range(A)))

    def kernel(*refs):
        (mask_in, seed_st, seed_ok, invoke, ret, fop) = refs[:6]
        acols = refs[6:6 + A]
        rcols = refs[6 + A:6 + 2 * A]
        j_out, st_out, mask = refs[6 + 2 * A:]
        del mask_in   # aliased to ``mask``: same buffer, initialized

        # global op index per mask element (ops are in natural =
        # priority order; no permutation needed with an unpacked mask)
        gid3 = (lax.broadcasted_iota(jnp.int32, (NCH, NS, CH), 0) * CH
                + lax.broadcasted_iota(jnp.int32, (NCH, NS, CH), 2))
        g2 = lax.broadcasted_iota(jnp.int32, (1, NS, CH), 2)

        def body(t, carry):
            st, alive = carry                # (1,NS,S), (1,NS,1) i32

            # pass A -- the WGL bound: min return over unlinearized ops
            def rm_chunk(c, rm):
                unl = mask[pl.ds(c, 1), :, :] != 0     # (1,NS,CH)
                retc = ret[pl.ds(c, 1), :]             # (1, CH)
                return jnp.minimum(rm, jnp.min(
                    jnp.where(unl, retc, INF32), axis=2,
                    keepdims=True))
            rm = lax.fori_loop(
                0, NCH, rm_chunk,
                jnp.full((1, NS, 1), INF32, jnp.int32))

            # pass B -- first eligible op in index (= priority) order
            # whose model step succeeds, plus its post-step state
            def choose_chunk(c, acc):
                jf, stacc = acc
                unl = mask[pl.ds(c, 1), :, :] != 0
                elig = unl & (invoke[pl.ds(c, 1), :] < rm)
                fc = fop[pl.ds(c, 1), :]
                ap = _Planes([a[pl.ds(c, 1), :] for a in acols],
                             jnp.int32)
                rp = _Planes([r[pl.ds(c, 1), :] for r in rcols],
                             jnp.int32)
                sp = _Planes([st[:, :, i:i + 1] for i in range(S)],
                             jnp.int32)
                st2, okc = step_fn(sp, fc, ap, rp, _kernel_xp)
                succ = elig & okc
                g = g2 + c * CH
                jloc = jnp.min(jnp.where(succ, g, n), axis=2,
                               keepdims=True)          # (1,NS,1)
                better = jloc < jf
                # i32 multiply, not a bool-mask where: Mosaic cannot
                # insert a minor dim on an i1 vector
                pick32 = jnp.where(succ & (g == jloc) & better, 1, 0)
                stn = jnp.concatenate(
                    [jnp.sum(st2[i] * pick32, axis=2, keepdims=True)
                     for i in range(S)], axis=2)       # (1,NS,S)
                return (jnp.minimum(jf, jloc),
                        jnp.where(better, stn, stacc))
            jf, stacc = lax.fori_loop(
                0, NCH, choose_chunk,
                (jnp.full((1, NS, 1), n, jnp.int32),
                 jnp.zeros((1, NS, S), jnp.int32)))

            # ``alive`` rides the loop as i32: Mosaic fails to
            # legalize an i1 vector as an scf.for carry
            took = (jf < n) & (alive != 0)
            # flip the chosen op out of the resident mask: one masked
            # full-tensor multiply (jf broadcast against the global
            # op-index iota)
            flip = (gid3 == jnp.minimum(jf, n - 1)) & took
            mask[:, :, :] = mask[:, :, :] * jnp.where(flip, 0, 1) \
                .astype(jnp.uint32)
            st = jnp.where(took, stacc, st)
            alive = jnp.where(took, 1, 0)
            j_out[pl.ds(t, 1), :, :] = jnp.where(took, jf, -1)
            st_out[pl.ds(t, 1), :, :] = st
            return st, alive

        lax.fori_loop(0, R, body, (seed_st[:, :, :], seed_ok[:, :, :]))

    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((R, NS, 1), jnp.int32),
                   jax.ShapeDtypeStruct((R, NS, S), jnp.int32),
                   jax.ShapeDtypeStruct((NCH, NS, CH), jnp.uint32)),
        input_output_aliases={0: 2},   # mask mutates in place
        interpret=interpret,
    )

    bit_idx = (np.arange(n) % 32).astype(np.uint32)

    def run(seed_lin, seed_st, seed_ok, *prepped):
        # unpack the seed bitsets to the (NCH, NS, CH) mask in XLA
        # (jnp.repeat and reshapes are fine OUTSIDE the kernel)
        wbits = jnp.repeat(seed_lin, 32, axis=1)[:, :n]      # (NS, n)
        unl = ((wbits >> bit_idx[None, :]) & jnp.uint32(1)) \
            ^ jnp.uint32(1)
        mask = jnp.transpose(unl.reshape(NS, NCH, CH), (1, 0, 2))
        j_rs, st_rs, _ = call(mask, seed_st[None, :, :],
                              seed_ok.astype(jnp.int32)[None, :, None],
                              *prepped)
        return (jnp.transpose(j_rs[:, :, 0], (1, 0)),
                jnp.transpose(st_rs, (1, 0, 2)))

    return prep, run
