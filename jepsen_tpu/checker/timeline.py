"""Renders an HTML timeline of a history (reference
jepsen/src/jepsen/checker/timeline.clj)."""

from __future__ import annotations

import html as _html
import logging

from .. import history as h
from .core import Checker

logger = logging.getLogger(__name__)

#: Maximum number of operations to render — keeps the timeline usable on
#: massive histories (timeline.clj:12-14).
OP_LIMIT = 10_000

TIMESCALE = 1e6       # nanoseconds per pixel
COL_WIDTH = 100       # pixels
GUTTER_WIDTH = 106    # pixels
HEIGHT = 16           # pixels

STYLESHEET = """\
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.12),
                          0 1px 2px rgba(0,0,0,0.24);
              transition: all 0.3s cubic-bezier(.25,.8,.25,1);
              overflow: hidden; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #6DB6FE; }
.op.info    { background: #FFAA26; }
.op.fail    { background: #FEB5DA; }
.op:target  { box-shadow: 0 14px 28px rgba(0,0,0,0.25),
                          0 10px 10px rgba(0,0,0,0.22); }
"""


def _style(m):
    return ";".join(f"{k}:{v}" for k, v in m.items())


def _pairs(history):
    """[invoke, completion] / [lone-info] pairs in history order
    (timeline.clj:38-57)."""
    invocations = {}
    out = []
    for op in history:
        t = op.get("type")
        p = op.get("process")
        if t == "invoke":
            invocations[p] = op
        elif t == "info" and p not in invocations:
            out.append([op])
        elif t in ("ok", "fail", "info"):
            inv = invocations.pop(p, None)
            if inv is not None:
                out.append([inv, op])
            else:
                out.append([op])
    # ops still in flight at the end of the history render as lone
    # invocations (.op.invoke bars)
    for inv in invocations.values():
        out.append([inv])
    return out


def _is_nemesis(op):
    return op.get("process") == "nemesis"


def _title(test, op, start, stop):
    parts = []
    if _is_nemesis(op):
        parts.append(f"Msg: {start.get('value')!r}")
    if stop is not None:
        dur = int((stop.get("time", 0) - start.get("time", 0)) / 1e6)
        parts.append(f"Dur: {dur} ms")
    parts.append(f"Err: {op.get('error')!r}")
    parts.append("")
    extra = {k: v for k, v in op.items()
             if k not in ("process", "type", "f", "index", "sub_index",
                          "value", "time")}
    parts.append("Op:\n" + "\n ".join(
        [f"{{process {op.get('process')}",
         f"type {op.get('type')}",
         f"f {op.get('f')}"] +
        [f"{k} {v!r}" for k, v in extra.items()] +
        [f"value {op.get('value')!r}}}"]))
    return "\n".join(parts)


def _body(op, start, stop):
    same = stop is not None and start.get("value") == stop.get("value")
    s = f"{op.get('process')} {op.get('f')} "
    if not _is_nemesis(op):
        s += _html.escape(repr(start.get("value")))
    if stop is not None and not same:
        s += "<br />" + _html.escape(repr(stop.get("value")))
    return s


def _pair_div(n_hist, test, process_index, pair):
    start = pair[0]
    stop = pair[1] if len(pair) > 1 else None
    op = stop or start
    p = start.get("process")
    s = {"width": COL_WIDTH,
         "left": GUTTER_WIDTH * process_index.get(p, 0),
         "top": HEIGHT * start.get("sub_index", 0)}
    if stop is not None and stop.get("type") == "info":
        s["height"] = HEIGHT * (n_hist + 1 - start.get("sub_index", 0))
    elif stop is not None:
        s["height"] = HEIGHT * max(1, (stop.get("sub_index", 0)
                                       - start.get("sub_index", 0)))
    else:
        s["height"] = HEIGHT
    idx = op.get("index")
    title = _html.escape(_title(test, op, start, stop), quote=True)
    return (f'<a href="#i{idx}">'
            f'<div class="op {op.get("type")}" id="i{idx}" '
            f'style="{_style(s)}" title="{title}">'
            f'{_body(op, start, stop)}</div></a>')


def _process_index(history):
    """Maps processes to columns: clients sorted first, then named
    processes like the nemesis (timeline.clj:169-175)."""
    procs = []
    for op in history:
        p = op.get("process")
        if p not in procs:
            procs.append(p)
    ints = sorted(p for p in procs if isinstance(p, int))
    names = sorted((p for p in procs if not isinstance(p, int)), key=str)
    return {p: i for i, p in enumerate(ints + names)}


class _TimelineHtml(Checker):
    def check(self, test, hist, opts=None):
        opts = opts or {}
        hist = h.complete(h.ensure_indexed(hist))
        for i, op in enumerate(hist):
            op["sub_index"] = i
        pairs = _pairs(hist)
        pair_count = len(pairs)
        truncated = pair_count > OP_LIMIT
        pairs = pairs[:OP_LIMIT]
        pindex = _process_index(hist)
        key = opts.get("history-key")
        divs = "\n".join(_pair_div(len(hist), test, pindex, pr)
                         for pr in pairs)
        warning = (f'<div class="truncation-warning">Showing only '
                   f"{OP_LIMIT} of {pair_count} operations in this "
                   f"history.</div>" if truncated else "")
        doc = f"""<html><head><style>{STYLESHEET}</style></head>
<body><h1>{_html.escape(str(test.get('name')))} key {key}</h1>
{warning}
<div class="ops">
{divs}
</div></body></html>"""
        try:
            from .. import store
            p = store.make_path(test, opts.get("subdirectory"),
                                "timeline.html")
            with open(p, "w") as f:
                f.write(doc)
        except (AssertionError, OSError):
            logger.debug("timeline: no store directory; skipping write")
        return {"valid": True}


def html():
    return _TimelineHtml()
