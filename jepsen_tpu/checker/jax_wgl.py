"""Batched Wing-Gong-Lowe linearizability search on TPU.

This is the TPU-native replacement for the engine the reference outsources to
knossos (jepsen/project.clj:14, dispatched from jepsen/src/jepsen/checker.clj:
199-202). The sequential oracle in wgl.py defines the semantics; this module
runs the same search as a *batched branch-and-bound* entirely on device, one
``lax.while_loop`` per check (SURVEY.md section 7: "keep the whole B&B loop in
one lax.while_loop").

Design (everything fixed-shape so XLA traces once):

* A **configuration** is (bitset of linearized ops, model state). Bitsets are
  ``uint32[B]`` words, B = ceil(n/32); states are ``int32[S]``.
* The search keeps a DFS **stack** of configurations in HBM
  (``buf_lin: uint32[O,B]``, ``buf_state: int32[O,S]``, scalar ``top``).
* Each iteration pops the top ``W`` configs (a *frontier*), expands all of
  them at once:
    - unlinearized-op bits are unpacked with a word gather + shift,
    - the WGL rule (op i may linearize next iff ``invoke[i] < min`` return
      over unlinearized ops) becomes a masked row-min + compare,
    - up to ``C`` candidate ops per config are selected with ``top_k``
      (C is the history's max point-concurrency, a static bound on how many
      ops can ever be eligible at once),
    - the model step function is vmapped over (frontier, candidate).
* **Dedup** uses a device-resident open-addressing hash table of 64-bit
  fingerprints (two independent 32-bit multiply-shift hashes over the config
  words). The table is insert-only with linear probing; scatter races between
  distinct keys are resolved by re-gathering ("landed?") and probing on.
  Crucially the table is *best-effort in the safe direction*: a failed insert
  only means the config may be re-explored (children strictly grow the
  bitset, so the search still terminates). A false "seen" requires a 64-bit
  fingerprint collision (~2^-64 per pair); invalid verdicts can be confirmed
  exactly with the sequential oracle via ``confirm=...``.
* New configs are pushed back on the stack with a cumsum scatter; stack
  overflow sets a ``dropped`` flag which degrades an "exhausted" verdict to
  ``unknown`` (success verdicts are unaffected -- dropping work can never
  manufacture a linearization).
* The loop ends on: success (a child linearizes every ``ok`` op), exhaustion
  (stack empty), or budget (iteration cap). Witness for invalid verdicts:
  the deepest config reached (max linearized-ok count) is tracked on device
  and decoded on host.

The same compiled search is reused across histories with identical padded
shapes (shapes are bucketed to powers of two for reuse). The search body is
pure, so a vmapped variant over a leading key axis (jepsen.independent-style
multi-key checks) builds on the same kernel.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..history import INF_TIME
from ..obs import phases as obs_phases
from ..obs import search as obs_search

INF32 = np.int32(2**31 - 1)

#: linear-probe length for the dedup hash table (4 keeps the probe
#: gather -- the kernel's dominant cost, see PROFILE.md -- half the
#: width of the original 8 at no measured dedup-quality cost)
PROBES = 4

#: deepest-distinct-config witness slots per key. knossos returns up to
#: 10 stuck :configs (reference checker.clj:213-216 truncates a list);
#: round 3 tracked exactly one, so the truncation guard could never
#: fire. 8 slots make the masked-reduction update one vector op wide.
TOPK = 8


# ---------------------------------------------------------------------------
# host-side helpers

def max_point_concurrency(invoke_idx, return_idx):
    """Static bound C on WGL candidates: the max, over return points t, of
    |{i : invoke_i < t <= return_i}| (info ops stay open forever). Every
    candidate set at any reachable configuration is contained in one such
    interval stab (see module docstring). Single O(n log n) event sweep."""
    n = len(invoke_idx)
    if n == 0:
        return 1
    finite = return_idx < INF_TIME
    if not finite.any():
        return n
    # +1 just after each invoke, -1 just after each finite return; the open
    # count sampled at a return point t is |{i: invoke_i < t <= return_i}|.
    # Returns sort before invokes at equal positions so an invoke AT t is
    # not counted (the stab requires invoke_i strictly < t).
    events = sorted(
        [(int(t), 1, +1) for t in invoke_idx] +
        [(int(t), 0, -1) for t in return_idx[finite]])
    best, open_ops = 1, 0
    for _t, kind, delta in events:
        if kind == 0:  # sample before closing the op at its return point
            best = max(best, open_ops)
        open_ops += delta
    return min(best, n)


def _hash_keys(length, seed=0x9E3779B9):
    """Two vectors of random odd uint32 multipliers (multiply-shift
    universal hashing over config words)."""
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    k = rng.randint(0, 2**31, size=(2, length)).astype(np.uint32)
    return (k[0] * 2 + 1), (k[1] * 2 + 1)


def _mix32(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return h


# status codes
RUNNING, VALID = np.int32(0), np.int32(1)


#: carry tuple element indices with a per-key leading axis (the rest are
#: shared per table-group); the batch checker's compaction gathers these
KEYED = (0, 1, 2, 3, 5, 6, 7, 8, 9, 10, 11)

#: version tag hashed into checkpoint fingerprints: bump whenever the
#: carry layout or table format changes, so snapshots from an older
#: build are cleanly ignored instead of crashing the resume
CARRY_LAYOUT = (f"carry-v6:tab-interleaved,probes{PROBES},topk{TOPK},"
                "incfp,tfail")

#: carry tuple indices (v6 layout; single source of truth for every
#: consumer -- hardcoded copies desynchronized once already when v2's
#: split tables were merged). v5 adds buf_fp: per-config PARTIAL HASH
#: SUMS over the lin bitset, updated O(1) per child instead of re-
#: hashing all B words per lane per iteration (the profiled dominant
#: cost at 100k+ ops -- see PROFILE.md round 4)
#: ... v6 appends tfail: per-table-group count of configs that WANTED a
#: dedup-table insert but found no empty slot in their probe window --
#: safe (only re-exploration) but a throughput tell: a saturated table
#: is otherwise indistinguishable from a slow search (VERDICT r4 #5)
(IDX_BUF_LIN, IDX_BUF_STATE, IDX_BUF_FP, IDX_TOP, IDX_TAB, IDX_DROPPED,
 IDX_STATUS, IDX_EXPLORED, IDX_BEST_DEPTH, IDX_BEST_LIN, IDX_BEST_STATE,
 IDX_ITS, IDX_IT, IDX_CLAIM, IDX_TFAIL) = range(15)

#: number of carry tuple elements (shard_map specs, checkpoint loaders)
N_CARRY = IDX_TFAIL + 1


@functools.lru_cache(maxsize=64)
def _build_search(step_fn, K, n, B, S, C, A, W, O, T, G=1, R=None,
                  NS=None, rollout_kernel="auto", axis_name=None,
                  axis_size=1, steal=16):
    """Compile the search for one shape bundle with an explicit key-batch
    axis K (jepsen.independent keys, BASELINE config 2). Returns jitted

        init_carry(init_states (K,S)) -> carry
        run_chunk(carry, invoke, ret, f, args, rets, ok_words, salt, bound)
          -> carry

    The K axis is batched *manually* (not vmap): all keys share one dedup
    table (fingerprints salted by key id) and one flat scatter per
    structure per iteration -- vmapping the table ops made XLA:TPU
    serialize the scatters per key and copy the (K,T) tables every
    iteration, which dominated runtime.

    Carry layout (see KEYED): buf_lin (K,O,B) u32, buf_state (K,O,S)
    i32, buf_fp (K,O,2) u32 (per-config incremental fingerprint sums
    over the lin bitset; v5), top (K,) i32, tab (G,T,2) u32 shared
    (h1/h2 fingerprint pairs
    interleaved so one gather fetches both words -- the two separate
    tables cost a second 590k-row gather per iteration, the kernel's
    single biggest op), dropped (K,) bool, status (K,)
    i32, explored (K,) i32, best_depth (K,TOPK) i32, best_lin (K,TOPK,B)
    u32, best_state (K,TOPK,S) i32 (TOPK distinct deepest-config witness
    slots, knossos's multi-:configs parity), its (K,) i32, it (G,) i32,
    claim (G,Tc) i32 shared, tfail (G,) i32 shared (dedup insert-failure
    count; v6). G is the table-group count: 1 locally; under shard_map over a
    mesh, G = mesh size so each device shard sees exactly one group (the
    body always indexes group 0 of its local view). Buffers depend
    on O/B/S/T but NOT on W, so kernel variants with
    different frontier widths are interchangeable mid-search (the batch
    checker widens W once stragglers remain).
    """
    word_idx = np.arange(n, dtype=np.int32) // 32          # (n,)
    bit_idx = (np.arange(n, dtype=np.int32) % 32).astype(np.uint32)
    k1, k2 = _hash_keys(B + S + 1)                         # +1: key salt
    arange_n = np.arange(n, dtype=np.int32)
    arange_W = np.arange(W, dtype=np.int32)
    arange_B = np.arange(B, dtype=np.uint32)
    arange_C = np.arange(C, dtype=np.int32)
    arange_K = np.arange(K, dtype=np.int32)
    M = W * C
    KM = K * M
    if R is None:
        # Greedy-rollout chain length per iteration. Each rollout step is
        # a handful of tiny sequential device ops, so the chain only pays
        # for itself once advancing R levels per iteration beats plain
        # branch-and-bound; only trivially short histories skip it.
        # (Round 2 used a 256-op cutoff, which left the multi-key batch
        # -- 200-op histories per key -- grinding one depth level per
        # iteration; lowering it to 64 cut rung 2 device time ~3x.)
        # SINGLE-KEY searches run deep chains (R=1024): a deep rollout
        # amortizes the expensive expansion/dedup iteration over 4x
        # the depth, and the win holds on BOTH rollout kernels (A/B,
        # rung-0 shapes: 144k-request cas 64.9 s / 1102 iterations at
        # R=256 -> 29.2 s / 264 at R=1024 on the scan path; mutex
        # 224k-request scan-R256 timed out at 90 s where fused-R1024
        # decided in 28.3 s). Wedge-prone histories pay more wall per
        # iteration for chains that die early, but those searches were
        # undecidable at R=256 too. The BATCH path keeps R=256: its
        # chip is filled by the key axis and (K, NS*R) push lanes
        # scale with R.
        R = 0 if n <= 64 else min(1024 if K == 1 else 256, n)
    if NS is None:
        # Greedy chains rolled per iteration, for SINGLE-KEY searches
        # only. On the latency-bound single-key chain (PROFILE.md rung
        # 5: 67 us/micro-step on O(n) values) widening each micro-step
        # to NS seeds is nearly free and multiplies depth progress
        # wherever one chain wedges on a plateau: measured 2.7x on a
        # 58.8k-op (112k requested) mutex, 27.7 s -> 10.3 s at NS=8.
        # On the key batch the O(K*NS*n) step work is no longer
        # latency-shadowed and NS=8 measured ~1.4x SLOWER (256-key
        # rung: 4.7 s -> 6.7 s), so the batch path pins NS=1
        # explicitly (keyshard.py) -- this K==1 default only governs
        # genuine single-key searches. Capped so the (NS, n, S)
        # rollout tensor stays ~<=256 MB: big queue states otherwise
        # build multi-GB intermediates that crash the TPU worker
        # (observed on a 9k-op FIFO search).
        NS = max(1, min(8, (64 << 20) // max(1, n * S))) if K == 1 else 1
    if R and K * NS * n * S > (256 << 20):
        # even at the chosen NS the rollout's (K, NS, n, S) step tensor
        # would exceed ~1 GB (huge padded states x many keys): drop the
        # rollout rather than risk the worker -- the search still
        # progresses one depth level per iteration
        R, NS = 0, 1

    # Fused Pallas rollout (VERDICT r4 #1): single-key searches only --
    # the chain is their latency bottleneck (~8 ms busy / ~60 ms wall
    # per iteration, PROFILE.md). "auto" engages it on real TPU when
    # the shape fits VMEM; "pallas" forces it (interpret mode off-TPU,
    # for tests); "scan" keeps the measured lax.scan path (the batch
    # checker pins this -- its chip is filled by the key axis).
    fused = None
    if K == 1 and R and rollout_kernel != "scan":
        on_tpu = jax.default_backend() == "tpu"
        if rollout_kernel == "pallas" or on_tpu:
            from . import pallas_rollout
            fused = pallas_rollout.build_fused_rollout(
                step_fn, NS, R, n, B, S, A, interpret=not on_tpu)
    ML = M + NS * R
    KML = K * ML
    Tc = 1 << 16   # twin-claim scratch; fixed so carries are W-independent

    step_one = lambda st, f, a, r: step_fn(st, f, a, r, jnp)  # noqa: E731
    # vmap over candidates (state shared), frontier rows, then keys
    step_vvv = jax.vmap(jax.vmap(jax.vmap(
        step_one, in_axes=(None, 0, 0, 0)), in_axes=(0, 0, 0, 0)),
        in_axes=(0, 0, 0, 0))
    # vmap over all n ops from one state, then NS seed chains (ops
    # shared), then keys (rollout)
    step_vn = jax.vmap(jax.vmap(jax.vmap(
        step_one, in_axes=(None, 0, 0, 0)), in_axes=(0, None, None, None)),
        in_axes=(0, 0, 0, 0))

    k1j, k2j = jnp.asarray(k1), jnp.asarray(k2)

    # Fingerprints are mix(sum_i mix(word_i ^ key_i)) over the config's
    # (lin bitset, state, salt) words. Each word is xored with a
    # per-position random key and passed through the bijective
    # finalizer _before_ summing -- a plain keyed linear sum (sum of
    # w*k mod 2^32) is catastrophically weak in the high bits: configs
    # differing only in bit 31 of two different words always collide,
    # since 2^31*(k_i - k_j) = 0 mod 2^32 for odd keys, and such
    # sibling configs are extremely common in this search.
    #
    # The LIN part of the inner sum is carried per config (buf_fp) and
    # updated O(1) per child -- every child flips exactly one bitset
    # word, and the sum is mod-2^32 linear, so the incremental value
    # is bit-identical to a from-scratch hash. Re-hashing all B words
    # for every lane was the profiled dominant per-iteration cost at
    # 100k+ ops (PROFILE.md round 4: ~0.5-1 GB of hash work per
    # iteration at n=262k). State words still hash fresh (they change
    # wholesale each step; O(S) per lane).

    def finalize_fp(sum1, sum2, st, saltv):
        """Combine incremental lin-sums (leading shape L) with freshly
        hashed state words st (L, S) and the per-key salt (L,) into
        the table fingerprint pair."""
        stw = st.astype(jnp.uint32)
        s1 = sum1 + jnp.sum(_mix32(stw ^ k1j[B:B + S]), axis=-1,
                            dtype=jnp.uint32) + _mix32(saltv
                                                       ^ k1j[B + S])
        s2 = sum2 + jnp.sum(_mix32(stw ^ k2j[B:B + S]), axis=-1,
                            dtype=jnp.uint32) + _mix32(saltv
                                                       ^ k2j[B + S])
        h1 = _mix32(s1)
        h2 = _mix32(s2)
        # reserve (0,0): the empty table slot
        return h1, jnp.where((h1 == 0) & (h2 == 0), jnp.uint32(1), h2)

    def lin_deltas(oldw, neww, wsel):
        """Sum deltas for flipping word index ``wsel`` (any shape) from
        oldw to neww: mix(new^k_w) - mix(old^k_w), mod 2^32."""
        kw1 = jnp.take(k1j, wsel)
        kw2 = jnp.take(k2j, wsel)
        return (_mix32(neww ^ kw1) - _mix32(oldw ^ kw1),
                _mix32(neww ^ kw2) - _mix32(oldw ^ kw2))

    def body(carry, consts):
        (buf_lin, buf_state, buf_fp, top, tabg, dropped, status,
         explored, best_depth, best_lin, best_state, its, it,
         claimg, tfailg) = carry
        tab, claim = tabg[0], claimg[0]
        # fx: the fused rollout's pre-permuted op columns (empty tuple
        # when the scan path is active), built once per dispatch in
        # run_chunk -- never per iteration
        (invoke, ret, fop, args, rets, ok_words, salt, bound, fx) = consts
        running = (status == RUNNING) & (top > 0)             # (K,)

        # -- pop per-key frontiers ------------------------------------------
        # The stack is a RING over O slots with an absolute top counter:
        # overflow overwrites the OLDEST (shallowest) entries rather than
        # dropping the newest. Deep rollout chains must always land --
        # dropping them stalls the search at a plateau forever. Any
        # overwrite forfeits exhaustion proofs only (dropped flag);
        # popping a slot that was overwritten yields some other real
        # config, which is sound to explore.
        start = jnp.where(running, jnp.maximum(top - W, 0), top)
        idx = start[:, None] + arange_W[None, :]              # (K,W)
        fvalid = (idx < top[:, None]) & running[:, None]
        gidx = (arange_K[:, None] * O + idx % O).reshape(KM // C)
        lin = jnp.take(buf_lin.reshape(K * O, B), gidx,
                       axis=0).reshape(K, W, B)
        state = jnp.take(buf_state.reshape(K * O, S), gidx,
                         axis=0).reshape(K, W, S)
        fsum = jnp.take(buf_fp.reshape(K * O, 2), gidx,
                        axis=0).reshape(K, W, 2)
        top = start

        # -- candidate selection (the WGL rule) -----------------------------
        # word_idx[i] == i // 32 exactly, so the word gather is a
        # gather-free repeat + slice (TPU gathers are the kernel's
        # slowest ops; see PROFILE.md)
        wbits = jnp.repeat(lin, 32, axis=2)[:, :, :n]          # (K,W,n)
        unlin = ((wbits >> bit_idx[None, None, :]) & jnp.uint32(1)) == 0
        rmin = jnp.min(jnp.where(unlin, ret[:, None, :], INF32), axis=2)
        cand = unlin & (invoke[:, None, :] < rmin[..., None]) \
            & fvalid[..., None]
        # First C candidate positions per row without top_k (which lowers
        # to per-row sorts on TPU). Ops arrive ALREADY RENUMBERED into
        # linearization-priority order (host-side argsort by the model
        # hint / earliest deadline, see _priority_order), so "first C by
        # index" IS "best C by priority" -- the kernel stays all-static
        # index math with no per-iteration gathers.
        rank = jnp.cumsum(cand.astype(jnp.int32), axis=2)     # (K,W,n)
        if n * C <= 32768:
            # small problems: a dense one-hot reduction beats a dynamic
            # scatter (TPU scatters have high fixed cost)
            onehot = (rank[..., None]
                      == (arange_C[None, None, None, :] + 1)) \
                & cand[..., None]                             # (K,W,n,C)
            ci = jnp.sum(
                onehot * arange_n[None, None, :, None],
                axis=2).astype(jnp.int32)
        else:
            tgt = jnp.where(cand & (rank <= C), rank - 1, C)
            row = jnp.broadcast_to(
                (arange_K[:, None] * W + arange_W[None, :])[..., None],
                (K, W, n))
            ops_b = jnp.broadcast_to(arange_n[None, None, :], (K, W, n))
            ci = jnp.zeros((K * W, C), jnp.int32) \
                .at[row.reshape(-1), tgt.reshape(-1)] \
                .set(ops_b.reshape(-1), mode="drop").reshape(K, W, C)
        cvalid = arange_C[None, None, :] < rank[..., -1:]     # (K,W,C)

        # -- model step over (key, frontier, candidate) ---------------------
        gci = (arange_K[:, None, None] * n + ci).reshape(KM)
        fc = jnp.take(fop.reshape(K * n), gci).reshape(K, W, C)
        ac = jnp.take(args.reshape(K * n, A), gci,
                      axis=0).reshape(K, W, C, A)
        rc = jnp.take(rets.reshape(K * n, A), gci,
                      axis=0).reshape(K, W, C, A)
        st2, okf = step_vvv(state, fc, ac, rc)            # (K,W,C,S),(K,W,C)
        st2 = st2.astype(jnp.int32)

        wselc = jnp.take(word_idx, ci)                        # (K,W,C)
        bitc = jnp.uint32(1) << jnp.take(bit_idx, ci)
        addmask = jnp.where(
            arange_B[None, None, None, :]
            == wselc[..., None].astype(jnp.uint32),
            bitc[..., None], jnp.uint32(0))                   # (K,W,C,B)
        lin2 = lin[:, :, None, :] | addmask
        # incremental fingerprint sums: each child flips exactly one
        # bitset word (oldw -> oldw|bit); one gather per lane replaces
        # a full B-word re-hash
        oldw = jnp.take_along_axis(lin, wselc, axis=2)        # (K,W,C)
        d1, d2 = lin_deltas(oldw, oldw | bitc, wselc)
        sum1c = fsum[..., 0][:, :, None] + d1                 # (K,W,C)
        sum2c = fsum[..., 1][:, :, None] + d2

        child_valid = cvalid & okf & fvalid[..., None]
        okw = ok_words[:, None, None, :]
        done = jnp.all((lin2 & okw) == okw, axis=-1)
        status = jnp.where(
            running & jnp.any(child_valid & done, axis=(1, 2)),
            VALID, status)

        # -- witness tracking ----------------------------------------------
        # Row selection is a first-occurrence one-hot + masked SUM, not
        # argmax + take_along_axis: the per-key gathers lowered to
        # serialized scalar-memory fusions costing ~15 ms/iteration at
        # K=256 (profiled; see PROFILE.md), the masked reduction is a
        # plain vector op. Each update site inserts the iteration's best
        # candidate into TOPK distinct deepest-config slots.
        def topk_insert(bd3, bl3, bs3, cd, cl, cs):
            """Insert one candidate config per key (cd (K,), cl (K,B),
            cs (K,S)) into the TOPK distinct-deepest slots. Eviction
            replaces a min-depth slot; ``>=`` admits equal-depth DISTINCT
            configs (a stuck frontier is many configs at one max depth),
            and a max-depth slot can only be evicted by an equally deep
            distinct config, so the deepest witness is never lost."""
            dup = ((bl3 == cl[:, None, :]).all(-1)
                   & (bs3 == cs[:, None, :]).all(-1)
                   & (bd3 >= 0)).any(axis=1)                  # (K,)
            mind = jnp.min(bd3, axis=1)                       # (K,)
            do = (cd >= 0) & (cd >= mind) & ~dup
            sloteq = bd3 == mind[:, None]                     # (K,TOPK)
            spk = (sloteq
                   & (jnp.cumsum(sloteq.astype(jnp.int32), axis=1) == 1)
                   & do[:, None])
            return (jnp.where(spk, cd[:, None], bd3),
                    jnp.where(spk[..., None], cl[:, None, :], bl3),
                    jnp.where(spk[..., None], cs[:, None, :], bs3))

        depth = lax.population_count(lin2 & okw).sum(axis=-1) \
            .astype(jnp.int32)
        depth = jnp.where(child_valid, depth, -1).reshape(K, M)
        bd = jnp.max(depth, axis=1)                           # (K,)
        lin2k = lin2.reshape(K, M, B)
        st2k = st2.reshape(K, M, S)
        eq = depth == bd[:, None]
        pick = eq & (jnp.cumsum(eq.astype(jnp.int32), axis=1) == 1)
        cand_lin = jnp.sum(jnp.where(pick[..., None], lin2k, 0), axis=1,
                           dtype=jnp.uint32)                  # (K,B)
        cand_st = jnp.sum(jnp.where(pick[..., None], st2k, 0), axis=1,
                          dtype=jnp.int32)                    # (K,S)
        best_depth, best_lin, best_state = topk_insert(
            best_depth, best_lin, best_state, bd, cand_lin, cand_st)

        # -- greedy rollout -------------------------------------------------
        # Branch-and-bound advances depth at most 1 per iteration, and
        # iterations are latency-bound (~ms), so a 10k-op history would
        # need 10k dispapched iterations. Instead, from the deepest fresh
        # child, follow the greedy chain -- always linearize the eligible
        # op with the EARLIEST DEADLINE whose model step succeeds -- for
        # up to R steps inside this same kernel (one lax.scan; per-step
        # work is O(K*n), trivial). On valid histories the chain usually
        # just walks the witness, advancing depth R per iteration; the
        # chain configs are pushed (deepest on top) and deduped like any
        # others, so backtracking still explores alternatives around any
        # step the greedy choice got wrong.
        # Seed from the top-NS children in DFS order: the deepest popped
        # parent's best-priority surviving children first -- exactly what
        # plain DFS would pop next (parents are popped in w-ascending =
        # shallowest-first order, candidates in c-ascending = priority
        # order). Seeding from argmax-depth instead ties toward the
        # FIRST max-depth lane, i.e. some shallow parent's plateau
        # child, whose state wedges the chain immediately on brittle
        # models (FIFO: an equal-depth config with the wrong queue
        # contents is a dead end; measured as the chain advancing ~1
        # level/iteration). NS > 1 chains diversify around exactly the
        # choice points where one greedy chain wedges: the seeds differ
        # in which candidate linearizes at the current deepest level.
        # Selection is NS unrolled masked-max reductions over (K, M) --
        # no sort, no gather (dfs_rank values are distinct per lane, so
        # each "== smax" one-hot hits exactly one lane).
        dfs_rank = (arange_W[:, None] * C
                    + (C - 1 - arange_C)[None, :]).reshape(M)   # (M,)
        score = jnp.where(child_valid.reshape(K, M),
                          dfs_rank[None, :], -1)
        sum1k = sum1c.reshape(K, M)
        sum2k = sum2c.reshape(K, M)
        seed_lin_l, seed_st_l, seed_ok_l = [], [], []
        seed_s1_l, seed_s2_l = [], []
        for _s in range(NS):
            smax = jnp.max(score, axis=1)                      # (K,)
            ok_s = running & (smax >= 0)
            seq = score == smax[:, None]
            spick = seq & (jnp.cumsum(seq.astype(jnp.int32), axis=1)
                           == 1) & ok_s[:, None]               # (K,M)
            seed_lin_l.append(jnp.sum(
                jnp.where(spick[..., None], lin2k, 0), axis=1,
                dtype=jnp.uint32))
            seed_st_l.append(jnp.sum(
                jnp.where(spick[..., None], st2k, 0), axis=1,
                dtype=jnp.int32))
            seed_s1_l.append(jnp.sum(jnp.where(spick, sum1k, 0),
                                     axis=1, dtype=jnp.uint32))
            seed_s2_l.append(jnp.sum(jnp.where(spick, sum2k, 0),
                                     axis=1, dtype=jnp.uint32))
            seed_ok_l.append(ok_s)
            score = jnp.where(spick, -1, score)
        seed_lin = jnp.stack(seed_lin_l, axis=1)               # (K,NS,B)
        seed_st = jnp.stack(seed_st_l, axis=1)                 # (K,NS,S)
        seed_ok = jnp.stack(seed_ok_l, axis=1)                 # (K,NS)
        seed_s1 = jnp.stack(seed_s1_l, axis=1)                 # (K,NS)
        seed_s2 = jnp.stack(seed_s2_l, axis=1)                 # (K,NS)

        def roll_step(rc_, _):
            lin_r, st_r, alive, s1_r, s2_r = rc_            # (K,NS,B) ...
            wb = jnp.repeat(lin_r, 32, axis=2)[:, :, :n]      # (K,NS,n)
            unl = ((wb >> bit_idx[None, None, :]) & jnp.uint32(1)) == 0
            rm = jnp.min(jnp.where(unl, ret[:, None, :], INF32),
                         axis=2)                              # (K,NS)
            elig = unl & (invoke[:, None, :] < rm[..., None])
            stn, okn = step_vn(st_r, fop, args, rets)       # (K,NS,n,S)
            succ = elig & okn & alive[..., None]
            # first succeeding op in index order = best priority (ops are
            # pre-sorted by the linearization hint)
            j = jnp.argmax(succ, axis=2).astype(jnp.int32)    # (K,NS)
            took = succ.any(axis=2)
            wsel = jnp.take(word_idx, j)
            bitj = jnp.uint32(1) << jnp.take(bit_idx, j)
            bmask = (arange_B[None, None, :]
                     == wsel[..., None].astype(jnp.uint32))
            newlin = lin_r | jnp.where(
                bmask & took[..., None], bitj[..., None],
                jnp.uint32(0))
            newst = jnp.where(
                took[..., None],
                jnp.take_along_axis(stn, j[..., None, None],
                                    axis=2)[:, :, 0]
                .astype(jnp.int32), st_r)
            oldw = jnp.take_along_axis(lin_r, wsel[..., None],
                                       axis=2)[..., 0]        # (K,NS)
            d1, d2 = lin_deltas(oldw, oldw | bitj, wsel)
            s1_r = jnp.where(took, s1_r + d1, s1_r)
            s2_r = jnp.where(took, s2_r + d2, s2_r)
            alive = alive & took
            return ((newlin, newst, alive, s1_r, s2_r),
                    (newlin, newst, alive, s1_r, s2_r))

        if R and fused is not None:
            # one kernel rolls all NS chains R steps with bitsets and
            # states in VMEM; the per-step bitsets and incremental
            # fingerprint sums are reconstructed OUT here with wide
            # parallel ops (associative OR-scan over one-hot word
            # masks) -- bit-identical to the scan path's carries, but
            # without R sequential dispatch dependencies
            j_rs, st_rs = fused[1](seed_lin[0], seed_st[0], seed_ok[0],
                                   *fx)
            jt = j_rs[None]                               # (1,NS,R)
            took = jt >= 0
            jc = jnp.maximum(jt, 0)
            wselr = jnp.take(word_idx, jc)                # (1,NS,R)
            bitr = jnp.uint32(1) << jnp.take(bit_idx, jc)
            onehotw = (arange_B[None, None, None, :]
                       == wselr[..., None].astype(jnp.uint32))
            masks = jnp.where(onehotw & took[..., None],
                              bitr[..., None], jnp.uint32(0))
            cum = lax.associative_scan(jnp.bitwise_or, masks, axis=2)
            ch_lin = seed_lin[:, :, None, :] | cum        # (1,NS,R,B)
            prev_lin = jnp.concatenate(
                [seed_lin[:, :, None, :], ch_lin[:, :, :-1]], axis=2)
            # gather-free oldw: masked reduce over the B axis (per-key
            # take_along_axis lowered to serialized scalar fusions
            # once already -- see the witness-tracking note above)
            oldw = jnp.sum(jnp.where(onehotw, prev_lin, jnp.uint32(0)),
                           axis=3, dtype=jnp.uint32)      # (1,NS,R)
            d1r, d2r = lin_deltas(oldw, oldw | bitr, wselr)
            ch_s1 = seed_s1[:, :, None] + jnp.cumsum(
                jnp.where(took, d1r, jnp.uint32(0)), axis=2,
                dtype=jnp.uint32)
            ch_s2 = seed_s2[:, :, None] + jnp.cumsum(
                jnp.where(took, d2r, jnp.uint32(0)), axis=2,
                dtype=jnp.uint32)
            ch_st = st_rs[None]                           # (1,NS,R,S)
            ch_alive = took
            # flip the seed axis so the BEST seed's chain flattens to
            # the LAST lanes (top of stack), as in the scan path below
            ch_lin = ch_lin[:, ::-1].reshape(K, NS * R, B)
            ch_st = ch_st[:, ::-1].reshape(K, NS * R, S)
            ch_alive = ch_alive[:, ::-1].reshape(K, NS * R)
            ch_s1 = ch_s1[:, ::-1].reshape(K, NS * R)
            ch_s2 = ch_s2[:, ::-1].reshape(K, NS * R)
        elif R:
            # unroll: the chain is LATENCY-bound (PROFILE.md: ~26 us
            # busy vs ~175 us wall per micro-step at n=131k -- the gap
            # is loop-boundary dispatch latency); unrolling fuses 8
            # micro-steps per loop iteration so XLA schedules across
            # step boundaries
            _, (ch_lin, ch_st, ch_alive, ch_s1, ch_s2) = lax.scan(
                roll_step, (seed_lin, seed_st, seed_ok, seed_s1,
                            seed_s2), None, length=R, unroll=8)
            # (R,K,NS,*) -> (K,NS,R,*); flip the seed axis so the BEST
            # seed's chain flattens to the LAST lanes (= top of stack,
            # its deepest config on the very top), then fold seeds into
            # one chain-lane axis of NS*R
            ch_lin = jnp.transpose(ch_lin, (1, 2, 0, 3))[:, ::-1] \
                .reshape(K, NS * R, B)
            ch_st = jnp.transpose(ch_st, (1, 2, 0, 3))[:, ::-1] \
                .reshape(K, NS * R, S)
            ch_alive = jnp.transpose(ch_alive, (1, 2, 0))[:, ::-1] \
                .reshape(K, NS * R)
            ch_s1 = jnp.transpose(ch_s1, (1, 2, 0))[:, ::-1] \
                .reshape(K, NS * R)
            ch_s2 = jnp.transpose(ch_s2, (1, 2, 0))[:, ::-1] \
                .reshape(K, NS * R)

        if R:
            okw2 = ok_words[:, None, :]
            ch_done = jnp.all((ch_lin & okw2) == okw2, axis=-1) & ch_alive
            status = jnp.where(running & ch_done.any(axis=1), VALID,
                               status)
            ch_depth = jnp.where(
                ch_alive,
                lax.population_count(ch_lin & okw2).sum(-1)
                .astype(jnp.int32),
                -1)                                           # (K,NS*R)
            cbd = jnp.max(ch_depth, axis=1)
            ceq = ch_depth == cbd[:, None]
            cpick = ceq & (jnp.cumsum(ceq.astype(jnp.int32), axis=1)
                           == 1)                              # (K,NS*R)
            cc_lin = jnp.sum(jnp.where(cpick[..., None], ch_lin, 0),
                             axis=1, dtype=jnp.uint32)
            cc_st = jnp.sum(jnp.where(cpick[..., None], ch_st, 0),
                            axis=1, dtype=jnp.int32)
            best_depth, best_lin, best_state = topk_insert(
                best_depth, best_lin, best_state, cbd, cc_lin, cc_st)

        # -- combined lanes (expansion then chain, natural order) -----------
        # Stack positions are assigned ARITHMETICALLY below so lane data
        # never needs reordering (flipping the (K,M,B) tensors every
        # iteration costs real bandwidth).
        exp_lin = lin2.reshape(K, M, B)
        exp_st = st2.reshape(K, M, S)
        exp_val = child_valid.reshape(K, M)
        if R:
            all_lin = jnp.concatenate([exp_lin, ch_lin], axis=1)
            all_st = jnp.concatenate([exp_st, ch_st], axis=1)
            all_val = jnp.concatenate([exp_val, ch_alive], axis=1)
            all_s1 = jnp.concatenate([sum1k, ch_s1], axis=1)
            all_s2 = jnp.concatenate([sum2k, ch_s2], axis=1)
        else:
            all_lin, all_st, all_val = exp_lin, exp_st, exp_val
            all_s1, all_s2 = sum1k, sum2k

        # -- fingerprints (key-salted: all keys share the tables) -----------
        lin2f = all_lin.reshape(KML, B)
        st2f = all_st.reshape(KML, S)
        sum1f = all_s1.reshape(KML)
        sum2f = all_s2.reshape(KML)
        saltw = jnp.broadcast_to(salt[:, None], (K, ML)).reshape(KML)
        h1, h2 = finalize_fp(sum1f, sum2f, st2f, saltw)
        cv = all_val.reshape(KML)

        # In-batch twin dedup: parents in the same frontier often generate
        # identical children (diamond orders); left unchecked each copy is
        # pushed and re-expanded (~6x measured blowup on exhaustion
        # proofs). Every valid lane claims a slot keyed by fingerprint in a
        # small persistent scratch; of the lanes with equal fingerprints at
        # a claimed slot, exactly the scatter winner survives. Distinct-
        # fingerprint collisions just mean both survive (extra work only).
        # Stale claims are unreadable: a slot is only read by lanes that
        # wrote it this iteration.
        lane = jnp.arange(KML, dtype=jnp.int32)
        cslot = jnp.where(cv, (h1 & jnp.uint32(Tc - 1)).astype(jnp.int32),
                          Tc)
        claim = claim.at[cslot].set(lane, mode="drop")
        winner = claim.at[cslot].get(mode="fill", fill_value=0)
        dup = cv & (winner != lane) & (jnp.take(h1, winner) == h1) \
            & (jnp.take(h2, winner) == h2)

        # One vectorized probe round against the shared seen-table: gather
        # all PROBES slots at once, then a single scatter into the first
        # empty slot. Scatter-race losers are simply not recorded (their
        # configs may be re-explored later; extra work, never lost work).
        slot0 = (h1 & jnp.uint32(T - 1)).astype(jnp.int32)
        slots = (slot0[:, None]
                 + jnp.arange(PROBES, dtype=jnp.int32)[None, :]) & (T - 1)
        slots = jnp.where((cv & ~dup)[:, None], slots, T)
        cur = tab.at[slots].get(mode="fill", fill_value=0)   # (KM,P,2)
        cur1, cur2 = cur[..., 0], cur[..., 1]
        seen = ((cur1 == h1[:, None]) & (cur2 == h2[:, None])).any(axis=1) \
            & cv & ~dup
        empty = (cur1 == 0) & (cur2 == 0)
        first_empty = jnp.argmax(empty, axis=1)
        islot = jnp.take_along_axis(slots, first_empty[:, None],
                                    axis=1)[:, 0]
        has_empty = empty.any(axis=1)
        want = cv & ~dup & ~seen & has_empty
        wslot = jnp.where(want, islot, T)
        tab = tab.at[wslot].set(jnp.stack([h1, h2], axis=-1),
                                mode="drop")
        # saturation tell: lanes that wanted an insert but every probe
        # slot was full (safe -- only re-exploration -- but it silently
        # costs throughput, so it is counted and surfaced at harvest)
        tfailg = tfailg.at[0].add(
            jnp.sum(cv & ~dup & ~seen & ~has_empty, dtype=jnp.int32))

        # -- push fresh configs (per-key positions, one flat scatter) -------
        # Stack order (ascending position = popped sooner next time):
        # expansion lanes in (w asc, c desc) -- so the deepest popped
        # parent's best-priority child sits highest among expansions --
        # then the chain ascending, its deepest config on the very top.
        # Ranks are computed from cumsums over the masks alone; lane
        # DATA stays in natural order.
        fresh = (cv & ~dup & ~seen).reshape(K, ML)
        fe = fresh[:, :M].reshape(K, W, C).astype(jnp.int32)
        row_tot = fe.sum(axis=2)                               # (K,W)
        rows_before = jnp.cumsum(row_tot, axis=1) - row_tot
        suffix_in_row = row_tot[:, :, None] - jnp.cumsum(fe, axis=2)
        rank_e = (rows_before[:, :, None] + suffix_in_row).reshape(K, M)
        exp_total = row_tot.sum(axis=1)                        # (K,)
        if R:
            fc_ = fresh[:, M:].astype(jnp.int32)
            rank_c = exp_total[:, None] + jnp.cumsum(fc_, axis=1) - 1
            offs = jnp.concatenate([rank_e, rank_c], axis=1)
            cnt = exp_total + fc_.sum(axis=1)
        else:
            offs = rank_e
            cnt = exp_total
        pos = top[:, None] + offs
        dropped = dropped | (running & (top + cnt > O))
        fpos = jnp.where(fresh, arange_K[:, None] * O + pos % O,
                         K * O).reshape(KML)
        buf_lin = buf_lin.reshape(K * O, B).at[fpos] \
            .set(lin2f, mode="drop").reshape(K, O, B)
        buf_state = buf_state.reshape(K * O, S).at[fpos] \
            .set(st2f, mode="drop").reshape(K, O, S)
        buf_fp = buf_fp.reshape(K * O, 2).at[fpos] \
            .set(jnp.stack([sum1f, sum2f], axis=-1),
                 mode="drop").reshape(K, O, 2)
        # renormalize so the absolute counter can't overflow int32 over
        # long runs: shifting by O preserves every slot index mod O, and
        # `dropped` has already latched once a wrap occurred
        top = top + cnt
        top = jnp.where(top >= 2 * O, top - O, top)

        if axis_name is not None:
            # -- single-search mesh sharding (SURVEY §7 step 9) ---------
            # This kernel instance is ONE SHARD of a single search: the
            # DFS stack/frontier is partitioned per device (K == 1
            # locally), dedup tables are per-device (insert failures
            # only cost re-exploration, so skipping cross-device dedup
            # is sound), and the only cross-device traffic is a tiny
            # per-iteration work-balance vector (all_gather of frontier
            # sizes) plus a bounded hand-off of configs donated to a
            # STARVING right neighbor over the ring (ppermute) -- the
            # ICI-collective design SURVEY §5 promises, not a port of
            # the reference's thread-pool parallelism
            # (checker.clj:101-116).
            D, H = axis_size, steal
            me = lax.axis_index(axis_name)
            loads = lax.all_gather(top[0], axis_name)         # (D,)
            starving = jnp.take(loads, (me + 1) % D) == 0
            donate = (top[0] > 2 * H) & starving \
                & (status[0] == RUNNING)
            # deepest H entries (ring positions top-1 .. top-H); a
            # donor keeps plenty and the thief resumes depth-first
            # from the donor's best configs
            idxh = (top[0] - 1
                    - jnp.arange(H, dtype=jnp.int32)) % O     # (H,)
            hval = jnp.where(donate, 1, 0) \
                * jnp.ones(H, jnp.int32)                      # (H,)
            h_lin = jnp.take(buf_lin[0], idxh, axis=0)        # (H, B)
            h_st = jnp.take(buf_state[0], idxh, axis=0)
            h_fp = jnp.take(buf_fp[0], idxh, axis=0)
            top = jnp.where(donate, top - H, top)
            ring = [(i, (i + 1) % D) for i in range(D)]
            r_lin = lax.ppermute(h_lin, axis_name, ring)
            r_st = lax.ppermute(h_st, axis_name, ring)
            r_fp = lax.ppermute(h_fp, axis_name, ring)
            r_val = lax.ppermute(hval, axis_name, ring) != 0  # (H,)
            # push the received configs (shallowest of the donation on
            # the bottom: they arrive deepest-first, so reverse)
            r_val = r_val[::-1]
            cnt_r = jnp.sum(r_val, dtype=jnp.int32)
            pos_r = top[0] + jnp.cumsum(
                r_val.astype(jnp.int32)) - 1
            dropped = dropped | ((status == RUNNING)
                                 & (top + cnt_r > O))
            fpos_r = jnp.where(r_val, pos_r % O, O)
            buf_lin = buf_lin.reshape(O, B).at[fpos_r] \
                .set(r_lin[::-1], mode="drop").reshape(K, O, B)
            buf_state = buf_state.reshape(O, S).at[fpos_r] \
                .set(r_st[::-1], mode="drop").reshape(K, O, S)
            buf_fp = buf_fp.reshape(O, 2).at[fpos_r] \
                .set(r_fp[::-1], mode="drop").reshape(K, O, 2)
            top = top + cnt_r

        explored = explored + jnp.where(running,
                                        fvalid.sum(axis=1,
                                                   dtype=jnp.int32), 0)
        its = its + running.astype(jnp.int32)
        it = it + 1
        return (buf_lin, buf_state, buf_fp, top, tab[None], dropped,
                status, explored, best_depth, best_lin, best_state, its,
                it, claim[None], tfailg)

    def init_carry(init_states):
        buf_lin = jnp.zeros((K, O, B), jnp.uint32)
        buf_state = jnp.zeros((K, O, S), jnp.int32) \
            .at[:, 0, :].set(init_states)
        # every slot starts with the all-zero bitset's lin-sums (only
        # slot 0 is live; the rest are overwritten before any pop)
        z = jnp.stack([jnp.sum(_mix32(k1j[:B]), dtype=jnp.uint32),
                       jnp.sum(_mix32(k2j[:B]), dtype=jnp.uint32)])
        buf_fp = jnp.broadcast_to(z, (K, O, 2)).astype(jnp.uint32)
        return (buf_lin, buf_state, buf_fp, jnp.ones(K, jnp.int32),
                jnp.zeros((G, T, 2), jnp.uint32),
                jnp.zeros(K, bool), jnp.full(K, RUNNING),
                jnp.zeros(K, jnp.int32),
                jnp.full((K, TOPK), -1, jnp.int32),
                jnp.zeros((K, TOPK, B), jnp.uint32),
                jnp.zeros((K, TOPK, S), jnp.int32),
                jnp.zeros(K, jnp.int32),
                jnp.zeros(G, jnp.int32), jnp.zeros((G, Tc), jnp.int32),
                jnp.zeros(G, jnp.int32))

    def run_chunk(carry, invoke, ret, fop, args, rets, ok_words, salt,
                  bound):
        """Advance the search until every key succeeds/exhausts or the
        iteration counter reaches ``bound``. Bounded dispatches keep device
        kernels short (long single while_loops can trip runtime watchdogs)
        and let the host enforce wall-clock budgets between chunks.

        Op arrays must be pre-sorted into linearization-priority order
        (_priority_order): index order IS search order."""
        fx = (fused[0](invoke[0], ret[0], fop[0], args[0], rets[0])
              if fused is not None else ())
        consts = (invoke, ret, fop, args, rets, ok_words, salt, bound,
                  fx)

        def cond(c):
            local = jnp.any((c[IDX_STATUS] == RUNNING)
                            & (c[IDX_TOP] > 0))
            if axis_name is None:
                return local & (c[IDX_IT][0] < bound)
            # sharded single search: every shard must agree on the
            # loop trip count (a shard exiting early would desert the
            # body's collectives), so continuation is GLOBAL -- any
            # shard holding work keeps everyone stepping (starved
            # shards idle until the ring feeds them), and any shard's
            # success stops everyone
            work = lax.psum(jnp.where(local, 1, 0), axis_name)
            found = lax.psum(
                jnp.sum((c[IDX_STATUS] == VALID).astype(jnp.int32)),
                axis_name)
            return (work > 0) & (found == 0) & (c[IDX_IT][0] < bound)

        return lax.while_loop(cond, lambda c: body(c, consts), carry)

    return jax.jit(init_carry), jax.jit(run_chunk, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# public entry points

def table_stats(carry):
    """Dedup-table occupancy diagnostics (VERDICT r4 #5): load factor
    from one reduction over the table at harvest time -- off the hot
    loop -- plus the accumulated insert-failure count. Failed inserts
    are safe (re-exploration only, never wrong answers) but silently
    degrade throughput as the table fills; without these numbers a
    saturated table is indistinguishable from a slow search."""
    tab = carry[IDX_TAB]
    # ONE host round-trip for both scalars: a separate device_get per
    # stat cost ~0.2 s each over the remote-TPU tunnel, a fixed
    # per-check overhead that measurably dented the small batch rungs
    used, fails = jax.device_get(
        (jnp.sum((tab != jnp.uint32(0)).any(-1), dtype=jnp.int32),
         jnp.sum(carry[IDX_TFAIL])))
    total = int(tab.shape[0] * tab.shape[1])
    return {"table_load": round(int(used) / total, 4),
            "table_insert_failures": int(fails)}


def _bucket(x, lo):
    """Round up to a power of two (>= lo) so compiled searches are reused
    across histories of similar size."""
    return max(lo, 1 << (int(x) - 1).bit_length())


def _n_floor():
    """Minimum op-count bucket. Campaigns raise it
    (campaign.compile_cache.set_n_floor) so sweep cells whose op
    counts straddle a power of two still share one compiled search;
    padding rows are inert, so a coarser bucket is always sound."""
    from ..campaign import compile_cache
    return compile_cache.n_floor()


def _note_compile(engine, key):
    """Report this search's compile plan to the campaign-level
    compile-reuse ledger (hit/miss counters; never verdict-bearing).
    Returns True when the ledger calls it a MISS — the phase plane
    attributes the next dispatch's wall to XLA compile, not
    device-compute."""
    try:
        from ..campaign import compile_cache
        return not compile_cache.note(engine, key)
    except Exception:  # noqa: BLE001 - telemetry only
        return False


def _adapt_quantum(cap, per_it, target_s, left_s=None):
    """Next dispatch quantum (shared by the single-key and batched
    loops): ~``target_s`` of measured per-iteration wall, capped by the
    caller's ``chunk_iters`` contract, and shrunk to fit the remaining
    wall budget ``left_s`` (budgets are only enforced BETWEEN
    dispatches, so a mispredicted quantum is the whole overshoot).
    Both fixed policies failed measurably: large chunks overshot a
    60 s budget to 282 s; fixed-small chunks made big searches
    sync-bound over the remote-TPU tunnel (PROFILE.md round 4)."""
    eff = max(1, min(cap, int(target_s / per_it)))
    if left_s is not None:
        eff = max(1, min(eff, int(left_s / per_it) + 1))
    return eff


def _plan_sizes(n, S, C, frontier_width=None, stack_size=None,
                table_size=None):
    B = max(1, (n + 31) // 32)
    if frontier_width is None:
        # aim for ~32k candidate expansions per iteration, capped so
        # the (W, C, S) model-step tensor stays ~<=256 MB -- large
        # padded queue states at high point-concurrency otherwise
        # build multi-GB intermediates that crash the TPU worker
        # (observed on a 9k-op FIFO search: C=512, S=8192) -- AND at
        # 16*C: width beyond the candidate branching buys nothing
        # (measured on a 37k-op 2-process history: identical iteration
        # counts at W=64/256/1024, wall 6.4 s / 15.8 s / 52.3 s --
        # every extra lane is pure cost at low point-concurrency;
        # exhaustion proofs trade the wider pop for more, cheaper
        # iterations)
        frontier_width = max(
            8, min(4096, 32768 // max(1, C), 16 * C,
                   (64 << 20) // max(1, C * S)))
    if stack_size is None:
        # ~128 MB of stack at most
        per = (B + S) * 4
        stack_size = max(4096, min(1 << 18, (128 << 20) // per))
    if table_size is None:
        # a fixed 2^20 table SATURATES at rung-0 scales (round-5
        # instrumentation measured load 0.985 on a 64k-request cas
        # search after only 194 iterations): failed inserts silently
        # degrade the search to re-exploration. Scale with the history
        # size -- ~32 slots per op -- capped at 2^23 (64 MB of HBM)
        table_size = max(1 << 20, min(1 << 23, 32 * n))
    # slot indexing uses h & (T-1): every size must be a power of two
    return (B, _bucket(frontier_width, 8), _bucket(stack_size, 1024),
            _bucket(table_size, 1024))


def _encode_arrays(e):
    """Dense int32 arrays for the device search. Invoke/return indices are
    re-ranked to small ints; INF_TIME becomes INF32."""
    n = len(e)
    invoke = e.invoke_idx.astype(np.int64)
    ret = e.return_idx
    finite = np.concatenate([invoke, ret[ret < INF_TIME]])
    ranks = {v: i for i, v in enumerate(np.unique(finite))}
    inv32 = np.array([ranks[v] for v in invoke], np.int32) \
        if n else np.zeros(0, np.int32)
    ret32 = np.array([ranks[v] if v < INF_TIME else INF32 for v in ret],
                     np.int32) if n else np.zeros(0, np.int32)
    ok_words = np.zeros(max(1, (n + 31) // 32), np.uint32)
    for i in range(n):
        if e.is_ok[i]:
            ok_words[i // 32] |= np.uint32(1) << np.uint32(i % 32)
    return inv32, ret32, ok_words


def _state_abstraction_check(spec, e, init_state, max_states=4096,
                             max_rounds=64):
    """Sound invalidity pre-check: enumerate an over-approximation of
    the reachable model states (fixpoint of applying every op to every
    state, ignoring timing -- a superset of all linearization-prefix
    states). An ok op whose step fails from EVERY reachable state can
    appear in no linearization, so the history is invalid -- this
    decides e.g. a read of a never-written value on histories far too
    large to exhaust. Models with big state spaces overflow the cap and
    return None (no claim)."""
    n = len(e)
    # distinct (f, args, ret) rows: a 10k-op register history has a
    # few dozen, so the fixpoint is tiny regardless of history length
    rows = np.concatenate(
        [np.asarray(e.f, np.int32)[:, None],
         np.asarray(e.args, np.int32).reshape(n, -1),
         np.asarray(e.ret, np.int32).reshape(n, -1)], axis=1)
    uniq, inverse = np.unique(rows, axis=0, return_inverse=True)
    if len(uniq) > 512:
        return None
    A = np.asarray(e.args, np.int32).reshape(n, -1).shape[1]
    uf = uniq[:, 0]
    ua = uniq[:, 1:1 + A]
    ur = uniq[:, 1 + A:]
    states = {np.asarray(init_state, np.int32).tobytes():
              np.asarray(init_state, np.int32)}
    frontier = list(states.values())
    # per-row "some reachable state accepts it", accumulated as the
    # fixpoint steps every (state, row) pair exactly once
    possible = np.zeros(len(uniq), bool)
    for _ in range(max_rounds):
        new = []
        for st in frontier:
            for u in range(len(uniq)):
                st2, ok = spec.step(st, uf[u], ua[u], ur[u], np)
                if not ok:
                    continue
                possible[u] = True
                st2 = np.asarray(st2, np.int32)
                key = st2.tobytes()
                if key not in states:
                    if len(states) >= max_states:
                        return None
                    states[key] = st2
                    new.append(st2)
        if not new:
            break
        frontier = new
    else:
        return None   # no fixpoint within the round budget
    bad = np.flatnonzero(~possible[inverse] & np.asarray(e.is_ok, bool))
    if len(bad):
        return False, {"op_index": int(bad[0]),
                       "pattern": "impossible-from-every-state",
                       "reachable_states": len(states)}
    return None


def _fast_result(spec, e, init_state, fast, confirm=False):
    """Shape a fast_check decision like a search result, including the
    failure witness op and optional oracle confirmation."""
    result = {"configs_explored": 0, "iterations": 0, "engine": "aspect"}
    if fast is True:
        result["valid"] = True
        return result
    valid, info = fast
    result["valid"] = valid
    result.update({k: v for k, v in info.items() if k != "op_index"})
    i = info.get("op_index")
    if i is not None and e.ops is not None:
        inv, comp = e.ops[i]
        result["op"] = dict(comp if comp is not None else inv)
    if confirm:
        from . import wgl
        oracle = wgl.check_encoded(spec, e, init_state)
        result["confirmed"] = oracle["valid"] is valid
        result["valid"] = oracle["valid"]
    return result


def _apply_prune(spec, e, inv32, ret32):
    """Apply the model's validity-preserving candidate prune (if any):
    dropped rows get the padding-row treatment (invoke just below INF so
    they are never candidates while any ok op is outstanding, return at
    INF so they never constrain the WGL rule). Pruning only ever removes
    non-ok ops, so the success condition is untouched."""
    if spec.prune is None:
        return inv32, ret32
    keep = spec.prune(e, inv32, ret32)
    if keep is None:
        return inv32, ret32
    keep = np.asarray(keep, bool)
    assert not np.any(~keep & np.asarray(e.is_ok, bool)), \
        "prune must never drop ok ops"
    return (np.where(keep, inv32, INF32 - 1).astype(np.int32),
            np.where(keep, ret32, INF32).astype(np.int32))


def _priority_order(spec, e, inv32, ret32):
    """Renumber ops into linearization-priority order: argsort by the
    model hint (default: earliest deadline / return index). The kernel
    then searches candidates in plain index order with zero per-iteration
    gather cost. Returns (perm, inv32, ret32, fop, args, rets, ok_words)
    all permuted; witnesses decode back through perm."""
    n = len(e)
    pri = (np.asarray(spec.hint(e, inv32, ret32), np.int64)
           if spec.hint is not None else ret32.astype(np.int64))
    perm = np.argsort(pri, kind="stable").astype(np.int64)
    inv_s = inv32[perm]
    ret_s = ret32[perm]
    fop = np.asarray(e.f, np.int32)[perm]
    args = np.asarray(e.args, np.int32).reshape(n, -1)[perm]
    rets = np.asarray(e.ret, np.int32).reshape(n, -1)[perm]
    ok_s = np.asarray(e.is_ok, bool)[perm]
    ok_words = np.zeros(max(1, (n + 31) // 32), np.uint32)
    for i in np.flatnonzero(ok_s):
        ok_words[i // 32] |= np.uint32(1) << np.uint32(i % 32)
    return perm, inv_s, ret_s, fop, args, rets, ok_words


def _prepare_search(spec, e, init_state, confirm=False):
    """Shared host-side preparation for a single-key search: empty/fast
    paths, prune, priority order, padding to power-of-two buckets,
    state padding. Returns ``("fast", result)`` when a polynomial path
    decided the history, else ``("search", (perm, inv32, ret32, fop,
    args, rets, ok_words, init_state, n_pad, C, A, S))``. Used by both
    the single-chip path below and the mesh-sharded single search
    (parallel/searchshard.py)."""
    n = len(e)
    if n == 0 or e.n_ok == 0:
        return ("fast", {"valid": True, "configs_explored": 0})

    inv32, ret32, _ = _encode_arrays(e)
    if spec.fast_check is not None:
        fast = spec.fast_check(e, inv32, ret32)
        if fast is not None:
            # exact polynomial decision (e.g. queue bad patterns) --
            # no search needed at any history size
            return ("fast", _fast_result(spec, e, init_state, fast,
                                         confirm))
    if spec.pad_state is None:   # fixed small state spaces only
        fast = _state_abstraction_check(spec, e, init_state)
        if fast is not None:
            return ("fast", _fast_result(spec, e, init_state, fast,
                                         confirm))
    inv32, ret32 = _apply_prune(spec, e, inv32, ret32)
    C = max_point_concurrency(
        inv32,
        np.where(ret32 == INF32, INF_TIME, ret32.astype(np.int64)))
    A = int(e.args.shape[1]) if e.args.ndim == 2 else 1
    perm, inv32, ret32, fop, args, rets, ok_words = _priority_order(
        spec, e, inv32, ret32)

    # Pad shapes to power-of-two buckets so the compiled search is reused.
    # Padding rows are never candidates: they "invoke" after every finite
    # return (invoke INF32-1 >= any reachable r_min) and are not ok ops.
    n_pad = _bucket(n, _n_floor())
    C = min(_bucket(C, 4), n_pad)
    if n_pad > n:
        pn = n_pad - n
        inv32 = np.concatenate([inv32, np.full(pn, INF32 - 1, np.int32)])
        ret32 = np.concatenate([ret32, np.full(pn, INF32, np.int32)])
        fop = np.concatenate([fop, np.zeros(pn, np.int32)])
        args = np.concatenate([args, np.zeros((pn, A), np.int32)])
        rets = np.concatenate([rets, np.zeros((pn, A), np.int32)])
        # padding rows are never ok ops: just zero-extend the packed bits
        extra = (n_pad + 31) // 32 - len(ok_words)
        ok_words = np.concatenate([ok_words, np.zeros(extra, np.uint32)])

    init_state = np.asarray(init_state, np.int32)
    if spec.pad_state is not None:
        S_pad = _bucket(init_state.shape[0], 2)
        init_state = np.asarray(spec.pad_state(init_state, S_pad), np.int32)
    S = int(init_state.shape[0])
    return ("search", (perm, inv32, ret32, fop, args, rets, ok_words,
                       init_state, n_pad, C, A, S))


def check_encoded(spec, e, init_state, max_configs=50_000_000,
                  frontier_width=None, stack_size=None, table_size=None,
                  confirm=False, timeout_s=None, chunk_iters=256,
                  checkpoint=None, checkpoint_every_s=60.0, cancel=None,
                  rollout_seeds=None, rollout_kernel="auto",
                  rollout_depth=None):
    """Device WGL search over an EncodedHistory. Result dict mirrors
    wgl.check_encoded: {"valid": True|False|"unknown", "configs_explored",
    ...}, plus device budget diagnostics. ``timeout_s`` bounds wall clock
    (checked between device chunks of ``chunk_iters`` iterations);
    exceeding it yields {"valid": "unknown", "error": "timeout"}.

    ``checkpoint`` names a file the search frontier is periodically
    snapshotted to (every ``checkpoint_every_s``, between chunks) — the
    checkpoint/resume capability for long checks (SURVEY.md §5; the
    reference has nothing comparable, its unit of durability is a whole
    phase). A timed-out/killed check rerun with the same arguments
    resumes from the snapshot instead of restarting; snapshots carry a
    fingerprint of the search inputs so a stale file for a different
    history or plan is ignored."""
    # phase cursor (obs.phases): attributes this search's wall to
    # encode/plan/h2d/compile/device/d2h/host spans; a pair of clock
    # reads per lap when obs is unbound
    ph = obs_phases.capture("jax-wgl")
    prep = _prepare_search(spec, e, init_state, confirm)
    if prep[0] == "fast":
        return prep[1]
    (perm, inv32, ret32, fop, args, rets, ok_words, init_state, n_pad,
     C, A, S) = prep[1]
    ph.lap("encode")

    B, W, O, T = _plan_sizes(n_pad, S, C, frontier_width, stack_size,
                             table_size)
    # cross-run compile-reuse ledger: everything feeding _build_search's
    # lru/jit key must feed this key too, or a "hit" could lie
    ph.note_compile(_note_compile(
        "jax-wgl", (spec.name, n_pad, B, S, C, A, W, O, T,
                    rollout_kernel, rollout_seeds, rollout_depth)))
    # honor tiny explicit budgets (a 1-iteration run must bail after 1
    # iteration, not 64 -- the checkpoint tests rely on it); the default
    # 50M-config budget keeps max_iters far above any real search
    max_iters = max(1, max_configs // W)

    init_carry, run_chunk = _build_search(spec.step, 1, n_pad, B, S, C, A,
                                          W, O, T, R=rollout_depth,
                                          NS=rollout_seeds,
                                          rollout_kernel=rollout_kernel)
    ph.lap("plan")
    consts = (jnp.asarray(inv32[None]), jnp.asarray(ret32[None]),
              jnp.asarray(fop[None]), jnp.asarray(args[None]),
              jnp.asarray(rets[None]), jnp.asarray(ok_words[None]),
              jnp.zeros(1, jnp.uint32))
    carry = init_carry(jnp.asarray(init_state[None]))
    ph.sync(carry)
    ph.lap("h2d")
    import time as _time
    fingerprint = None
    if checkpoint is not None:
        import hashlib
        h = hashlib.sha256()
        h.update(CARRY_LAYOUT.encode())
        h.update(spec.name.encode())
        for a in (inv32, ret32, fop, args, rets, ok_words, init_state,
                  np.asarray([n_pad, B, S, C, W, O, T], np.int64)):
            h.update(np.ascontiguousarray(a).tobytes())
        fingerprint = h.hexdigest()
        resumed = _load_checkpoint(checkpoint, fingerprint)
        if resumed is not None:
            carry = tuple(jnp.asarray(x) for x in resumed)
        elif not _checkpoint_owned(checkpoint, fingerprint):
            # the path holds a different check's live snapshot; don't
            # touch it (all later saves/cleanup honor this too)
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint %s belongs to a different check; "
                "checkpointing disabled for this run", checkpoint)
            checkpoint = None
    t0 = _time.monotonic()
    last_ckpt = t0
    timed_out = False
    # sinks captured ONCE at search start: a competition-abandoned
    # straggler must not write into a later run's artifacts
    so = obs_search.capture()
    # padding accounting: one real history of len(e) rows rides an
    # n_pad-row padded plan (power-of-two bucket for compile reuse);
    # the per-bucket real/padded counters feed the waste table
    so.plan("jax-wgl", n_pad, len(e), n_pad)
    it = int(carry[IDX_IT][0])
    # Adaptive dispatch quantum. ``chunk_iters`` is the CAP (explicit
    # tiny values are a cadence contract the checkpoint tests rely
    # on); within it, the quantum is chosen from the measured
    # per-iteration wall so each dispatch targets ~3 s and never
    # overshoots the remaining budget by more than ~one misprediction.
    # Both failure modes are measured: a fixed 32-iteration chunk
    # overshot a 60 s budget to 282 s on a 96k-op history (budgets are
    # only enforced BETWEEN dispatches), and a fixed-small chunk
    # made the same history SYNC-bound -- hundreds of host round
    # trips over the remote-TPU tunnel (BENCH_r04 / PROFILE.md).
    # first dispatch: small enough to calibrate cheaply even at huge
    # shapes (a 32-iteration first chunk at n_pad=262k ran 353 s
    # before the first budget check); adaptation takes over after it
    eff = min(chunk_iters, 32, max(1, (32 * 16384) // n_pad))
    while True:
        prev_it = it
        t_chunk = _time.monotonic()
        bound = min(it + eff, max_iters)
        ph.lap("host")
        carry = run_chunk(carry, *consts, jnp.int32(bound))
        # device-compute bracket: the sync exists ONLY while phase
        # attribution is on (otherwise the progress device_get below
        # stays the dispatch's one sync, as before)
        ph.sync(carry)
        dev_s = ph.lap("device", iteration=it)
        # ONE host round-trip for the whole progress tensor (separate
        # device_gets cost ~0.2 s each over the remote-TPU tunnel; see
        # table_stats): status/top/it/explored scalars plus the TOPK
        # witness depths, whose max is the deepest linearized-ok count
        # reached — the search's progress toward n_ok
        status, top, it, explored, bdepth = jax.device_get(
            (carry[IDX_STATUS][0], carry[IDX_TOP][0],
             carry[IDX_IT][0], carry[IDX_EXPLORED][0],
             carry[IDX_BEST_DEPTH][0]))
        status, top, it, explored = (int(status), int(top), int(it),
                                     int(explored))
        ph.lap("d2h")
        # heartbeat per dispatch: long searches stop being a silent jit
        # black box (frontier depth + cumulative explored + deepest op
        # reached, streamed to the captured tracer/registry; no-op when
        # obs is unbound, and no extra device round-trips either way —
        # everything rides the batched device_get above)
        so.heartbeat(
            "jax-wgl", iteration=it,
            chunk_s=_time.monotonic() - t_chunk,
            device_s=dev_s if ph.enabled else None, frontier=top,
            explored=explored,
            depth=max(0, int(np.asarray(bdepth).max())))
        if status != RUNNING or top == 0 or it >= max_iters:
            break
        now = _time.monotonic()
        per_it = max(1e-4, (now - t_chunk) / max(1, it - prev_it))
        eff = _adapt_quantum(
            chunk_iters, per_it, 3.0,
            timeout_s - (now - t0) if timeout_s is not None else None)
        if checkpoint is not None and \
                now - last_ckpt >= checkpoint_every_s:
            _save_checkpoint(checkpoint, fingerprint, carry)
            last_ckpt = now
        if (timeout_s is not None and now - t0 > timeout_s) or \
                (cancel is not None and cancel.is_set()):
            timed_out = True
            if checkpoint is not None:
                _save_checkpoint(checkpoint, fingerprint, carry)
            break

    ph.lap("host")
    out = {"status": carry[IDX_STATUS][0], "top": carry[IDX_TOP][0],
           "dropped": carry[IDX_DROPPED][0],
           "explored": carry[IDX_EXPLORED][0],
           "iterations": carry[IDX_ITS][0],
           "best_depth": carry[IDX_BEST_DEPTH][0],
           "best_lin": carry[IDX_BEST_LIN][0],
           "best_state": carry[IDX_BEST_STATE][0]}
    out = jax.device_get(out)
    tstats = table_stats(carry)
    ph.lap("d2h")
    if timed_out and int(out["status"]) == RUNNING and int(out["top"]) > 0:
        result = {"valid": "unknown", "error": "timeout",
                  "configs_explored": int(out["explored"]),
                  "iterations": int(out["iterations"]),
                  "engine": "jax-wgl", **tstats,
                  **({"checkpoint": checkpoint} if checkpoint else {})}
        so.summary("jax-wgl", result)
        ph.lap("host")
        return result
    result = _interpret(spec, e, out, max_iters, confirm, init_state,
                        perm)
    result.update(tstats)
    so.summary("jax-wgl", result)
    ph.lap("host")
    # never clobber a snapshot that belongs to a DIFFERENT check (the
    # mismatched-fingerprint case the load guard already ignores)
    if checkpoint is not None and _checkpoint_owned(checkpoint,
                                                    fingerprint):
        if result.get("valid") in (True, False):
            # decided: the snapshot is spent
            import contextlib as _ctx
            import os as _os
            with _ctx.suppress(FileNotFoundError):
                _os.unlink(checkpoint)
        else:
            # undecided (budget/overflow): keep a fresh snapshot so a
            # rerun with a larger budget resumes instead of restarting
            _save_checkpoint(checkpoint, fingerprint, carry)
            result["checkpoint"] = checkpoint
    return result


def _checkpoint_owned(path, fingerprint):
    """True when path is free or holds a snapshot with this
    fingerprint."""
    import os as _os
    if not _os.path.exists(path):
        return True
    try:
        with np.load(path) as data:
            return bytes(data["fingerprint"]).decode() == fingerprint
    except Exception:  # noqa: BLE001 - corrupt file: treat as free
        return True


def write_snapshot(path, fingerprint, arrays):
    """Atomically write a fingerprinted npz snapshot (shared by the
    single-key and batched checkpoint paths)."""
    import os as _os
    tmp = f"{path}.tmp"     # np.savez appends .npz to names without it
    _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(
        tmp,
        fingerprint=np.frombuffer(
            fingerprint.encode(), dtype=np.uint8),
        **arrays)
    _os.replace(f"{tmp}.npz", path)


def read_snapshot(path, fingerprint):
    """Load a fingerprinted snapshot's array dict, or None when the file
    is absent, corrupt, or belongs to a different check."""
    import os as _os
    if not _os.path.exists(path):
        return None
    try:
        with np.load(path) as data:
            got = bytes(data["fingerprint"]).decode()
            if got != fingerprint:
                return None
            return {k: data[k] for k in data.files
                    if k != "fingerprint"}
    except Exception:  # noqa: BLE001 - corrupt snapshot = start fresh
        return None


def _save_checkpoint(path, fingerprint, carry):
    """Atomically snapshot the search carry (stack, tables, witness
    trackers, counters) with the input fingerprint."""
    host = [np.asarray(x) for x in jax.device_get(carry)]
    write_snapshot(path, fingerprint,
                   {f"c{i}": x for i, x in enumerate(host)})


def _load_checkpoint(path, fingerprint):
    """Load a snapshot if it exists and matches the fingerprint; returns
    the carry arrays or None."""
    data = read_snapshot(path, fingerprint)
    if data is None:
        return None
    return [data[f"c{i}"] for i in range(len(data))]


def _interpret(spec, e, out, max_iters, confirm, init_state, perm=None):
    status = int(out["status"])
    explored = int(out["explored"])
    result = {"configs_explored": explored,
              "iterations": int(out["iterations"]),
              "engine": "jax-wgl"}
    if status == VALID:
        result["valid"] = True
        _attach_valid_witness(result, e, out, perm, spec, init_state)
        return result
    exhausted = int(out["top"]) == 0
    dropped = bool(out["dropped"])
    if exhausted and not dropped:
        result["valid"] = False
        _attach_witness(result, e, out, perm, spec, init_state)
        if confirm:
            from . import wgl
            oracle = wgl.check_encoded(spec, e, init_state)
            result["confirmed"] = oracle["valid"] is False
            result["valid"] = oracle["valid"]
        return result
    result["valid"] = "unknown"
    result["error"] = ("stack-overflow" if dropped
                       else "max-configs-exceeded")
    return result


def _decode_slots(e, out, perm):
    """Decode the TOPK witness slots into (linearized bool[n], state)
    pairs, deepest-first. Bit positions are in priority-sorted space;
    perm maps them back to original op indices. Shared by the invalid
    path (stuck configurations) and the VALID path (the winning
    configuration rides the same slots)."""
    depths = np.asarray(out["best_depth"], np.int32).reshape(-1)
    lins = np.asarray(out["best_lin"], np.uint32).reshape(len(depths), -1)
    states = np.asarray(out["best_state"],
                        np.int32).reshape(len(depths), -1)
    n = len(e)
    slots = []
    for s in np.argsort(-depths, kind="stable"):
        if depths[s] < 0:
            continue
        lin = lins[s]
        linearized = np.zeros(n, bool)
        for i in range(n):
            pos = int(perm[i]) if perm is not None else i
            linearized[pos] = bool((lin[i // 32] >> np.uint32(i % 32)) & 1)
        slots.append((linearized, states[s]))
    return slots


def _attach_witness(result, e, out, perm, spec, init_state):
    """Decode the TOPK deepest distinct stuck configurations into
    knossos-style witness fields (op / final_paths / previous_ok /
    configs, see checker/witness.py; knossos returns a LIST of stuck
    :configs, reference checker.clj:213-216)."""
    slots = _decode_slots(e, out, perm)
    if not slots:
        # no child ever linearized (the search wedged at the root):
        # the root config IS the stuck config
        slots = [(np.zeros(len(e), bool),
                  np.asarray(init_state, np.int32))]
    from . import witness
    witness.attach_multi(result, spec, e, slots, init_state)


def _attach_valid_witness(result, e, out, perm, spec, init_state):
    """On VALID the winning configuration sits in the TOPK witness
    slots too (both kernel success sites topk_insert the candidate
    before raising the status), so a valid verdict's proof decodes
    exactly like the invalid path's: the deepest slot covering every
    ok op IS the linearization the search found. The normalized
    witness (checker/witness.py ``build``) lands on
    ``result["witness"]`` for the certifier to replay. Absence -- a
    slot-layout drift -- leaves the witness off; the certifier
    reports it (VC006), never a crash here."""
    is_ok = np.asarray(e.is_ok, bool)
    n_ok = int(is_ok.sum())
    for linearized, _state in _decode_slots(e, out, perm):
        if int((linearized & is_ok).sum()) == n_ok:
            from . import witness
            result["witness"] = witness.build(
                spec, e, result.get("engine"), True, linearized,
                init_state)
            return


def check_history(spec, history, **kw):
    """Encode an event history for ``spec`` and run the device search."""
    e, init_state = spec.encode(history)
    return check_encoded(spec, e, init_state, **kw)
