"""Batched Wing-Gong-Lowe linearizability search on TPU.

This is the TPU-native replacement for the engine the reference outsources to
knossos (jepsen/project.clj:14, dispatched from jepsen/src/jepsen/checker.clj:
199-202). The sequential oracle in wgl.py defines the semantics; this module
runs the same search as a *batched branch-and-bound* entirely on device, one
``lax.while_loop`` per check (SURVEY.md section 7: "keep the whole B&B loop in
one lax.while_loop").

Design (everything fixed-shape so XLA traces once):

* A **configuration** is (bitset of linearized ops, model state). Bitsets are
  ``uint32[B]`` words, B = ceil(n/32); states are ``int32[S]``.
* The search keeps a DFS **stack** of configurations in HBM
  (``buf_lin: uint32[O,B]``, ``buf_state: int32[O,S]``, scalar ``top``).
* Each iteration pops the top ``W`` configs (a *frontier*), expands all of
  them at once:
    - unlinearized-op bits are unpacked with a word gather + shift,
    - the WGL rule (op i may linearize next iff ``invoke[i] < min`` return
      over unlinearized ops) becomes a masked row-min + compare,
    - up to ``C`` candidate ops per config are selected with ``top_k``
      (C is the history's max point-concurrency, a static bound on how many
      ops can ever be eligible at once),
    - the model step function is vmapped over (frontier, candidate).
* **Dedup** uses a device-resident open-addressing hash table of 64-bit
  fingerprints (two independent 32-bit multiply-shift hashes over the config
  words). The table is insert-only with linear probing; scatter races between
  distinct keys are resolved by re-gathering ("landed?") and probing on.
  Crucially the table is *best-effort in the safe direction*: a failed insert
  only means the config may be re-explored (children strictly grow the
  bitset, so the search still terminates). A false "seen" requires a 64-bit
  fingerprint collision (~2^-64 per pair); invalid verdicts can be confirmed
  exactly with the sequential oracle via ``confirm=...``.
* New configs are pushed back on the stack with a cumsum scatter; stack
  overflow sets a ``dropped`` flag which degrades an "exhausted" verdict to
  ``unknown`` (success verdicts are unaffected -- dropping work can never
  manufacture a linearization).
* The loop ends on: success (a child linearizes every ``ok`` op), exhaustion
  (stack empty), or budget (iteration cap). Witness for invalid verdicts:
  the deepest config reached (max linearized-ok count) is tracked on device
  and decoded on host.

The same compiled search is reused across histories with identical padded
shapes (shapes are bucketed to powers of two for reuse). The search body is
pure, so a vmapped variant over a leading key axis (jepsen.independent-style
multi-key checks) builds on the same kernel.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..history import INF_TIME

INF32 = np.int32(2**31 - 1)

#: linear-probe length for the dedup hash table
PROBES = 8


# ---------------------------------------------------------------------------
# host-side helpers

def max_point_concurrency(invoke_idx, return_idx):
    """Static bound C on WGL candidates: the max, over return points t, of
    |{i : invoke_i < t <= return_i}| (info ops stay open forever). Every
    candidate set at any reachable configuration is contained in one such
    interval stab (see module docstring). Single O(n log n) event sweep."""
    n = len(invoke_idx)
    if n == 0:
        return 1
    finite = return_idx < INF_TIME
    if not finite.any():
        return n
    # +1 just after each invoke, -1 just after each finite return; the open
    # count sampled at a return point t is |{i: invoke_i < t <= return_i}|.
    # Returns sort before invokes at equal positions so an invoke AT t is
    # not counted (the stab requires invoke_i strictly < t).
    events = sorted(
        [(int(t), 1, +1) for t in invoke_idx] +
        [(int(t), 0, -1) for t in return_idx[finite]])
    best, open_ops = 1, 0
    for _t, kind, delta in events:
        if kind == 0:  # sample before closing the op at its return point
            best = max(best, open_ops)
        open_ops += delta
    return min(best, n)


def _hash_keys(length, seed=0x9E3779B9):
    """Two vectors of random odd uint32 multipliers (multiply-shift
    universal hashing over config words)."""
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    k = rng.randint(0, 2**31, size=(2, length)).astype(np.uint32)
    return (k[0] * 2 + 1), (k[1] * 2 + 1)


def _mix32(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return h


# status codes
RUNNING, VALID = np.int32(0), np.int32(1)


@functools.lru_cache(maxsize=64)
def _build_search(step_fn, n, B, S, C, A, W, O, T):
    """Compile the search for one shape bundle. Returns a jitted function

        search(invoke, ret, f, args, rets, ok_words, init_state, max_iters)
          -> dict of final carry scalars + witness arrays

    All array args are device int32/uint32 with the shapes documented in the
    module docstring; the function is pure so it can be vmapped over a
    leading key axis.
    """
    word_idx = np.arange(n, dtype=np.int32) // 32          # (n,)
    bit_idx = (np.arange(n, dtype=np.int32) % 32).astype(np.uint32)
    k1, k2 = _hash_keys(B + S)
    arange_n = np.arange(n, dtype=np.int32)
    arange_W = np.arange(W, dtype=np.int32)
    arange_B = np.arange(B, dtype=np.uint32)
    M = W * C

    step_one = lambda st, f, a, r: step_fn(st, f, a, r, jnp)  # noqa: E731
    # vmap over candidates (state shared), then over frontier rows
    step_vv = jax.vmap(jax.vmap(step_one, in_axes=(None, 0, 0, 0)),
                       in_axes=(0, 0, 0, 0))

    def fingerprint(words):
        """words: (M, B+S) uint32 -> two (M,) uint32 hashes."""
        h1 = _mix32(jnp.sum(words * k1[None, :], axis=1, dtype=jnp.uint32))
        h2 = _mix32(jnp.sum(words * k2[None, :], axis=1, dtype=jnp.uint32))
        # reserve (0,0) (empty table slot) and h1=0xFFFFFFFF (invalid-lane
        # sentinel in the in-batch dedup) so real fingerprints never alias
        # either
        h2 = jnp.where((h1 == 0) & (h2 == 0), jnp.uint32(1), h2)
        h1 = jnp.where(h1 == jnp.uint32(0xFFFFFFFF), jnp.uint32(0xFFFFFFFE),
                       h1)
        return h1, h2

    def body(carry, consts):
        (buf_lin, buf_state, top, tab1, tab2, dropped, status, explored,
         best_depth, best_lin, best_state, it) = carry
        invoke, ret, fop, args, rets, ok_words, max_iters = consts

        # -- pop frontier ---------------------------------------------------
        start = jnp.maximum(top - W, 0)
        lin = lax.dynamic_slice_in_dim(buf_lin, start, W, axis=0)
        state = lax.dynamic_slice_in_dim(buf_state, start, W, axis=0)
        fvalid = (start + arange_W) < top
        top = start

        # -- candidate selection (the WGL rule) -----------------------------
        wbits = jnp.take(lin, word_idx, axis=1)               # (W,n)
        unlin = ((wbits >> bit_idx[None, :]) & jnp.uint32(1)) == 0
        rmin = jnp.min(jnp.where(unlin, ret[None, :], INF32), axis=1)
        cand = unlin & (invoke[None, :] < rmin[:, None]) & fvalid[:, None]
        score = jnp.where(cand, n - arange_n[None, :], 0)
        vals, ci = lax.top_k(score, C)                        # (W,C)
        cvalid = vals > 0

        # -- model step over (frontier, candidate) --------------------------
        fc = jnp.take(fop, ci)                                # (W,C)
        ac = jnp.take(args, ci, axis=0)                       # (W,C,A)
        rc = jnp.take(rets, ci, axis=0)
        st2, okf = step_vv(state, fc, ac, rc)                 # (W,C,S),(W,C)
        st2 = st2.astype(jnp.int32)

        addmask = jnp.where(
            arange_B[None, None, :] == jnp.take(word_idx, ci)[..., None]
            .astype(jnp.uint32),
            jnp.uint32(1) << jnp.take(bit_idx, ci)[..., None],
            jnp.uint32(0))                                    # (W,C,B)
        lin2 = lin[:, None, :] | addmask

        child_valid = cvalid & okf & fvalid[:, None]
        done = jnp.all((lin2 & ok_words[None, None, :]) == ok_words[None,
                       None, :], axis=-1)
        status = jnp.where(jnp.any(child_valid & done), VALID, status)

        # -- witness tracking ----------------------------------------------
        depth = lax.population_count(lin2 & ok_words[None, None, :]) \
            .sum(axis=-1).astype(jnp.int32)
        depth = jnp.where(child_valid, depth, -1).reshape(M)
        bi = jnp.argmax(depth)
        better = depth[bi] > best_depth
        best_depth = jnp.where(better, depth[bi], best_depth)
        best_lin = jnp.where(better, lin2.reshape(M, B)[bi], best_lin)
        best_state = jnp.where(better, st2.reshape(M, S)[bi], best_state)

        # -- dedup: fingerprints, in-batch, then table ----------------------
        lin2f = lin2.reshape(M, B)
        st2f = st2.reshape(M, S)
        words = jnp.concatenate([lin2f, st2f.astype(jnp.uint32)], axis=1)
        h1, h2 = fingerprint(words)
        cv = child_valid.reshape(M)
        # Invalid lanes still compute (garbage) configs; give them unique
        # sentinel fingerprints so they can never alias a real child in the
        # in-batch dedup sort below.
        lane = jnp.arange(M, dtype=jnp.uint32)
        h1 = jnp.where(cv, h1, jnp.uint32(0xFFFFFFFF))
        h2 = jnp.where(cv, h2, lane)

        sh1, sh2, sidx = lax.sort(
            (h1, h2, jnp.arange(M, dtype=jnp.int32)), num_keys=2)
        dup_sorted = jnp.concatenate(
            [jnp.zeros(1, bool),
             (sh1[1:] == sh1[:-1]) & (sh2[1:] == sh2[:-1])])
        dup = jnp.zeros(M, bool).at[sidx].set(dup_sorted)

        slot0 = (h1 & jnp.uint32(T - 1)).astype(jnp.int32)
        seen = jnp.zeros(M, bool)
        placed = ~cv | dup        # only first-occurrence valid keys insert
        for j in range(PROBES):
            slot = (slot0 + j) & (T - 1)
            cur1 = tab1[slot]
            cur2 = tab2[slot]
            empty = (cur1 == 0) & (cur2 == 0)
            seen = seen | ((cur1 == h1) & (cur2 == h2) & cv)
            want = cv & ~placed & ~seen & empty
            wslot = jnp.where(want, slot, T)
            tab1 = tab1.at[wslot].set(h1, mode="drop")
            tab2 = tab2.at[wslot].set(h2, mode="drop")
            landed = want & (tab1[slot] == h1) & (tab2[slot] == h2)
            placed = placed | landed

        # -- push fresh configs ---------------------------------------------
        fresh = cv & ~seen & ~dup
        offs = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        cnt = offs[M - 1] + 1
        pos = jnp.where(fresh, top + offs, O)
        dropped = dropped | (top + cnt > O)
        buf_lin = buf_lin.at[pos].set(lin2f, mode="drop")
        buf_state = buf_state.at[pos].set(st2f, mode="drop")
        top = jnp.minimum(top + cnt, O)

        explored = explored + fvalid.sum(dtype=jnp.int32)
        it = it + 1
        return (buf_lin, buf_state, top, tab1, tab2, dropped, status,
                explored, best_depth, best_lin, best_state, it)

    def init_carry(init_state):
        buf_lin = jnp.zeros((O, B), jnp.uint32)
        buf_state = jnp.zeros((O, S), jnp.int32) \
            .at[0].set(init_state)
        return (buf_lin, buf_state, jnp.int32(1),
                jnp.zeros(T, jnp.uint32), jnp.zeros(T, jnp.uint32),
                jnp.zeros((), bool), RUNNING, jnp.int32(0),
                jnp.int32(-1), jnp.zeros(B, jnp.uint32),
                jnp.zeros(S, jnp.int32), jnp.int32(0))

    def run_chunk(carry, invoke, ret, fop, args, rets, ok_words, bound):
        """Advance the search until success/exhaustion or iteration
        ``bound``. Bounded dispatches keep individual device kernels short
        (long single while_loops can trip runtime watchdogs) and let the
        host enforce wall-clock budgets between chunks."""
        consts = (invoke, ret, fop, args, rets, ok_words, bound)

        def cond(c):
            return (c[6] == RUNNING) & (c[2] > 0) & (c[11] < bound)

        return lax.while_loop(cond, lambda c: body(c, consts), carry)

    return jax.jit(init_carry), jax.jit(run_chunk, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# public entry points

def _bucket(x, lo):
    """Round up to a power of two (>= lo) so compiled searches are reused
    across histories of similar size."""
    return max(lo, 1 << (int(x) - 1).bit_length())


def _plan_sizes(n, S, C, frontier_width=None, stack_size=None,
                table_size=None):
    B = max(1, (n + 31) // 32)
    if frontier_width is None:
        # aim for ~32k candidate expansions per iteration
        frontier_width = max(32, min(4096, 32768 // max(1, C)))
    if stack_size is None:
        # ~128 MB of stack at most
        per = (B + S) * 4
        stack_size = max(4096, min(1 << 18, (128 << 20) // per))
    if table_size is None:
        table_size = 1 << 20
    # slot indexing uses h & (T-1): every size must be a power of two
    return (B, _bucket(frontier_width, 32), _bucket(stack_size, 1024),
            _bucket(table_size, 1024))


def _encode_arrays(e):
    """Dense int32 arrays for the device search. Invoke/return indices are
    re-ranked to small ints; INF_TIME becomes INF32."""
    n = len(e)
    invoke = e.invoke_idx.astype(np.int64)
    ret = e.return_idx
    finite = np.concatenate([invoke, ret[ret < INF_TIME]])
    ranks = {v: i for i, v in enumerate(np.unique(finite))}
    inv32 = np.array([ranks[v] for v in invoke], np.int32) \
        if n else np.zeros(0, np.int32)
    ret32 = np.array([ranks[v] if v < INF_TIME else INF32 for v in ret],
                     np.int32) if n else np.zeros(0, np.int32)
    ok_words = np.zeros(max(1, (n + 31) // 32), np.uint32)
    for i in range(n):
        if e.is_ok[i]:
            ok_words[i // 32] |= np.uint32(1) << np.uint32(i % 32)
    return inv32, ret32, ok_words


def check_encoded(spec, e, init_state, max_configs=50_000_000,
                  frontier_width=None, stack_size=None, table_size=None,
                  confirm=False, timeout_s=None, chunk_iters=256):
    """Device WGL search over an EncodedHistory. Result dict mirrors
    wgl.check_encoded: {"valid": True|False|"unknown", "configs_explored",
    ...}, plus device budget diagnostics. ``timeout_s`` bounds wall clock
    (checked between device chunks of ``chunk_iters`` iterations);
    exceeding it yields {"valid": "unknown", "error": "timeout"}."""
    n = len(e)
    if n == 0 or e.n_ok == 0:
        return {"valid": True, "configs_explored": 0}

    inv32, ret32, ok_words = _encode_arrays(e)
    C = max_point_concurrency(inv32, np.where(ret32 == INF32,
                                              INF_TIME, ret32.astype(np.int64)))
    A = int(e.args.shape[1]) if e.args.ndim == 2 else 1

    # Pad shapes to power-of-two buckets so the compiled search is reused.
    # Padding rows are never candidates: they "invoke" after every finite
    # return (invoke INF32-1 >= any reachable r_min) and are not ok ops.
    n_pad = _bucket(n, 64)
    C = min(_bucket(C, 4), n_pad)
    fop, args, rets = (np.asarray(e.f, np.int32), np.asarray(e.args, np.int32),
                       np.asarray(e.ret, np.int32))
    if n_pad > n:
        pn = n_pad - n
        inv32 = np.concatenate([inv32, np.full(pn, INF32 - 1, np.int32)])
        ret32 = np.concatenate([ret32, np.full(pn, INF32, np.int32)])
        fop = np.concatenate([fop, np.zeros(pn, np.int32)])
        args = np.concatenate([args, np.zeros((pn, A), np.int32)])
        rets = np.concatenate([rets, np.zeros((pn, A), np.int32)])
        # padding rows are never ok ops: just zero-extend the packed bits
        extra = (n_pad + 31) // 32 - len(ok_words)
        ok_words = np.concatenate([ok_words, np.zeros(extra, np.uint32)])

    init_state = np.asarray(init_state, np.int32)
    if spec.pad_state is not None:
        S_pad = _bucket(init_state.shape[0], 2)
        init_state = np.asarray(spec.pad_state(init_state, S_pad), np.int32)
    S = int(init_state.shape[0])

    B, W, O, T = _plan_sizes(n_pad, S, C, frontier_width, stack_size,
                             table_size)
    max_iters = max(64, max_configs // W)

    init_carry, run_chunk = _build_search(spec.step, n_pad, B, S, C, A, W,
                                          O, T)
    consts = (jnp.asarray(inv32), jnp.asarray(ret32), jnp.asarray(fop),
              jnp.asarray(args), jnp.asarray(rets), jnp.asarray(ok_words))
    carry = init_carry(jnp.asarray(init_state))
    import time as _time
    t0 = _time.monotonic()
    timed_out = False
    it = 0
    while True:
        bound = min(it + chunk_iters, max_iters)
        carry = run_chunk(carry, *consts, jnp.int32(bound))
        status, top, it = (int(carry[6]), int(carry[2]), int(carry[11]))
        if status != RUNNING or top == 0 or it >= max_iters:
            break
        if timeout_s is not None and _time.monotonic() - t0 > timeout_s:
            timed_out = True
            break

    out = {"status": carry[6], "top": carry[2], "dropped": carry[5],
           "explored": carry[7], "iterations": carry[11],
           "best_depth": carry[8], "best_lin": carry[9],
           "best_state": carry[10]}
    out = jax.device_get(out)
    if timed_out and int(out["status"]) == RUNNING and int(out["top"]) > 0:
        return {"valid": "unknown", "error": "timeout",
                "configs_explored": int(out["explored"]),
                "iterations": int(out["iterations"]), "engine": "jax-wgl"}
    return _interpret(spec, e, out, max_iters, confirm, init_state)


def _interpret(spec, e, out, max_iters, confirm, init_state):
    status = int(out["status"])
    explored = int(out["explored"])
    result = {"configs_explored": explored,
              "iterations": int(out["iterations"]),
              "engine": "jax-wgl"}
    if status == VALID:
        result["valid"] = True
        return result
    exhausted = int(out["top"]) == 0
    dropped = bool(out["dropped"])
    if exhausted and not dropped:
        result["valid"] = False
        _attach_witness(result, e, out)
        if confirm:
            from . import wgl
            oracle = wgl.check_encoded(spec, e, init_state)
            result["confirmed"] = oracle["valid"] is False
            result["valid"] = oracle["valid"]
        return result
    result["valid"] = "unknown"
    result["error"] = ("stack-overflow" if dropped
                       else "max-configs-exceeded")
    return result


def _attach_witness(result, e, out):
    """Decode the deepest stuck configuration into reference-style
    :op / :final-paths info."""
    lin = np.asarray(out["best_lin"], np.uint32)
    n = len(e)
    linearized = np.zeros(n, bool)
    for i in range(n):
        linearized[i] = bool((lin[i // 32] >> np.uint32(i % 32)) & 1)
    stuck = [i for i in range(n) if e.is_ok[i] and not linearized[i]]
    if stuck:
        i = stuck[0]
        if e.ops is not None:
            inv, comp = e.ops[i]
            result["op"] = dict(comp if comp is not None else inv)
        result["final_state"] = np.asarray(out["best_state"]).tolist()
        result["linearized_ok_ops"] = int(out["best_depth"])


def check_history(spec, history, **kw):
    """Encode an event history for ``spec`` and run the device search."""
    e, init_state = spec.encode(history)
    return check_encoded(spec, e, init_state, **kw)
