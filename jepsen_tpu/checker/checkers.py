"""The checker library: histories in, verdict maps out (reference
jepsen/src/jepsen/checker.clj:124-795).

Each checker returns {"valid": True|False|"unknown", ...}. The
``linearizable`` checker is the gate to the linearizability engines: it
dispatches on "algorithm" exactly like the reference dispatches to knossos
(checker.clj:199-202), with "jax-wgl" selecting the TPU engine and
"competition" racing the CPU oracle against it."""

from __future__ import annotations

import collections
import logging
import re
import threading

from .. import history as h
from .. import obs
from ..models import base as mbase
from .core import Checker, merge_valid

logger = logging.getLogger(__name__)

__all__ = [
    "unhandled_exceptions", "stats", "linearizable", "queue", "set_checker",
    "set_full", "expand_queue_drain_ops", "total_queue", "unique_ids",
    "counter", "log_file_pattern",
]


class _UnhandledExceptions(Checker):
    """Aggregates info ops carrying exceptions by class
    (checker.clj:124-151)."""

    def check(self, test, hist, opts=None):
        exes = [o for o in hist
                if o.get("exception") and o.get("type") == "info"]
        groups = collections.defaultdict(list)
        for o in exes:
            groups[o.get("exception")].append(o)
        out = sorted(groups.values(), key=len, reverse=True)
        result = {"valid": True}
        if out:
            result["exceptions"] = [
                {"count": len(ops), "class": ops[0].get("exception"),
                 "example": ops[0]} for ops in out]
        return result


def unhandled_exceptions():
    return _UnhandledExceptions()


def _stats_map(hist):
    ok = sum(1 for o in hist if h.ok(o))
    fail = sum(1 for o in hist if h.fail(o))
    info = sum(1 for o in hist if h.info(o))
    return {"valid": ok > 0, "count": ok + fail + info,
            "ok-count": ok, "fail-count": fail, "info-count": info}


class _Stats(Checker):
    """ok/fail/info counts overall and by :f; valid iff every f saw an ok
    (checker.clj:153-183)."""

    def check(self, test, hist, opts=None):
        hist = [o for o in hist
                if not h.invoke(o) and o.get("process") != "nemesis"]
        by_f = collections.defaultdict(list)
        for o in hist:
            by_f[o.get("f")].append(o)
        groups = {f: _stats_map(sub) for f, sub in sorted(
            by_f.items(), key=lambda kv: str(kv[0]))}
        out = _stats_map(hist)
        out["by-f"] = groups
        out["valid"] = merge_valid([g["valid"] for g in groups.values()])
        return out


def stats():
    return _Stats()


class Linearizable(Checker):
    """THE gate to the linearizability engines (checker.clj:185-216).
    algorithm: "wgl" (sequential CPU oracle), "jax-wgl" (batched device
    search), "linear" (just-in-time linearization; bounded config set,
    may return "unknown" on overflow), or default "competition" (races
    all three; the first definite verdict wins)."""

    def __init__(self, model, algorithm="competition", engine_opts=None,
                 init_ops=None):
        assert model is not None, \
            "the linearizable checker requires a model"
        self.spec = mbase.model_spec(model)
        self.algorithm = algorithm
        self.engine_opts = engine_opts or {}
        #: ops establishing the initial state, e.g. [{"f": "write",
        #: "value": 0}] for a register pre-set to 0 (the reference's
        #: (model/cas-register 0)). Prepended as already-completed pairs
        #: before every real op.
        self.init_ops = list(init_ops or [])

    def prepare_history(self, client_hist):
        """Prepend the init ops as already-completed pairs ordered before
        every real op (negative indices). Both the direct check and
        independent's batched per-key path go through this, and both
        must feed it the SAME selection of ops — ``history.client_ops``
        (integer process ids only; the nemesis and log lines never
        linearize). A nemesis-laced history must produce identical
        verdicts on either path."""
        if not self.init_ops:
            return client_hist
        lo = min((o.get("index", 0) for o in client_hist), default=0)
        synth = []
        for j, op in enumerate(self.init_ops):
            base = lo - 2 * (len(self.init_ops) - j)
            synth.append({"type": "invoke", "process": -1,
                          "f": op["f"], "value": op.get("value"),
                          "index": base, "time": base})
            synth.append({"type": "ok", "process": -1,
                          "f": op["f"], "value": op.get("value"),
                          "index": base + 1, "time": base + 1})
        return synth + client_hist

    def check(self, test, hist, opts=None):
        from . import jax_wgl, linear, wgl
        client_hist = self.prepare_history(h.client_ops(hist))
        algo = self.algorithm
        # search planning (analysis/searchplan.py): sealed quiescent
        # cuts slice the history into independent segments routed as
        # ONE batch through parallel/keyshard (same _n_floor buckets,
        # so the compile ledger still hits). Default on; opt out with
        # test["searchplan?"] = False. None = no reduction / planning
        # failed -> the unplanned search below runs as always.
        a = None
        if algo == "jax-wgl" and "mesh" not in self.engine_opts:
            a = self._check_planned(test, client_hist)
        if a is None:
            e, init_state = self.spec.encode(client_hist)
            if algo == "wgl":
                a = wgl.check_encoded(self.spec, e, init_state)
            elif algo == "linear":
                a = linear.check_encoded(self.spec, e, init_state)
            elif algo == "jax-wgl":
                opts = dict(self.engine_opts)
                mesh = opts.pop("mesh", None)
                if mesh is not None:
                    # one SINGLE-key search sharded across the mesh
                    # (parallel/searchshard.py); the multi-key batched
                    # path takes mesh via independent's engine_opts.
                    # Forward only the options the sharded engine
                    # supports; warn-drop the rest rather than crash a
                    # whole check over e.g. a checkpoint path
                    from ..parallel import check_encoded_sharded
                    keep = {"max_configs", "frontier_width",
                            "stack_size", "table_size", "timeout_s",
                            "chunk_iters", "steal", "rollout_seeds"}
                    dropped = sorted(set(opts) - keep)
                    if dropped:
                        logger.warning(
                            "engine_opts %s are not supported by the "
                            "mesh-sharded search; ignoring", dropped)
                    a = check_encoded_sharded(
                        self.spec, e, init_state, mesh,
                        **{k: v for k, v in opts.items() if k in keep})
                else:
                    a = jax_wgl.check_encoded(self.spec, e, init_state,
                                              **opts)
            else:
                a = self._competition(e, init_state)
        # truncate heavyweight fields (checker.clj:213-216: "writing
        # these can take *hours*"): at most 10 paths / 10 configs
        if "final_paths" in a:
            a["final_paths"] = a["final_paths"][:10]
        if "configs" in a:
            a["configs"] = a["configs"][:10]
        if a.get("valid") is False:
            # render the failure witness like the reference's linear.svg
            # (checker.clj:206-212); never let plotting break the verdict
            try:
                from . import linear_report
                linear_report.render_analysis(test, client_hist, a, opts)
            except Exception:  # noqa: BLE001
                logger.warning("couldn't render linear.png",
                               exc_info=True)
        a["valid?"] = a["valid"]
        return a

    #: engine_opts forwarded to the planned batch path — everything
    #: check_batch_encoded supports, including checkpoint/resume (its
    #: fingerprint covers the per-segment inputs, so a rerun of the
    #: same plan resumes). The rest are single-search-only
    #: (confirm/rollout_kernel/rollout_depth); mesh is excluded up
    #: front in check().
    _PLANNED_OPTS = frozenset({"max_configs", "chunk_iters", "timeout_s",
                               "frontier_width", "stack_size",
                               "table_size", "rollout_seeds",
                               "checkpoint", "checkpoint_every_s"})

    def _check_planned(self, test, client_hist):
        """Consult the search plan for this (already init-op-prepared)
        client history: when sealed quiescent cuts slice it into >= 2
        segments, run them as one batched device call and merge.
        Returns None when planning is off, yields no reduction, or
        fails -- the caller then runs the unplanned search, so a
        planner bug can never change a verdict."""
        if not isinstance(test, dict):
            return None
        from ..analysis import searchplan
        # this path's only reduction IS quiescent-cut segmentation, so
        # it honors the predicate list, not just the on/off knob
        if not searchplan.segments_enabled(test):
            return None
        unsupported = set(self.engine_opts) - self._PLANNED_OPTS
        if "confirm" in unsupported:
            # oracle confirmation changes the result contract
            # (result["confirmed"]); the flat search honors it, so
            # planning steps aside rather than silently dropping it
            return None
        if unsupported:
            logger.warning(
                "engine_opts %s are not supported by the planned "
                "batch search; ignoring", sorted(unsupported))
        try:
            import time as _time
            t0 = _time.monotonic()
            segs, info = searchplan.plan_segments(
                self.spec, client_hist, searchplan.min_segment(test))
            if len(segs) < 2:
                return None
            # plan_s = the analyzer's own cost (matching the
            # independent path's measurement); encoding is charged to
            # the search like it is on the unplanned path
            plan_s = _time.monotonic() - t0
            from ..parallel import check_batch_encoded
            pairs = [self.spec.encode(s.events) for s in segs]
            eopts = {k: v for k, v in self.engine_opts.items()
                     if k in self._PLANNED_OPTS}
            results = check_batch_encoded(self.spec, pairs, **eopts)
            # stamp segment provenance onto each normalized witness
            # before the merge folds them: the certifier re-derives
            # the same cuts and matches index/count/seed exactly
            for i, (r, s) in enumerate(zip(results, segs)):
                w = r.get("witness")
                if isinstance(w, dict):
                    w["segment"] = {"index": i, "count": len(segs),
                                    "seed": s.seed}
            merged = searchplan.merge_segment_results(results, info,
                                                      plan_s)
            if obs.enabled():
                obs.inc("checker.planned_checks",
                        valid=str(merged.get("valid")))
                obs.observe("checker.plan_s", plan_s)
            return merged
        except Exception:  # noqa: BLE001 - fall back to the flat search
            logger.warning("planned search failed; falling back to the "
                           "unplanned path", exc_info=True)
            return None

    def _competition(self, e, init_state):
        """Race the sequential oracle against the device engine; the first
        *definite* verdict wins (knossos.competition semantics,
        checker.clj:199-202). If the first engine to finish returns
        "unknown" (config-budget overflow, timeout, crash), wait for the
        other engine and prefer its verdict when definite."""
        from . import jax_wgl, linear, wgl
        first_done = threading.Event()
        results = {}
        order = []
        lock = threading.Lock()

        def run(name, fn):
            try:
                r = fn()
            except Exception as exc:  # noqa: BLE001
                r = {"valid": "unknown", "error": repr(exc)}
            with lock:
                results[name] = r
                order.append(name)
            first_done.set()

        # the CPU engines get config budgets so they yield on hard
        # searches; knossos.competition likewise races linear + wgl.
        # Each racer runs under a contextvars snapshot (like the
        # interpreter/control fan-outs) so the run-scoped obs sinks —
        # and span parentage — follow it: the device engine's
        # heartbeats must land in THIS run's registry even while an
        # overlapping campaign cell holds the process-global binding.
        import contextvars
        cancel = threading.Event()
        threads = [
            threading.Thread(
                target=contextvars.copy_context().run,
                args=(run, "wgl", lambda: wgl.check_encoded(
                    self.spec, e, init_state, max_configs=2_000_000,
                    cancel=cancel)),
                daemon=True),
            threading.Thread(
                target=contextvars.copy_context().run,
                args=(run, "linear", lambda: linear.check_encoded(
                    self.spec, e, init_state, max_configs=200_000,
                    cancel=cancel)),
                daemon=True),
            threading.Thread(
                target=contextvars.copy_context().run,
                args=(run, "jax-wgl", lambda: jax_wgl.check_encoded(
                    self.spec, e, init_state, cancel=cancel,
                    **self.engine_opts)),
                daemon=True),
        ]
        for t in threads:
            t.start()
        # wait for the first DEFINITE verdict (or everyone to give up)
        while True:
            first_done.wait()
            with lock:
                first_done.clear()
                definite = [(nm, results[nm]) for nm in order
                            if results[nm].get("valid") != "unknown"]
                if definite:
                    name, r = definite[0]
                    break
                if len(order) == len(threads):
                    name, r = order[0], results[order[0]]
                    break
        # ask the losing engines to stop (checked between device chunks
        # / every few thousand host configs). Join only briefly: a
        # device compile in flight can take tens of seconds and the
        # verdict is already in hand -- the daemon threads drain
        # themselves once they next check the flag.
        cancel.set()
        for t in threads:
            t.join(timeout=0.5)
        r = dict(r)
        r["engine"] = name
        if obs.enabled():
            obs.inc("checker.competition_wins", engine=name)
            obs.instant("checker.competition", cat="checker",
                        winner=name, valid=str(r.get("valid")))
        return r


def linearizable(opts):
    """linearizable({"model": ..., "algorithm": ...})
    (checker.clj:185-216)."""
    if isinstance(opts, dict):
        return Linearizable(opts["model"], opts.get("algorithm",
                                                    "competition"),
                            opts.get("engine_opts"),
                            opts.get("init-ops"))
    return Linearizable(opts)


class _Queue(Checker):
    """Model-fold queue check: non-failing enqueues count, only ok
    dequeues count (checker.clj:218-238)."""

    def __init__(self, model):
        self.model = model

    def check(self, test, hist, opts=None):
        state = self.model
        for op in hist:
            f = op.get("f")
            take = (f == "enqueue" and h.invoke(op)) or \
                   (f == "dequeue" and h.ok(op))
            if not take:
                continue
            state = state.step(op)
            if mbase.is_inconsistent(state):
                return {"valid": False, "error": state.msg}
        return {"valid": True, "final-queue": state}


def queue(model):
    return _Queue(model)


class _SetChecker(Checker):
    """adds + final read: lost/unexpected/recovered analysis
    (checker.clj:240-291)."""

    def check(self, test, hist, opts=None):
        attempts = {o.get("value") for o in hist
                    if h.invoke(o) and o.get("f") == "add"}
        adds = {o.get("value") for o in hist
                if h.ok(o) and o.get("f") == "add"}
        final_read = None
        for o in hist:
            if h.ok(o) and o.get("f") == "read":
                final_read = o.get("value")
        if final_read is None:
            return {"valid": "unknown", "error": "Set was never read"}
        final = set(final_read)
        ok = final & attempts
        unexpected = final - attempts
        lost = adds - final
        recovered = ok - adds
        return {"valid": not lost and not unexpected,
                "attempt-count": len(attempts),
                "acknowledged-count": len(adds),
                "ok-count": len(ok),
                "lost-count": len(lost),
                "recovered-count": len(recovered),
                "unexpected-count": len(unexpected),
                "ok": sorted(ok), "lost": sorted(lost),
                "unexpected": sorted(unexpected),
                "recovered": sorted(recovered)}


def set_checker():
    return _SetChecker()


class _SetFullElement:
    """Per-element timeline state (checker.clj SetFullElement,
    :300-340)."""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known = None
        self.last_present = None
        self.last_absent = None

    def add_ok(self, op):
        if self.known is None:
            self.known = op

    def read_present(self, inv, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or \
                self.last_present["index"] < inv["index"]:
            self.last_present = inv

    def read_absent(self, inv, op):
        if self.last_absent is None or \
                self.last_absent["index"] < inv["index"]:
            self.last_absent = inv

    def results(self):
        """Outcome classification (checker.clj:346-405)."""
        idx = lambda o, d=-1: o["index"] if o is not None else d  # noqa:E731
        stable = bool(self.last_present is not None and
                      idx(self.last_absent) < idx(self.last_present))
        lost = bool(self.known is not None and
                    self.last_absent is not None and
                    idx(self.last_present) < idx(self.last_absent) and
                    self.known["index"] < idx(self.last_absent))
        never_read = not (stable or lost)
        known_time = self.known["time"] if self.known else None
        stable_latency = None
        lost_latency = None
        if stable:
            stable_time = (self.last_absent["time"] + 1
                           if self.last_absent else 0)
            stable_latency = int(max(0, stable_time - known_time) / 1e6)
        if lost:
            lost_time = (self.last_present["time"] + 1
                         if self.last_present else 0)
            lost_latency = int(max(0, lost_time - known_time) / 1e6)
        return {"element": self.element,
                "outcome": ("stable" if stable else
                            "lost" if lost else "never-read"),
                "stable-latency": stable_latency,
                "lost-latency": lost_latency,
                "known": self.known,
                "last-absent": self.last_absent,
                "never_read": never_read}


def _frequency_distribution(points, values):
    values = sorted(values)
    if not values:
        return None
    n = len(values)
    return {p: values[min(n - 1, int(n * p))] for p in points}


class _SetFull(Checker):
    """Per-element stable/lost timeline analysis with latency quantiles
    (checker.clj:294-592)."""

    def __init__(self, linearizable=False):
        self.linearizable = linearizable

    def check(self, test, hist, opts=None):
        hist = h.ensure_indexed(hist)
        elements = {}
        reads = {}
        dups = {}
        for op in hist:
            if not isinstance(op.get("process"), int):
                continue
            f = op.get("f")
            v = op.get("value")
            p = op.get("process")
            if f == "add":
                if h.invoke(op):
                    elements[v] = _SetFullElement(v)
                elif h.ok(op) and v in elements:
                    elements[v].add_ok(op)
            elif f == "read":
                if h.invoke(op):
                    reads[p] = op
                elif h.fail(op):
                    reads.pop(p, None)
                elif h.ok(op):
                    inv = reads.pop(p, op)
                    counts = collections.Counter(v)
                    for k, c in counts.items():
                        if c > 1:
                            dups[k] = max(dups.get(k, 0), c)
                    vs = set(v)
                    for el, state in elements.items():
                        if el in vs:
                            state.read_present(inv, op)
                        else:
                            state.read_absent(inv, op)
        rs = [elements[k].results()
              for k in sorted(elements, key=lambda x: (str(type(x)), x))]
        outcomes = collections.defaultdict(list)
        for r in rs:
            outcomes[r["outcome"]].append(r)
        stale = [r for r in outcomes["stable"]
                 if r["stable-latency"] and r["stable-latency"] > 0]
        valid = (False if outcomes["lost"] else
                 "unknown" if not outcomes["stable"] else
                 False if self.linearizable and stale else True)
        if dups:
            valid = False
        out = {"valid": valid,
               "attempt-count": len(rs),
               "stable-count": len(outcomes["stable"]),
               "lost-count": len(outcomes["lost"]),
               "lost": sorted(r["element"] for r in outcomes["lost"]),
               "never-read-count": len(outcomes["never-read"]),
               "never-read": sorted(r["element"]
                                    for r in outcomes["never-read"]),
               "stale-count": len(stale),
               "stale": sorted(r["element"] for r in stale),
               "worst-stale": sorted(stale, key=lambda r:
                                     -(r["stable-latency"] or 0))[:8],
               "duplicated-count": len(dups),
               "duplicated": dups}
        points = (0, 0.5, 0.95, 0.99, 1)
        sl = [r["stable-latency"] for r in rs
              if r["stable-latency"] is not None]
        ll = [r["lost-latency"] for r in rs
              if r["lost-latency"] is not None]
        if sl:
            out["stable-latencies"] = _frequency_distribution(points, sl)
        if ll:
            out["lost-latencies"] = _frequency_distribution(points, ll)
        return out


def set_full(checker_opts=None):
    opts = checker_opts or {}
    return _SetFull(linearizable=opts.get("linearizable?", False))


def expand_queue_drain_ops(hist):
    """Expand ok :drain ops into dequeue invoke/ok pairs
    (checker.clj:594-626)."""
    out = []
    for op in hist:
        if op.get("f") != "drain":
            out.append(op)
        elif h.invoke(op) or h.fail(op):
            continue
        elif h.ok(op):
            for element in op.get("value") or []:
                inv = dict(op)
                inv.update(type="invoke", f="dequeue", value=None)
                comp = dict(op)
                comp.update(type="ok", f="dequeue", value=element)
                out.extend([inv, comp])
        else:
            raise ValueError(
                f"not sure how to handle a crashed drain: {op!r}")
    return out


class _TotalQueue(Checker):
    """Multiset conservation: what goes in must come out
    (checker.clj:628-687)."""

    def check(self, test, hist, opts=None):
        hist = expand_queue_drain_ops(hist)
        attempts = collections.Counter(
            o.get("value") for o in hist
            if h.invoke(o) and o.get("f") == "enqueue")
        enqueues = collections.Counter(
            o.get("value") for o in hist
            if h.ok(o) and o.get("f") == "enqueue")
        dequeues = collections.Counter(
            o.get("value") for o in hist
            if h.ok(o) and o.get("f") == "dequeue")
        ok = dequeues & attempts
        unexpected = collections.Counter(
            {k: v for k, v in dequeues.items() if k not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {"valid": not lost and not unexpected,
                "attempt-count": sum(attempts.values()),
                "acknowledged-count": sum(enqueues.values()),
                "ok-count": sum(ok.values()),
                "unexpected-count": sum(unexpected.values()),
                "duplicated-count": sum(duplicated.values()),
                "lost-count": sum(lost.values()),
                "recovered-count": sum(recovered.values()),
                "lost": dict(lost), "unexpected": dict(unexpected),
                "duplicated": dict(duplicated),
                "recovered": dict(recovered)}


def total_queue():
    return _TotalQueue()


class _UniqueIds(Checker):
    """Are generated IDs distinct? (checker.clj:689-734)"""

    def check(self, test, hist, opts=None):
        attempted = sum(1 for o in hist
                        if h.invoke(o) and o.get("f") == "generate")
        acks = [o.get("value") for o in hist
                if h.ok(o) and o.get("f") == "generate"]
        counts = collections.Counter(acks)
        dups = {k: v for k, v in counts.items() if v > 1}
        rng = [min(acks), max(acks)] if acks else None
        top_dups = dict(sorted(dups.items(), key=lambda kv: -kv[1])[:48])
        return {"valid": not dups,
                "attempted-count": attempted,
                "acknowledged-count": len(acks),
                "duplicated-count": len(dups),
                "duplicated": top_dups,
                "range": rng}


def unique_ids():
    return _UniqueIds()


class _Counter(Checker):
    """Bounds-interval counter check: each read must fall within
    [sum of ok adds at invoke, sum of attempted adds at completion]
    (checker.clj:737-795)."""

    def check(self, test, hist, opts=None):
        hist = [o for o in h.complete(hist)
                if not h.fail(o) and not o.get("fails?")]
        lower = 0
        upper = 0
        pending = {}
        reads = []
        for op in hist:
            key = (op.get("type"), op.get("f"))
            if key == ("invoke", "read"):
                pending[op["process"]] = [lower, op.get("value")]
            elif key == ("ok", "read"):
                r = pending.pop(op["process"], None)
                if r is not None:
                    reads.append(r + [upper])
            elif key == ("invoke", "add"):
                v = op.get("value") or 0
                # a pending add widens the bound in its direction; a
                # negative add lowers the reachable floor instead
                if v >= 0:
                    upper += v
                else:
                    lower += v
            elif key == ("ok", "add"):
                v = op.get("value") or 0
                if v >= 0:
                    lower += v
                else:
                    upper += v
        errors = [r for r in reads
                  if not (r[0] <= r[1] <= r[2])]
        return {"valid": not errors, "reads": reads, "errors": errors}


def counter():
    return _Counter()


class _LogFilePattern(Checker):
    """Greps downloaded node logs in the store dir for a pattern
    (checker.clj:839-881)."""

    def __init__(self, pattern, filename):
        self.pattern = re.compile(pattern)
        self.filename = filename

    def check(self, test, hist, opts=None):
        from .. import store
        try:
            paths = {node: store.path(test, node, self.filename)
                     for node in test.get("nodes", [])}
        except (AssertionError, KeyError):
            return {"valid": "unknown",
                    "error": "no store directory for this test"}
        matches = []
        for node, path in paths.items():
            try:
                with open(path, errors="replace") as f:
                    for line in f:
                        if self.pattern.search(line):
                            matches.append({"node": node,
                                            "line": line.rstrip("\n")})
            except FileNotFoundError:
                continue
        return {"valid": not matches, "count": len(matches),
                "matches": matches}


def log_file_pattern(pattern, filename):
    return _LogFilePattern(pattern, filename)
