"""Sequential Wing-Gong-Lowe linearizability search (CPU oracle).

This is the exact reference implementation the batched TPU engine
(jax_wgl.py) is differential-tested against. It reconstructs the algorithm
knossos.wgl implements (knossos is an external dependency of the reference,
jepsen/project.clj:14, dispatched from jepsen/src/jepsen/checker.clj:199-202;
see SURVEY.md section 2.9) from its published description: depth-first search
over linearization orders with memoized (linearized-bitset, model-state)
configurations.

Given operations sorted by invocation index, with return index INF_TIME for
indeterminate (:info) ops:

* a configuration is (bitset of linearized ops, model state);
* op X may be linearized next iff X is unlinearized and
  invoke(X) < min{return(Y) : Y unlinearized} -- i.e. X is concurrent with
  or precedes every other pending op (real-time order is respected);
* the model step must accept X (not Inconsistent);
* the history is linearizable iff some reachable configuration has all
  :ok ops linearized (:info ops may linearize or silently never happen;
  :fail ops were dropped at encoding).

The search runs directly on the dense tensor encoding, using the same
branch-free model step as the device path (models.base.ModelSpec.step with
xp=numpy), so the two engines share transition semantics by construction.
"""

from __future__ import annotations

import numpy as np

from ..history import INF_TIME


def check_encoded(spec, e, init_state, max_configs=None, cancel=None):
    """Run the WGL search over an EncodedHistory ``e`` with ``init_state``.

    Returns a result dict:
      valid: True | False
      configs_explored: number of distinct configurations visited
      op / final_paths / previous_ok / configs: on failure, the
        knossos-style witness fields (see checker/witness.py).
    """
    n = len(e)
    invoke = e.invoke_idx
    ret_t = e.return_idx
    is_ok = e.is_ok
    full = (1 << n) - 1
    ok_mask = 0
    for i in range(n):
        if is_ok[i]:
            ok_mask |= 1 << i

    if ok_mask == 0:
        return {"valid": True, "configs_explored": 0}

    step = spec.step
    f = e.f
    args = e.args
    rets = e.ret

    init_key = (0, init_state.tobytes())
    seen = {init_key}
    stack = [(0, init_state)]
    explored = 0
    # Track the deepest stuck frontier for the witness: configs maximizing
    # the number of linearized ok ops.
    best_depth = -1
    best_configs = []

    while stack:
        lin, state = stack.pop()
        explored += 1
        if max_configs is not None and explored > max_configs:
            return {"valid": "unknown", "configs_explored": explored,
                    "error": "max-configs-exceeded"}
        if cancel is not None and explored % 4096 == 0 \
                and cancel.is_set():
            return {"valid": "unknown", "configs_explored": explored,
                    "error": "cancelled"}
        unlin = full & ~lin
        # minimum return among unlinearized ops
        r_min = INF_TIME
        m = unlin
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            if ret_t[i] < r_min:
                r_min = ret_t[i]
        depth = (lin & ok_mask).bit_count()
        if depth > best_depth:
            best_depth = depth
            best_configs = []
        progressed = False
        m = unlin
        while m:
            i = (m & -m).bit_length() - 1
            m &= m - 1
            if invoke[i] >= r_min:
                break  # rows sorted by invoke: nothing further qualifies
            state2, ok = step(state, f[i], args[i], rets[i], np)
            if not bool(ok):
                continue
            state2 = np.asarray(state2, np.int32)
            lin2 = lin | (1 << i)
            if (lin2 & ok_mask) == ok_mask:
                return {"valid": True, "configs_explored": explored}
            key = (lin2, state2.tobytes())
            if key not in seen:
                seen.add(key)
                stack.append((lin2, state2))
                progressed = True
        if not progressed and depth == best_depth and len(best_configs) < 8:
            best_configs.append((lin, state))

    # exhausted: not linearizable; decode knossos-style witnesses
    # (op / final_paths / previous_ok / configs -- see checker/witness.py)
    result = {"valid": False, "configs_explored": explored}
    if best_configs:
        # the oracle tracks several distinct deepest configs; decode them
        # through the same multi-config path as the device engine's TOPK
        # slots so the two witness shapes can never drift
        from . import witness
        slots = []
        for lin_x, state in best_configs:
            lx = np.zeros(n, bool)
            for i in range(n):
                lx[i] = bool((lin_x >> i) & 1)
            slots.append((lx, state))
        witness.attach_multi(result, spec, e, slots, init_state)
    return result


def check_history(spec, history, **kw):
    """Encode ``history`` (event dicts) for ``spec`` and run the search."""
    e, init_state = spec.encode(history)
    return check_encoded(spec, e, init_state, **kw)
