"""knossos-parity failure witnesses for the linearizability engines.

knossos.wgl/analysis returns, for an invalid history, ``{:valid? false,
:op, :previous-ok, :configs, :final-paths}`` — a step-by-step path of
``{:op, :model}`` entries to the deepest configuration, the last ok op on
it, and the stuck configurations with their pending candidates. Jepsen
truncates and persists these (reference checker.clj:206-216). The device
engine tracks only the deepest configuration's (linearized-bitset, model
state), so this module reconstructs the rest on host:

* ``final_path`` replays the linearized SET into a legal WGL ORDER
  (depth-first over set members under the real eligibility rule, guided
  by the model's linearization-priority hint) and records the model
  state after every step.
* ``attach`` shapes the knossos-style fields onto a result dict:
  ``final_paths`` (list of paths of ``{"op", "model"}``),
  ``previous_ok`` (last ok op on the first path), and ``configs``
  (``{"model", "last_op", "pending"}`` for the stuck configuration,
  pending = ok/info ops that were WGL-eligible when the search wedged).

Both engines (the sequential oracle and the device search) share this
code, so their failure artifacts are interchangeable.
"""

from __future__ import annotations

import numpy as np

from ..history import INF_TIME

#: keep at most this many trailing steps per reported path (a 10k-op
#: history's full path would dominate results.json; the tail is where
#: the search got stuck, which is the part a human reads)
PATH_TAIL = 100

#: step-attempt budget for the replay DFS; the linearized set came from
#: a real search path so the hint-guided replay almost never backtracks,
#: but an adversarial set could force exponential work
REPLAY_BUDGET = 500_000

#: version tag of the NORMALIZED witness dict (``result["witness"]``)
#: every engine emits -- the one schema the verdict certifier
#: (analysis/certify.py) replays. Bump on any field change; the
#: certifier rejects unknown schemas as malformed (VC005).
WITNESS_SCHEMA = 1


class _RetMin:
    """Segment tree over return indices: global min with O(log n)
    activate/deactivate, for the WGL eligibility rule under DFS
    backtracking."""

    def __init__(self, rets):
        n = max(1, len(rets))
        size = 1
        while size < n:
            size *= 2
        self.size = size
        self.t = np.full(2 * size, INF_TIME, np.int64)
        self.t[size:size + len(rets)] = rets
        for i in range(size - 1, 0, -1):
            self.t[i] = min(self.t[2 * i], self.t[2 * i + 1])
        self.rets = np.asarray(rets, np.int64)

    def set_active(self, i, active):
        j = self.size + i
        self.t[j] = self.rets[i] if active else INF_TIME
        j //= 2
        while j:
            self.t[j] = min(self.t[2 * j], self.t[2 * j + 1])
            j //= 2

    def min(self):
        return self.t[1]


def final_path(spec, e, linearized, init_state, budget=REPLAY_BUDGET):
    """Order the linearized op set into a legal WGL step sequence.

    ``linearized``: bool[n] over ``e``'s rows. Returns a list of
    ``(row_index, state_after)`` or None if the replay budget runs out
    (the witness then stays set-only)."""
    n = len(e)
    member = np.asarray(linearized, bool)
    total = int(member.sum())
    if total == 0:
        return []
    invoke = np.asarray(e.invoke_idx, np.int64)
    rets = np.asarray(e.return_idx, np.int64)
    f = np.asarray(e.f)
    args = np.asarray(e.args).reshape(n, -1)
    rvals = np.asarray(e.ret).reshape(n, -1)

    # candidate order: the model's search hint (same priority the engine
    # used), so the replay follows the search's own footsteps
    if spec.hint is not None:
        from .jax_wgl import _encode_arrays
        inv32, ret32, _ = _encode_arrays(e)
        pri = np.asarray(spec.hint(e, inv32, ret32), np.int64)
    else:
        pri = rets
    members = sorted(np.flatnonzero(member).tolist(),
                     key=lambda i: (pri[i], i))

    tree = _RetMin(rets)

    # doubly-linked list over member positions so each DFS level only
    # scans still-undone members (a flat rescan is quadratic in path
    # length); position `total` is the sentinel head/tail
    head = total
    nxt = list(range(1, total + 1)) + [0]      # nxt[head] = 0
    prv = [head] + list(range(total))          # prv[head] = total - 1

    def remove(j):
        nxt[prv[j]] = nxt[j]
        prv[nxt[j]] = prv[j]

    def restore(j):
        nxt[prv[j]] = j
        prv[nxt[j]] = j

    path = []                 # (row, state_after)
    states = [np.asarray(init_state, np.int32)]
    scan = [nxt[head]]        # per-level next list position to try
    work = budget
    while True:
        if len(path) == total:
            return path
        j = scan[-1]
        state = states[-1]
        taken = False
        while j != head:
            work -= 1
            if work < 0:
                return None
            i = members[j]
            if invoke[i] < tree.min():
                st2, ok = spec.step(state, f[i], args[i], rvals[i], np)
                if bool(ok):
                    st2 = np.asarray(st2, np.int32)
                    tree.set_active(i, False)
                    remove(j)
                    path.append((i, st2))
                    states.append(st2)
                    scan[-1] = j          # resume point on backtrack
                    scan.append(nxt[head])
                    taken = True
                    break
            j = nxt[j]
        if not taken:
            scan.pop()
            states.pop()
            if not path:
                return None
            i, _ = path.pop()
            jprev = scan[-1]
            restore(jprev)
            tree.set_active(i, True)
            scan[-1] = nxt[jprev]


def _decode_op(e, i):
    if e.ops is not None and i < len(e.ops):
        inv, comp = e.ops[i]
        return dict(comp if comp is not None else inv)
    return {"row": int(i)}


def _decode_state(spec, state):
    state = np.asarray(state)
    if spec.decode_state is not None:
        try:
            return spec.decode_state(state)
        except Exception:  # noqa: BLE001 - padding etc: fall through
            pass
    return state.tolist()


def config_entry(spec, e, linearized, state, last_op=None):
    """One knossos-style stuck-config map: the model state plus the ops
    still open under the WGL rule at this configuration (invoked before
    every unlinearized return)."""
    rets = np.asarray(e.return_idx, np.int64)
    invoke = np.asarray(e.invoke_idx, np.int64)
    un = ~np.asarray(linearized, bool)
    rmin = rets[un].min() if un.any() else INF_TIME
    pending = np.flatnonzero(un & (invoke < rmin))
    return {"model": _decode_state(spec, state),
            "last_op": last_op,
            "pending": [_decode_op(e, int(i)) for i in pending[:16]]}


def _witness_dict(spec, e, engine, valid, linearized, path,
                  fallback_state):
    """The normalized witness dict (schema ``WITNESS_SCHEMA``) built
    from an already-computed replay ``path`` (or None when the replay
    budget ran out). This is the ONE shape all engines emit -- the
    device single-key search, the keyshard batch, the mesh-sharded
    search, and the CPU engines -- so one certifier reads all of
    them. Fields:

      schema: WITNESS_SCHEMA
      engine: the producing engine's name (None when the caller sets
        none, e.g. the bare CPU oracle before competition labels it)
      verdict: the verdict this witness supports -- True: ``order`` is
        a claimed legal linearization covering every ok op; False: the
        deepest stuck configuration the search reached
      rows / n_ok: the encoded-history shape the row indices refer to
      linearized_rows: sorted encoded-row indices in the configuration
      order: those rows as a legal WGL step sequence, or None when the
        replay budget ran out (the set is then unordered)
      final_state: decoded model state after the last ordered step
      segment: searchplan provenance {"index", "count", "seed"} filled
        in by the planned batch path, else None
    """
    linearized = np.asarray(linearized, bool)
    state = path[-1][1] if path else fallback_state
    return {"schema": WITNESS_SCHEMA,
            "engine": engine,
            "verdict": bool(valid),
            "rows": int(len(e)),
            "n_ok": int(e.n_ok),
            "linearized_rows": [int(i)
                                for i in np.flatnonzero(linearized)],
            "order": ([int(i) for i, _ in path]
                      if path is not None else None),
            "final_state": _decode_state(spec, state),
            "segment": None}


def build(spec, e, engine, valid, linearized, init_state,
          budget=REPLAY_BUDGET):
    """Build a normalized witness for ``linearized`` from scratch:
    replay the set into a legal order (final_path) and shape the
    schema-``WITNESS_SCHEMA`` dict. Used by the engines' VALID paths,
    where no knossos-style attach ran to compute the path already."""
    linearized = np.asarray(linearized, bool)
    path = final_path(spec, e, linearized, init_state, budget=budget)
    return _witness_dict(spec, e, engine, valid, linearized, path,
                         np.asarray(init_state, np.int32))


def attach(result, spec, e, linearized, best_state, init_state):
    """Shape knossos-style witness fields onto ``result`` (mutates and
    returns it). ``linearized``: bool[n] of the deepest configuration."""
    linearized = np.asarray(linearized, bool)
    is_ok = np.asarray(e.is_ok, bool)
    stuck = np.flatnonzero(is_ok & ~linearized)
    if len(stuck):
        result["op"] = _decode_op(e, int(stuck[0]))
    result["final_state"] = _decode_state(spec, best_state)
    result["linearized_ok_ops"] = int((linearized & is_ok).sum())

    path = final_path(spec, e, linearized, init_state)
    # the machine-checkable counterpart of the knossos fields below:
    # one normalized dict the certifier replays, same path, no extra
    # search work
    result["witness"] = _witness_dict(
        spec, e, result.get("engine"), result.get("valid", False),
        linearized, path, np.asarray(best_state, np.int32))
    if path is not None:
        tail = path[-PATH_TAIL:]
        steps = [{"op": _decode_op(e, i),
                  "model": _decode_state(spec, st)} for i, st in tail]
        result["final_paths"] = [steps]
        if len(path) > len(tail):
            result["final_paths_truncated_steps"] = len(path) - len(tail)
        result["previous_ok"] = next(
            (_decode_op(e, i) for i, _ in reversed(path) if e.is_ok[i]),
            None)

    result["configs"] = [config_entry(
        spec, e, linearized, best_state,
        last_op=_decode_op(e, path[-1][0]) if path else None)]
    return result


def attach_multi(result, spec, e, slots, init_state):
    """Multi-config variant of ``attach``: ``slots`` is a list of
    (linearized bool[n], state) deepest-first. The primary witness
    fields (op / final_paths / previous_ok) decode from slot 0; EVERY
    slot contributes a stuck-config entry with its own pending set
    (knossos returns up to 10 :configs, reference checker.clj:213-216;
    round 3 only ever produced one)."""
    if not slots:
        return result
    linearized, state = slots[0]
    attach(result, spec, e, linearized, state, init_state)
    result["configs"] = result["configs"] + [
        config_entry(spec, e, lin_s, st_s) for lin_s, st_s in slots[1:]]
    return result
