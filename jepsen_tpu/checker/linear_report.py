"""Failure-witness rendering for invalid linearizability analyses: the
analogue of knossos.linear.report's linear.svg (the reference renders it
from the checker at checker.clj:206-212).

Draws the neighborhood of the stuck operation as per-process bars over
time — the witness op in red, ops concurrent with it highlighted — and
annotates the model states that were still reachable when the search got
stuck."""

from __future__ import annotations

import logging

from .. import history as h
from .perf import _out_path

logger = logging.getLogger(__name__)

#: how many ops around the witness to draw
WINDOW = 30


def _op_intervals(history):
    """[(invoke_op, completion_op_or_None)] with numeric processes."""
    return [(inv, comp) for inv, comp in h.pairs(history)
            if inv is not None and isinstance(inv.get("process"), int)]


def _overlaps(a0, a1, b0, b1):
    return a0 <= b1 and b0 <= a1


def render_analysis(test, history, analysis, opts=None):
    """Render linear.png next to the other artifacts; returns the path,
    or None when there's nothing to draw."""
    op = analysis.get("op")
    if op is None or not history:
        return None
    pairs = _op_intervals(history)
    if not pairs:
        return None

    t_end = max(op_.get("time", 0) for op_ in history)

    def interval(inv, comp):
        t0 = inv.get("time", 0)
        t1 = comp.get("time", t_end) if comp is not None else t_end
        return t0, max(t1, t0)

    # locate the witness pair: same process + f + index if present
    def is_witness(inv, comp):
        cand = comp if comp is not None else inv
        if op.get("index") is not None and cand.get("index") is not None:
            return cand["index"] == op["index"] or \
                inv.get("index") == op.get("index")
        return (cand.get("process") == op.get("process")
                and cand.get("f") == op.get("f")
                and cand.get("value") == op.get("value"))

    wpair = next(((inv, comp) for inv, comp in pairs
                  if is_witness(inv, comp)), None)
    if wpair is None:
        wpair = pairs[-1]
    w0, w1 = interval(*wpair)

    # keep ops overlapping the witness, then nearest others, cap WINDOW
    def sort_key(pair):
        t0, t1 = interval(*pair)
        if _overlaps(t0, t1, w0, w1):
            return (0, t0)
        return (1, min(abs(t0 - w1), abs(w0 - t1)))

    chosen = sorted(pairs, key=sort_key)[:WINDOW]
    if wpair not in chosen:      # never truncate away the witness itself
        chosen[-1] = wpair
    chosen.sort(key=lambda p: interval(*p)[0])

    path = _out_path(test, opts or {}, "linear.png")
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.patches import Rectangle
    procs = sorted({inv["process"] for inv, _ in chosen})
    ys = {p: i for i, p in enumerate(procs)}
    fig, ax = plt.subplots(
        figsize=(10, 0.5 * max(4, len(procs)) + 1.2))
    try:
        for inv, comp in chosen:
            t0, t1 = interval(inv, comp)
            t0, t1 = t0 / 1e9, t1 / 1e9
            y = ys[inv["process"]]
            witness = (inv, comp) == wpair
            cand = comp if comp is not None else inv
            color = ("#B31B1B" if witness else
                     "#7FA3CC" if cand.get("type") == "ok" else
                     "#C9B458" if cand.get("type") == "info" else
                     "#AAAAAA")
            ax.add_patch(Rectangle((t0, y - 0.35),
                                   max(t1 - t0, (w1 - w0) / 1e9 / 50
                                       or 1e-6),
                                   0.7, facecolor=color,
                                   edgecolor="black", lw=0.5))
            label = f"{cand.get('f')} {cand.get('value')!r}"
            ax.text(t0, y, label[:28], fontsize=6, va="center",
                    ha="left", clip_on=True)
        ax.set_yticks(range(len(procs)))
        ax.set_yticklabels([f"process {p}" for p in procs], fontsize=7)
        ax.set_ylim(-0.8, len(procs) - 0.2)
        xs = [t / 1e9 for p_ in chosen for t in interval(*p_)]
        ax.set_xlim(min(xs), max(xs) * 1.02 + 1e-6)
        ax.set_xlabel("Time (s)")
        states = [c.get("model") for c in
                  (analysis.get("configs") or [])[:4]
                  if isinstance(c, dict) and c.get("model") is not None]
        title = (f"{test.get('name', 'test')}: not linearizable — "
                 f"stuck before {op.get('f')} {op.get('value')!r} "
                 f"(process {op.get('process')})")
        if states:
            title += f"\nreachable model states: {states}"
        prev = analysis.get("previous_ok")
        if prev:
            title += (f"\nlast linearized ok op: {prev.get('f')} "
                      f"{prev.get('value')!r} "
                      f"(process {prev.get('process')})")
        ax.set_title(title, fontsize=8)
        fig.tight_layout()
        fig.savefig(path, dpi=120)
    finally:
        plt.close(fig)
    return path
