"""The monitor thread: consume the live op stream, extend the WGL
verdict chunk by chunk, abort the run on violation.

Threading contract:

* ``offer(op)`` runs on the interpreter's event-loop thread for every
  history op, after serial-stripping and zombie filtering (the op-sink
  fan-out in interpreter.py). It appends to a deque and occasionally
  notifies -- the whole per-op cost the interpreter pays.
* one daemon thread (``jepsen monitor``) drains the deque, feeds the
  per-key `StreamEncoder`s, and runs a prefix check over every key
  that saw new completions once ``chunk`` completions accumulated.
* ``stop()`` is idempotent and bounded: it asks the thread to finish
  (draining + one final check so the verdict covers everything
  consumed), joins with a timeout, and cancels a wedged device check
  through the engines' ``cancel`` event rather than waiting forever.

Verdict semantics: the monitor re-checks the *prefix*, so its False is
exactly the offline checker's False on the same cut -- the acceptance
property tests equivalence across chunk sizes. "unknown" checks never
abort anything; they are counted and the offline checker keeps the
final word.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time as _time

from .. import independent
from .. import obs
from .. import robust
from ..obs import phases as obs_phases
from ..checker.core import merge_valid
from . import engine as mengine
from .stream import StreamEncoder

logger = logging.getLogger(__name__)

__all__ = ["DEFAULT_CHUNK", "Monitor", "config", "find_linearizable",
           "install", "finalize"]

#: completed client ops per monitor step (pow-2 so encoded prefixes
#: cross shape buckets as rarely as possible)
DEFAULT_CHUNK = 64

#: bounded join for the monitor thread at stop(); a device check that
#: outlives this is cancelled, then given a short grace
STOP_JOIN_S = 60.0
CANCEL_JOIN_S = 5.0

#: latch reason for monitor-triggered aborts (campaign outcome logic
#: and docs key off this string)
ABORT_REASON = "monitor-violation"


def config(test):
    """Normalize ``test["monitor"]`` (True | chunk int | options dict)
    into an options dict, or None when monitoring is off. Recognized
    keys: chunk, engine, engine-opts, skip-offline?, final?,
    quiescent-carry? (default True: truncate proven prefixes at sealed
    quiescent cuts so chunk checks stay O(window))."""
    mon = test.get("monitor")
    if not mon:
        return None
    if mon is True:
        cfg = {}
    elif isinstance(mon, int) and not isinstance(mon, bool):
        cfg = {"chunk": mon}
    elif isinstance(mon, dict):
        cfg = dict(mon)
    else:
        logger.warning("unrecognized test['monitor'] %r: monitoring "
                       "disabled", mon)
        return None
    if test.get("monitor-chunk") is not None:
        cfg.setdefault("chunk", test["monitor-chunk"])
    return cfg


def find_linearizable(checker):
    """Walk a checker tree to the Linearizable gate. Returns
    (linearizable, keyed) -- keyed True when the gate sits under an
    independent checker (ops carry [k v] tuples) -- or (None, False)
    when the family has no incremental engine (e.g. the cycle
    checker)."""
    from ..checker.checkers import Linearizable
    seen = set()

    def walk(c, keyed):
        if c is None or id(c) in seen:
            return None
        seen.add(id(c))
        if isinstance(c, Linearizable):
            return c, keyed
        if isinstance(c, independent._IndependentChecker):
            return walk(c.inner, True)
        # unwrap the common single-child wrappers (device-slot,
        # concurrency-limit) by attribute convention
        for attr in ("inner", "checker"):
            child = getattr(c, attr, None)
            if child is not None and child is not c:
                got = walk(child, keyed)
                if got is not None:
                    return got
        cmap = getattr(c, "checker_map", None)
        if isinstance(cmap, dict):
            for child in cmap.values():
                got = walk(child, keyed)
                if got is not None:
                    return got
        return None

    got = walk(checker, False)
    return got if got is not None else (None, False)


class Monitor:
    """One run's streaming monitor. Build via `install(test)`."""

    def __init__(self, spec, latch, chunk=DEFAULT_CHUNK,
                 engine="jax-wgl", engine_opts=None, init_ops=(),
                 keyed=False, device_sem=None, quiescent_carry=True):
        self.spec = spec
        self.latch = latch
        self.chunk = max(1, int(chunk))
        self.engine = engine
        self.engine_opts = dict(engine_opts or {})
        self.init_ops = list(init_ops or ())
        self.keyed = keyed
        self.device_sem = device_sem
        #: quiescent-cut carry (analysis/searchplan.py): after a True
        #: prefix verdict, the encoder truncates to the latest sealed
        #: quiescent cut, so crash-free monitored runs re-check
        #: O(window) instead of O(prefix). Off via the monitor config
        #: {"quiescent-carry?": False} (planlint PL015 flags that).
        self.quiescent_carry = bool(quiescent_carry)
        self.truncated_ops = 0
        self.violation = None
        #: certifiable violation evidence (encoded prefix + engine
        #: result), parked on the test map by finalize for the
        #: certify backstop in core.analyze
        self.evidence = None
        # sinks captured at construction through the RUN-SCOPED
        # resolution (install runs on the run's own thread inside
        # obs.run_scope): overlapping campaign cells must not
        # cross-attribute monitor telemetry through the
        # last-binder-wins process-global binding
        self._tr, self._reg = obs.current_sinks()
        self._cancel = threading.Event()
        self._cond = threading.Condition()
        self._queue = collections.deque()   # (op, index, t_offer)
        self._pending_completions = 0
        self._n_seen = 0
        self._stopping = False
        self._finish = True
        self._encoders = {}                 # key -> StreamEncoder
        self._dirty = {}                    # key -> t_offer of newest op
        self._verdicts = {}                 # key -> last check validity
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="jepsen monitor")
        # counters mirrored into the summary (registry may be absent)
        self.ops_consumed = 0
        self.chunks = 0
        self.checks = 0
        self.unknown_checks = 0
        self.unkeyed_skipped = 0
        self._t_start = _time.monotonic()
        self._t_first_verdict = None

    # -- interpreter side --------------------------------------------------

    def offer(self, op):
        """Op-sink entry: called on the event-loop thread per history
        op. O(1); never raises."""
        try:
            with self._cond:
                idx = self._n_seen
                self._n_seen += 1
                if self.violation is not None or self._stopping:
                    return
                self._queue.append((op, idx, _time.monotonic()))
                if op.get("type") != "invoke" \
                        and isinstance(op.get("process"), int):
                    self._pending_completions += 1
                    if self._pending_completions >= self.chunk:
                        self._cond.notify()
        except Exception:  # noqa: BLE001 - must never hurt the run
            logger.warning("monitor offer failed", exc_info=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread.start()
        return self

    def stop(self, finish=True, timeout_s=STOP_JOIN_S):
        """Ask the thread to wrap up and join (idempotent). With
        ``finish`` the thread drains the queue and runs one last check
        over every dirty key, so the summary verdict covers the whole
        consumed stream; without it (crash paths) the thread exits at
        the next opportunity."""
        with self._cond:
            self._stopping = True
            self._finish = self._finish and finish
            self._cond.notify_all()
        if not self._thread.is_alive():
            return
        self._thread.join(timeout_s)
        if self._thread.is_alive():
            self._cancel.set()
            self._thread.join(CANCEL_JOIN_S)
            if self._thread.is_alive():
                logger.warning("monitor thread did not exit; abandoning")
                self._inc("robust.leaked_threads")

    # -- summary -----------------------------------------------------------

    def summary(self):
        """The ``results["monitor"]`` block."""
        if self.violation is not None:
            verdict = False
        else:
            verdict = merge_valid(self._verdicts.values()) \
                if self._verdicts else True
        out = {
            "verdict": verdict,
            "engine": self.engine,
            "chunk": self.chunk,
            "ops_consumed": self.ops_consumed,
            "chunks": self.chunks,
            "checks": self.checks,
            "unknown_checks": self.unknown_checks,
            "keys": len(self._encoders),
            "time_to_first_verdict_s": self._t_first_verdict,
        }
        if self.unkeyed_skipped:
            out["unkeyed_ops_skipped"] = self.unkeyed_skipped
        if self.quiescent_carry:
            out["quiescent_truncated_ops"] = self.truncated_ops
        stream = self._stream_summary()
        if stream is not None:
            out["stream"] = stream
        if self.violation is not None:
            out.update(self.violation)
        return out

    #: stream counters reported as the max across keys (sizes/capacities
    #: describe a single stream's state, not fleet-wide volume)
    _STREAM_MAX_KEYS = ("frontier_size", "frontier_peak", "frontier_cap",
                        "window", "open_slots", "batch_peak")

    def _stream_summary(self):
        """Aggregate per-key StreamCheck telemetry (engine streamlin
        only): counters sum across keys, sizes take the max, and the
        first fall-back reason is surfaced so an accidentally-degraded
        run is visible in results["monitor"]["stream"]."""
        blocks = [enc.stream_summary() for enc in self._encoders.values()
                  if callable(getattr(enc, "stream_summary", None))]
        if not blocks:
            return None
        out = {}
        for b in blocks:
            for k, v in b.items():
                if k == "fallback":
                    out.setdefault(k, v)
                elif k in self._STREAM_MAX_KEYS:
                    out[k] = max(out.get(k, 0), v)
                else:
                    out[k] = out.get(k, 0) + v
        if "device_s" in out:
            out["device_s"] = round(out["device_s"], 4)
        return out

    # -- monitor thread ----------------------------------------------------

    def _inc(self, name, n=1, **labels):
        if self._reg is not None:
            self._reg.inc(name, n, **labels)

    def _span(self, name, **args):
        if self._tr is None:
            return contextlib.nullcontext()
        return self._tr.span(name, cat="monitor", args=args or None)

    def _encoder(self, key):
        enc = self._encoders.get(key)
        if enc is None:
            if self.engine == "streamlin":
                # the device-resident frontier driver; duck-types the
                # StreamEncoder surface and adds check(). Contained: a
                # construction failure falls back to the plain encoder
                # (whose checks then run streamlin's flat face)
                try:
                    from .wgl_stream import StreamCheck
                    enc = StreamCheck(self.spec, self.init_ops,
                                      opts=self.engine_opts)
                except Exception:  # noqa: BLE001
                    logger.warning("StreamCheck init failed; flat "
                                   "re-checks", exc_info=True)
            if enc is None:
                enc = StreamEncoder(self.spec, self.init_ops)
            self._encoders[key] = enc
        return enc

    def _consume(self, op, idx, t):
        """Feed one event into the right encoder; count completions."""
        if not isinstance(op.get("process"), int):
            return
        if self.keyed:
            v = op.get("value")
            if not independent.is_tuple(v):
                # independent.subhistory replicates un-keyed client ops
                # into every key; the stream can't (later keys don't
                # exist yet), so they are skipped and counted --
                # doc/monitoring.md spells out the caveat
                self.unkeyed_skipped += 1
                self._inc("monitor.unkeyed_ops_skipped")
                return
            op = dict(op)
            op["value"] = v.value
            key = v.key
        else:
            key = None
        enc = self._encoder(key)
        if enc.offer(op, idx):
            self.ops_consumed += 1
            self._inc("monitor.ops_consumed")
            self._dirty[key] = max(self._dirty.get(key, 0.0), t)

    def _check_key(self, key, t_newest):
        """Materialize + check one key's prefix; returns its validity
        and records a violation on False."""
        enc = self._encoders[key]
        stream = callable(getattr(enc, "check", None))
        e = init_state = None
        if not stream:
            # streamlin keeps the encoded prefix device-resident; the
            # host only materializes it on the flat paths (carry cuts,
            # violation evidence) below
            e, init_state = enc.materialize()
        t0 = _time.monotonic()
        sem = self.device_sem \
            if self.engine in ("jax-wgl", "streamlin") else None
        if sem is not None:
            t_w = _time.monotonic()
            sem.acquire()
            self._inc("monitor.device_waits")
            if self._reg is not None:
                self._reg.observe("monitor.device_wait_s",
                                  _time.monotonic() - t_w)
            # device-slot wait is a named phase in the attribution
            # plane: the engine's own phase spans start only once the
            # semaphore admits the check
            obs_phases.note_wait(self.engine,
                                 _time.monotonic() - t_w)
        try:
            with self._span("monitor.check", key=repr(key),
                            n=len(enc)):
                if stream:
                    r = enc.check(cancel=self._cancel)
                else:
                    r = mengine.check_prefix(
                        self.spec, e, init_state, self.engine,
                        self.engine_opts, cancel=self._cancel)
        finally:
            if sem is not None:
                sem.release()
        dt = _time.monotonic() - t0
        self.checks += 1
        valid = r.get("valid")
        self._inc("monitor.checks", valid=str(valid))
        if self._reg is not None:
            self._reg.observe("monitor.check_s", dt)
        if self._t_first_verdict is None and valid in (True, False):
            self._t_first_verdict = round(
                _time.monotonic() - self._t_start, 4)
            if self._reg is not None:
                self._reg.set_gauge("monitor.time_to_first_verdict_s",
                                    self._t_first_verdict)
        if valid is True and self.quiescent_carry:
            # the whole consumed prefix just proved linearizable:
            # carry the latest sealed quiescent cut so the next check
            # covers only the open window, not the ever-growing prefix
            # (decrease-and-conquer, arxiv 2410.04581). Contained: a
            # carry bug must never change a verdict, only cost —
            # UNLESS skip-offline? hands the monitor verdict over as
            # final, where the carry is verdict-bearing (PL015 warns
            # on that combination).
            try:
                from ..analysis import searchplan
                if e is None:
                    e, init_state = enc.materialize()
                cut = searchplan.stream_cut(self.spec, e)
                if cut is not None:
                    dropped = enc.truncate_before(*cut)
                    if dropped:
                        self.truncated_ops += dropped
                        self._inc("monitor.quiescent_truncated_ops",
                                  dropped)
            except Exception:  # noqa: BLE001 - telemetry-grade only
                logger.warning("quiescent-cut carry failed",
                               exc_info=True)
        if valid == "unknown":
            self.unknown_checks += 1
            # an undecided check leaves the key "unknown" until a
            # LATER check decides: checks are cumulative prefixes, so
            # a later True covers every earlier cut (prefix-closure of
            # linearizability) and overwrites this. Without the
            # degrade, an all-unknown run would summarize as verdict
            # True -- and with skip-offline? be recorded valid with
            # no check ever deciding. False stays sticky (it can
            # never unhappen, and it already aborted the run).
            if self._verdicts.get(key) is not False:
                self._verdicts[key] = "unknown"
            return "unknown"
        self._verdicts[key] = valid
        if valid is False and self.violation is None:
            if e is None:
                e, init_state = enc.materialize()
            latency = max(0.0, _time.monotonic() - t_newest)
            self.violation = {
                "detected_at_index": enc.last_index,
                "detection_latency_s": round(latency, 4),
                "checked_ops": len(e),
            }
            if self.keyed:
                self.violation["key"] = key
            w = r.get("op")
            if isinstance(w, dict):
                self.violation["detected_op"] = dict(w)
            # park the certifiable evidence: the encoded prefix that
            # decided False plus the engine result. core.analyze's
            # certify backstop replays the witness and cross-checks
            # the prefix through an INDEPENDENT engine — under
            # ``skip-offline?`` this verdict becomes the verdict of
            # record with no full offline check behind it
            self.evidence = {"e": e, "init_state": init_state,
                             "result": r, "key": key}
            self._inc("monitor.violations")
            if self._reg is not None:
                self._reg.set_gauge("monitor.detection_latency_s",
                                    self.violation["detection_latency_s"])
            if self._tr is not None:
                self._tr.instant("monitor.violation", cat="monitor",
                                 args=dict(self.violation,
                                           detected_op=None))
            logger.warning(
                "MONITOR: non-linearizable prefix detected at history "
                "index %d%s (%.3fs after the op landed); aborting run",
                enc.last_index,
                f" key {key!r}" if self.keyed else "", latency)
            self.latch.set(ABORT_REASON)
        return valid

    def _step(self):
        """Drain the queue and check every key that saw new
        completions (called per chunk, and once more at stop for the
        final flush)."""
        with self._cond:
            batch = list(self._queue)
            self._queue.clear()
            self._pending_completions = 0
        for op, idx, t in batch:
            self._consume(op, idx, t)
        if not self._dirty:
            return
        self.chunks += 1
        self._inc("monitor.chunks")
        dirty, self._dirty = self._dirty, {}
        for key in sorted(dirty, key=repr):
            if self.violation is not None or self._cancel.is_set():
                return
            self._check_key(key, dirty[key])

    def _run(self):
        # the monitor thread starts with an empty contextvars context;
        # pin the pair captured at construction as the run-scoped
        # sinks so the device checks it drives (and their search
        # heartbeats) land in THIS run's series, not whichever
        # overlapping cell holds the process-global binding
        with obs.sink_scope(self._tr, self._reg), \
                self._span("monitor.run", engine=self.engine,
                           chunk=self.chunk):
            while True:
                with self._cond:
                    while (self._pending_completions < self.chunk
                           and not self._stopping
                           and self.violation is None):
                        self._cond.wait(0.25)
                    stopping = self._stopping
                if self.violation is not None:
                    break
                if stopping:
                    if self._finish and not self._cancel.is_set():
                        self._step()
                    break
                self._step()


def _searchplan_segments_on(test):
    """searchplan.segments_enabled, contained: the carry defaults ON
    when the reflection itself fails (matching the pre-gate default),
    never crashes install."""
    try:
        from ..analysis import searchplan
        return searchplan.segments_enabled(test)
    except Exception:  # noqa: BLE001 - best-effort gate
        return bool(test.get("searchplan?", True))


def install(test):
    """Wire a Monitor into a prepared test map (``core.run`` calls
    this after preflight): discover the Linearizable gate in the
    test's checker tree, chain a per-run abort latch over
    ``test["abort"]``, subscribe to the interpreter's op-sink list,
    and start the thread. Returns the Monitor, or None when
    monitoring is off/unavailable (never raises)."""
    cfg = config(test)
    if cfg is None:
        return None
    if cfg.get("family") == "txn":
        # transactional family: no linearizable gate to discover; the
        # cycle engine's incremental frontier is the streaming check
        from . import txn as mtxn
        return mtxn.install_txn(test, cfg)
    try:
        lin, keyed = find_linearizable(test.get("checker"))
        if lin is None:
            logger.warning(
                "monitor requested but the checker tree has no "
                "linearizable gate (no incremental engine for this "
                "family); monitoring disabled for this run")
            obs.inc("monitor.disabled", reason="no-engine")
            return None
        engine = cfg.get("engine")
        if engine is None:
            engine = lin.algorithm if lin.algorithm in mengine.ENGINES \
                else "jax-wgl"
        latch = robust.ChainedLatch(test.get("abort"))
        test["abort"] = latch
        mon = Monitor(
            spec=lin.spec, latch=latch,
            chunk=cfg.get("chunk") or DEFAULT_CHUNK,
            engine=engine,
            engine_opts=cfg.get("engine-opts") or lin.engine_opts,
            init_ops=lin.init_ops, keyed=keyed,
            device_sem=test.get("monitor-device-sem"),
            # the carry honors BOTH knobs: its own monitor option and
            # the test-wide searchplan gate INCLUDING the predicate
            # list (a user disabling the planner or just the
            # crash-segments predicate to rule the cut code out must
            # actually stop it running; planlint PL015 warns either
            # way)
            quiescent_carry=(cfg.get("quiescent-carry?", True)
                             and _searchplan_segments_on(test)))
        test.setdefault("op-sinks", []).append(mon.offer)
        obs.inc("monitor.installed", engine=engine)
        return mon.start()
    except Exception:  # noqa: BLE001 - a monitor bug must not kill runs
        logger.warning("monitor install failed; continuing unmonitored",
                       exc_info=True)
        return None


def finalize(mon, test, finish=True):
    """Stop a Monitor and park its summary on the test map
    (idempotent; ``core.run`` calls it on every exit path before
    analyze so the verdict lands in results.json + monitor.json)."""
    if mon is None:
        return None
    try:
        mon.stop(finish=finish)
        summary = mon.summary()
        test["monitor-verdict"] = summary
        if mon.evidence is not None:
            # non-serializable (ndarrays + spec): store.py strips it;
            # core.analyze pops it for the certify backstop
            test["monitor-evidence"] = dict(mon.evidence,
                                            spec=mon.spec)
        sinks = test.get("op-sinks")
        if isinstance(sinks, list) and mon.offer in sinks:
            sinks.remove(mon.offer)
        return summary
    except Exception:  # noqa: BLE001
        logger.warning("monitor finalize failed", exc_info=True)
        return None
