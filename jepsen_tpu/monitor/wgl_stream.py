"""StreamCheck: the monitor-side driver for the device-resident
frontier (``checker/streamlin.py``).

The naive carry -- fold every chunk's events into a persistent
frontier -- is UNSOUND: a config may speculatively linearize a
still-open op using its unknown (NIL) result, and when the concrete
result lands later the offline sweep would have pruned differently.
The fix is the **stable horizon**:

    horizon = min(invoke index) over TRULY-OPEN rows
              (awaiting a completion -- ``StreamEncoder._open``)

Every event before the horizon belongs to a row whose encoding is
final: completed-ok rows carry their concrete result, info rows stay
NIL *forever* (an info can never be re-encoded). So each chunk check
runs at most three device steps, none of which grows with the prefix:

* **upload** -- scatter the chunk's new/re-encoded rows into the
  device-resident window tensors (the StreamEncoder's device half:
  the host never re-materializes the encoding on this path);
* **seal** -- fold events that crossed the horizon into the committed
  frontier, exactly once per event (amortized O(1)/event over the
  stream's life). Fully-sealed slots recycle: their bit is set in
  every surviving config, so a uniform mask clears them for reuse;
* **probe** -- fold the open-window events [horizon, now) from the
  sealed frontier and read the verdict; the probe frontier is
  discarded (those rows may still re-encode).

Seal+probe sweep the identical event sequence with identical encoded
data as the offline engine on the full prefix, so verdicts are
EXACTLY the offline engine's. Containment on every edge:

* frontier overflow pow-2-grows through ``compile_cache.bucket_for``
  up to the configured cap; past it a SEAL overflow degrades the
  stream permanently to flat re-checks and a PROBE overflow falls
  back flat for that one chunk (counted, never verdict-flipping);
* dynamic-state-size models (queues) and window-slot exhaustion
  degrade to flat re-checks the same way;
* a False frontier verdict is a *suspicion*: the flat engine re-checks
  the materialized prefix and owns the verdict of record, the witness
  artifact set, and the certify-backstop evidence (the monitor/txn.py
  contract) -- so a fingerprint collision can cost a confirm, never a
  wrong verdict.

Chunk folds route through the fleet Coalescer when one is configured
(``fleet.service``): hundreds of monitored streams share padded
``(streamlin:<model>, event bucket)`` device batches like /api/check
tenants, with per-stream deadline isolation and solo fall-back intact.
"""

from __future__ import annotations

import logging
import time as _time

import numpy as np

from .. import obs
from ..checker import streamlin
from ..obs import search as obs_search
from .stream import StreamEncoder

logger = logging.getLogger(__name__)

__all__ = ["StreamCheck", "FOLD_DEADLINE_S"]

#: per-fold coalescer deadline: a fold is one bounded dispatch, so a
#: generous budget only matters when the batcher is wedged -- after it
#: the stream folds solo (containment, not verdict)
FOLD_DEADLINE_S = 30.0


class _Degrade(Exception):
    """Internal: permanently degrade this stream to flat re-checks."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


def _bucket(x, lo=1):
    from ..campaign import compile_cache
    return compile_cache.bucket(x, lo)


class StreamCheck:
    """One monitored stream's incremental checker. Duck-types the
    StreamEncoder surface the monitor uses (``offer`` / ``last_index``
    / ``materialize`` / ``truncate_before`` / ``__len__``) and adds
    ``check(cancel)`` -- Monitor._check_key calls it instead of
    materialize+check_prefix when the engine is ``streamlin``."""

    def __init__(self, spec, init_ops=(), opts=None, owner="monitor"):
        opts = dict(opts or {})
        self.spec = spec
        self.enc = StreamEncoder(spec, init_ops)
        cap = int(opts.get("frontier-cap")
                  or streamlin.DEFAULT_FRONTIER_CAP)
        self.frontier_cap = min(streamlin.FRONTIER_CAP_MAX,
                                _bucket(max(1, cap)))
        self.window_cap = _bucket(int(opts.get("window-cap")
                                      or streamlin.DEFAULT_WINDOW_CAP))
        self.coalesce = bool(opts.get("coalesce?", True))
        self.confirm_engine = opts.get("confirm-engine") or "jax-wgl"
        self.confirm_opts = opts.get("confirm-opts")
        self.owner = str(opts.get("owner") or owner)
        self._tr, self._reg = obs.current_sinks()
        self.so = obs_search.capture()
        # counters (stream_summary + the monitor.* registry series)
        self.checks = 0
        self.seal_folds = 0
        self.probe_folds = 0
        self.fold_passes = 0
        self.fold_cells = 0
        self.frontier_grows = 0
        self.window_grows = 0
        self.flat_checks = 0
        self.probe_overflows = 0
        self.confirm_mismatches = 0
        self.coalesced_folds = 0
        self.solo_folds = 0
        #: widest device batch any of this stream's folds rode (>= 2
        #: proves strangers' streams actually shared a dispatch)
        self.batch_peak = 1
        self.sealed_rows = 0
        self.frontier_size = 1
        self.frontier_peak = 1
        self.device_s = 0.0
        #: non-None once the stream degraded to flat re-checks, with
        #: the reason (fallbacks are permanent except probe overflow)
        self.fallback = None
        # streamlin needs a fixed state width: the frontier tensor is
        # (F, S) and carries across chunks, so S must not depend on
        # the (growing) encoded history
        try:
            self.S = int(spec.state_size(None))
        except Exception:  # noqa: BLE001 - e.g. queues: len(e)-sized
            self.S = None
            self.fallback = "dynamic-state-size"
        # host bookkeeping for the device window
        self.F = None               # frontier rows (set at first check)
        self.NW = streamlin.WINDOW_FLOOR
        self.C = streamlin.OPEN_FLOOR
        self._committed = None      # (lin, st, live, open_w)
        self._window = None         # (w_f, w_args, w_ret)
        self._free = list(range(self.NW - 1, -1, -1))
        self._slot_by_row = {}      # id(row) -> slot
        self._row_by_slot = {}      # slot -> row (pins the row object)
        self._open_committed = 0    # open slots in the COMMITTED set
        self._pending = []          # (t, kind, row): unsealed events
        self._dirty = {}            # slot -> row awaiting upload
        self._planned = False
        if self.fallback is None:
            for row in self.enc.rows:   # init_ops: already-closed rows
                self._admit(row)

    # -- encoder surface (Monitor duck-typing) --------------------------

    def __len__(self):
        return len(self.enc)

    @property
    def last_index(self):
        return self.enc.last_index

    @property
    def skipped(self):
        return self.enc.skipped

    def materialize(self):
        return self.enc.materialize()

    def truncate_before(self, cut_invoke_idx, seed_invoke_idx=None):
        # quiescent-cut carry (PR 7): bounds the FLAT fall-back's
        # materialized prefix; the device window tracks rows on its
        # own, so truncation never touches slots or pending events
        return self.enc.truncate_before(cut_invoke_idx, seed_invoke_idx)

    def offer(self, op, index):
        p = op.get("process")
        prev = self.enc._open.get(p)
        completed = self.enc.offer(op, index)
        if self.fallback is not None:
            return completed
        try:
            t = op.get("type")
            if t == "invoke":
                row = self.enc._open.get(p)
                if row is not None and row is not prev:
                    self._admit(row)
            elif completed and prev is not None:
                if t == "fail":
                    self._discard(prev)
                elif prev.is_ok:
                    self._complete(prev)
                # info (or an ok whose re-encode failed): the window
                # row is already final -- NIL result, open forever
        except _Degrade as d:
            self._degrade(d.reason)
        except Exception as exc:  # noqa: BLE001 - contained
            logger.warning("streamlin window bookkeeping failed",
                           exc_info=True)
            self._degrade(repr(exc))
        return completed

    # -- window bookkeeping ---------------------------------------------

    def _degrade(self, reason):
        if self.fallback is None:
            self.fallback = str(reason)
            self._inc("monitor.stream_fallbacks")
            logger.warning("streamlin degrading to flat re-checks: %s",
                           reason)

    def _inc(self, name, n=1, **labels):
        if self._reg is not None:
            try:
                self._reg.inc(name, n, **labels)
            except Exception:  # noqa: BLE001
                pass

    def _admit(self, row):
        if not self._free:
            self._grow_window()
        slot = self._free.pop()
        self._slot_by_row[id(row)] = slot
        self._row_by_slot[slot] = row
        self._dirty[slot] = row
        self._pending.append((row.invoke_idx, 1, row))
        if row.is_ok:
            self._pending.append((row.return_idx, 2, row))

    def _complete(self, row):
        slot = self._slot_by_row.get(id(row))
        if slot is None:
            raise _Degrade("completion-for-unknown-row")
        self._dirty[slot] = row          # ok re-encoded args/ret
        self._pending.append((row.return_idx, 2, row))

    def _discard(self, row):
        # fail: the op definitely did not happen. A truly-open row's
        # invoke is >= horizon by definition, so it was never sealed
        # and removing its pending invoke erases it entirely.
        slot = self._slot_by_row.pop(id(row), None)
        if slot is None:
            return
        self._row_by_slot.pop(slot, None)
        self._dirty.pop(slot, None)
        self._pending = [ev for ev in self._pending
                         if ev[2] is not row]
        self._free.append(slot)

    def _grow_window(self):
        NW2 = self.NW * 2
        if NW2 > self.window_cap:
            raise _Degrade("window-overflow")
        import jax.numpy as jnp
        B, B2 = self.NW // 32, NW2 // 32
        if self._committed is not None:
            lin, st, live, open_w = self._committed
            self._committed = (
                jnp.pad(lin, ((0, 0), (0, B2 - B))), st, live,
                jnp.pad(open_w, (0, B2 - B)))
        if self._window is not None:
            w_f, w_args, w_ret = self._window
            pad = NW2 - self.NW
            self._window = (jnp.pad(w_f, (0, pad)),
                            jnp.pad(w_args, ((0, pad), (0, 0))),
                            jnp.pad(w_ret, ((0, pad), (0, 0))))
        self._free.extend(range(NW2 - 1, self.NW - 1, -1))
        self.NW = NW2
        self.window_grows += 1

    def _grow_frontier(self):
        from ..campaign import compile_cache
        F2 = min(self.frontier_cap,
                 compile_cache.bucket_for(self.F * 2))
        if F2 <= self.F:
            return False
        import jax.numpy as jnp
        lin, st, live, open_w = self._committed
        self._committed = (
            jnp.pad(lin, ((0, F2 - self.F), (0, 0))),
            jnp.pad(st, ((0, F2 - self.F), (0, 0))),
            jnp.pad(live, (0, F2 - self.F)), open_w)
        self.F = F2
        self.frontier_grows += 1
        return True

    def _ensure_committed(self):
        if self._committed is not None:
            return
        import jax.numpy as jnp
        from ..campaign import compile_cache
        e, init = self.enc.materialize()
        init = np.asarray(init, np.int32)
        if int(init.shape[0]) != self.S:
            raise _Degrade("init-state-width-mismatch")
        self.F = min(self.frontier_cap,
                     max(streamlin.FRONTIER_FLOOR,
                         compile_cache.bucket_for(1)))
        B = self.NW // 32
        lin, st, live, open_w = streamlin.fresh_frontier(
            self.F, B, self.S, init)
        self._committed = (jnp.asarray(lin), jnp.asarray(st),
                           jnp.asarray(live), jnp.asarray(open_w))
        A = int(self.spec.arg_width)
        self._window = (jnp.zeros(self.NW, jnp.int32),
                        jnp.zeros((self.NW, A), jnp.int32),
                        jnp.zeros((self.NW, A), jnp.int32))

    def _upload(self, dirty):
        import jax.numpy as jnp
        slots = np.fromiter(dirty.keys(), np.int32, len(dirty))
        rows = list(dirty.values())
        f_v = np.asarray([r.f for r in rows], np.int32)
        a_v = np.asarray([r.args for r in rows], np.int32)
        r_v = np.asarray([r.ret for r in rows], np.int32)
        w_f, w_args, w_ret = self._window
        self._window = (w_f.at[slots].set(jnp.asarray(f_v)),
                        w_args.at[slots].set(jnp.asarray(a_v)),
                        w_ret.at[slots].set(jnp.asarray(r_v)))

    # -- the chunk check ------------------------------------------------

    def check(self, cancel=None):
        """One chunk re-check over everything consumed so far. Returns
        an engine result dict ({"valid": ...}) with the flat engines'
        verdict names; the device work is O(window), independent of
        the prefix length."""
        self.checks += 1
        if self.fallback is not None:
            return self._flat_check(cancel)
        try:
            return self._stream_check(cancel)
        except _Degrade as d:
            self._degrade(d.reason)
            return self._flat_check(cancel)
        except Exception as exc:  # noqa: BLE001 - contained
            logger.warning("streamlin check crashed; degrading",
                           exc_info=True)
            self._degrade(repr(exc))
            return self._flat_check(cancel)

    def _flat_check(self, cancel, once=False):
        """The contained fall-back: flat re-search over the
        materialized prefix (quiescent-carry keeps it bounded when the
        monitor runs the PR 7 truncation). Never flips a verdict --
        this IS the offline engine."""
        from . import engine as mengine
        self.flat_checks += 1
        self._inc("monitor.stream_flat_checks")
        e, init = self.enc.materialize()
        r = mengine.check_prefix(self.spec, e, init,
                                 engine=self.confirm_engine,
                                 engine_opts=self.confirm_opts,
                                 cancel=cancel)
        r = dict(r)
        r["stream_fallback"] = "probe-overflow" if once \
            else (self.fallback or "unknown")
        return r

    def _max_open_during(self, events):
        c = c_max = self._open_committed
        for _t, kind, _row in events:
            c += 1 if kind == 1 else -1
            c_max = max(c_max, c)
        return max(1, c_max)

    def _fold(self, events, clear_slots, cancel, commit):
        """One fold dispatch (plus pow-2 frontier regrows on
        overflow while below the cap). Returns the raw fold result."""
        need_c = self._max_open_during(events)
        if need_c > self.C:
            self.C = min(self.NW, _bucket(need_c,
                                          streamlin.OPEN_FLOOR))
        B = self.NW // 32
        E = _bucket(len(events), streamlin.EVENT_FLOOR)
        ev_kind = np.zeros(E, np.int32)
        ev_slot = np.zeros(E, np.int32)
        for k, (_t, kind, row) in enumerate(events):
            ev_kind[k] = kind
            ev_slot[k] = self._slot_by_row[id(row)]
        clear_w = np.zeros(B, np.uint32)
        for s in clear_slots or ():
            clear_w[s // 32] |= np.uint32(1) << np.uint32(s % 32)
        while True:
            if cancel is not None and cancel.is_set():
                raise _Degrade("cancelled")
            lin, st, live, open_w = self._committed
            w_f, w_args, w_ret = self._window
            job = streamlin.FoldJob(self.spec, self.C, {
                "lin": lin, "st": st, "live": live, "open_w": open_w,
                "ev_kind": ev_kind, "ev_slot": ev_slot, "w_f": w_f,
                "w_args": w_args, "w_ret": w_ret, "clear_w": clear_w},
                len(events))
            r = self._dispatch(job)
            self.fold_passes += r["passes"]
            self.fold_cells += r["steps"]
            self.device_s += float(r.get("device_s") or 0.0)
            if r["status"] == 2 and self.F < self.frontier_cap \
                    and self._grow_frontier():
                continue
            return r

    def _dispatch(self, job):
        """Coalesced when a fleet batcher is live, solo otherwise.
        Deadline "unknown" and batcher failures both land on the solo
        path -- per-stream isolation, never a verdict change."""
        if self.coalesce:
            co = None
            try:
                from ..fleet import service as fsvc
                co = fsvc.coalescer()
            except Exception:  # noqa: BLE001 - service not wired
                co = None
            if co is not None:
                try:
                    item = co.submit(
                        streamlin.fold_lane_spec(self.spec), job, None,
                        deadline=_time.monotonic() + FOLD_DEADLINE_S,
                        owner=self.owner)
                    got = co.wait(item)
                    if isinstance(got, dict) and "status" in got:
                        self.coalesced_folds += 1
                        self.batch_peak = max(self.batch_peak,
                                              int(got.get("batch")
                                                  or 1))
                        return got
                except Exception:  # noqa: BLE001 - contained
                    logger.warning("coalesced stream fold failed; "
                                   "folding solo", exc_info=True)
        self.solo_folds += 1
        return streamlin.solo_fold(job)

    def _stream_check(self, cancel):
        t0 = _time.monotonic()
        d0 = self.device_s
        self._ensure_committed()
        if not self._planned:
            self.so.plan("streamlin", self.F, len(self.enc), self.NW,
                         owners=1)
            self._planned = True
        open_rows = [r for r in self.enc._open.values() if not r.dead]
        horizon = min((r.invoke_idx for r in open_rows), default=None)
        pend = sorted(self._pending, key=lambda ev: (ev[0], ev[1]))
        if horizon is None:
            seal_ev, probe_ev = pend, []
        else:
            seal_ev = [ev for ev in pend if ev[0] < horizon]
            probe_ev = [ev for ev in pend if ev[0] >= horizon]
        if self._dirty:
            dirty, self._dirty = self._dirty, {}
            self._upload(dirty)
        cells0 = self.fold_cells
        if seal_ev:
            sealed_slots = [self._slot_by_row[id(row)]
                            for (_t, kind, row) in seal_ev if kind == 2]
            r = self._fold(seal_ev, sealed_slots, cancel, commit=True)
            if r["status"] == 1:
                return self._confirm(r, cancel)
            if r["status"] == 2:
                # a seal that cannot fit even at the cap can never
                # commit -- the carry is gone for good on this stream
                raise _Degrade("frontier-overflow")
            self._committed = (r["lin"], r["st"], r["live"],
                               r["open_w"])
            self.seal_folds += 1
            self._inc("monitor.seal_folds")
            self.frontier_size = r["n_live"]
            self.frontier_peak = max(self.frontier_peak, r["n_live"])
            for _t, kind, row in seal_ev:
                if kind == 1:
                    self._open_committed += 1
                else:
                    self._open_committed -= 1
                    # fully sealed: recycle the slot (its frontier
                    # bits were cleared by this fold's clear_w)
                    slot = self._slot_by_row.pop(id(row), None)
                    if slot is not None:
                        self._row_by_slot.pop(slot, None)
                        self._free.append(slot)
                        self.sealed_rows += 1
        self._pending = probe_ev
        if probe_ev:
            r = self._fold(probe_ev, None, cancel, commit=False)
            self.probe_folds += 1
            self._inc("monitor.probe_folds")
            if r["status"] == 1:
                return self._confirm(r, cancel)
            if r["status"] == 2:
                # transient: the open window alone blew the cap; check
                # this one chunk flat and keep the carry for the next
                self.probe_overflows += 1
                self._inc("monitor.stream_probe_overflows")
                return self._flat_check(cancel, once=True)
            self.frontier_size = max(1, r["n_live"])
            self.frontier_peak = max(self.frontier_peak,
                                     self.frontier_size)
        cells = self.fold_cells - cells0
        self._inc("monitor.fold_cells", cells)
        if self._reg is not None:
            try:
                self._reg.set_gauge("monitor.frontier_size",
                                    int(self.frontier_size))
                self._reg.max_gauge("monitor.frontier_peak",
                                    int(self.frontier_peak))
            except Exception:  # noqa: BLE001
                pass
        self.so.heartbeat("streamlin", iteration=self.checks,
                          chunk_s=_time.monotonic() - t0,
                          device_s=self.device_s - d0,
                          frontier=int(self.frontier_size),
                          explored=int(self.fold_cells))
        return {"valid": True, "engine": "streamlin",
                "configs_explored": cells,
                "frontier": int(self.frontier_size)}

    def _confirm(self, r, cancel):
        """A frontier violation is a SUSPICION: the flat engine
        re-checks the materialized prefix and owns the verdict of
        record plus the witness (exactly the txn monitor's deference
        rule) -- the stream can pay an extra confirm, never flip a
        verdict."""
        from . import engine as mengine
        e, init = self.enc.materialize()
        rr = dict(mengine.check_prefix(
            self.spec, e, init, engine=self.confirm_engine,
            engine_opts=self.confirm_opts, cancel=cancel))
        rr["detected_by"] = "streamlin"
        rr["suspect_slot"] = int(r.get("viol_slot", -1))
        if rr.get("valid") is not False:
            self.confirm_mismatches += 1
            self._inc("monitor.stream_confirm_mismatches")
            logger.warning(
                "streamlin suspicion not confirmed by %s (%r); "
                "offline verdict stands", self.confirm_engine,
                rr.get("valid"))
        return rr

    # -- reporting ------------------------------------------------------

    def stream_summary(self):
        """The per-stream telemetry block (Monitor.summary aggregates
        these across keys; mirrors the txn monitor's
        ``closure_rebuilds`` contract: the O(window) claim is
        observable, not asserted in wall clock)."""
        out = {
            "frontier_size": int(self.frontier_size),
            "frontier_peak": int(self.frontier_peak),
            "frontier_cap": int(self.F or 0),
            "window": int(self.NW),
            "open_slots": len(self._slot_by_row),
            "checks": self.checks,
            "seal_folds": self.seal_folds,
            "probe_folds": self.probe_folds,
            "fold_passes": self.fold_passes,
            "fold_cells": self.fold_cells,
            "frontier_grows": self.frontier_grows,
            "window_grows": self.window_grows,
            "flat_checks": self.flat_checks,
            "probe_overflows": self.probe_overflows,
            "confirm_mismatches": self.confirm_mismatches,
            "coalesced_folds": self.coalesced_folds,
            "solo_folds": self.solo_folds,
            "batch_peak": self.batch_peak,
            "sealed_rows": self.sealed_rows,
            "device_s": round(self.device_s, 4),
        }
        if self.fallback is not None:
            out["fallback"] = self.fallback
        return out
