"""Streaming linearizability monitor: online WGL checking over the
live op stream, with early abort on violation.

The offline pipeline (ROADMAP "millions of ops") records blind for the
whole run before the checker says a word: a violation committed in the
first minute surfaces an hour later, after the generator, the drain,
and the full device search. This package closes that loop while the
run is still going:

* **stream tap** -- the interpreter's multi-subscriber op-sink list
  (``test["op-sinks"]``) delivers every history op, already
  serial-stripped and zombie-filtered, to `Monitor.offer` on the event
  loop thread. offer() is one deque append: no encoding, no device
  work, no locks shared with the search (the <=10% interpreter
  overhead budget is spent here).
* **incremental encoder** (`stream.StreamEncoder`) -- completed ops
  are appended into the dense EncodedHistory row format as they land
  (pairing, fail-drop, and info semantics identical to
  ``history.encode_history``); still-open invocations materialize as
  info rows, exactly how the offline checker would see the same
  prefix. Keyed workloads (jepsen.independent ``[k v]`` tuples) get
  one encoder per key, mirroring ``independent.subhistory``.
* **monitor thread** (`core.Monitor`) -- every ``chunk`` completed
  client ops it materializes the dirty prefixes and extends the WGL
  verdict through the configured engine (``jax-wgl`` by default: the
  device search, whose pow-2 padded shapes make the campaign
  compile-reuse ledger hit across chunk boundaries and runs;
  ``linear`` / ``wgl`` for CPU-only monitoring). The prefix-check
  formulation is the sound core of the incremental-monitoring papers
  (arxiv 2410.04581, 2509.17795): a linearizable prefix can only stay
  linearizable or become invalid as ops append, so the first invalid
  prefix IS the violation, and everything before the last valid check
  never needs re-litigating for the verdict's sake.
* **violation trigger** -- the moment a prefix proves
  non-linearizable the monitor flips its `robust.ChainedLatch`
  (reason ``monitor-violation``): the interpreter stops new ops at
  the generator boundary, drains, and the normal salvage path
  persists + re-checks the partial history. ``results["monitor"]``
  records the verdict, detection index, and detection latency.

`install(test)` wires all of this from ``test["monitor"]`` (True, a
chunk int, or an options dict) and is called by ``core.run``; the
monitor discovers the model through the test's own checker tree
(`find_linearizable`), so it checks exactly what the offline
Linearizable gate would.
"""

from __future__ import annotations

from .core import (DEFAULT_CHUNK, Monitor, config, finalize,  # noqa: F401
                   find_linearizable, install)
from .stream import StreamEncoder  # noqa: F401
from .txn import TxnCheck, TxnMonitor  # noqa: F401

__all__ = ["Monitor", "StreamEncoder", "TxnCheck", "TxnMonitor",
           "install", "finalize", "config", "find_linearizable",
           "DEFAULT_CHUNK"]
