"""Prefix-check dispatch for the streaming monitor.

One function, three engines -- the same trio the offline
``Linearizable`` gate races, minus the race (the monitor re-checks
every chunk, so it wants one predictable engine per run):

* ``jax-wgl`` -- the batched device search. Prefixes pad to the same
  pow-2 buckets as offline checks (``jax_wgl._bucket`` /
  ``_n_floor``), so a run's successive chunk checks reuse ONE
  compiled kernel per bucket, the campaign compile-reuse ledger
  counts the hits, and the carry advances through the existing
  ``run_chunk`` donate-argnums dispatch loop.
* ``linear`` -- just-in-time linearization: the CPU engine whose
  event-sweep formulation is itself the incremental-monitoring
  algorithm of the papers; the natural choice for CPU-only runs.
* ``wgl`` -- the sequential oracle, for tests and tiny histories.
* ``streamlin`` -- the device-resident incremental frontier
  (``checker/streamlin.py``). Through THIS dispatcher it runs as a
  one-shot fold over the whole prefix (the flat face the offline
  equivalence tests exercise); the real O(window) streaming driver is
  ``monitor/wgl_stream.StreamCheck``, which the monitor wires in
  ``_encoder`` and which only reaches this function for its contained
  flat fall-back and violation confirms.

Budgets are deliberately modest: a monitor check that can't decide
quickly returns "unknown" and the monitor moves on -- the offline
checker still owns the final word; the monitor only ever *adds* an
early abort.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

__all__ = ["ENGINES", "TXN_WORKLOADS", "check_prefix",
           "check_txn_prefix"]

#: engines the monitor can drive (planlint PL013 validates against it)
ENGINES = ("jax-wgl", "linear", "wgl", "streamlin")

#: txn-family workloads the monitor can stream (monitor/txn.py;
#: planlint PL025 validates against it)
TXN_WORKLOADS = ("append", "wr")

#: CPU-engine budgets: chunk checks repeat, so each one must stay small
LINEAR_MAX_CONFIGS = 200_000
WGL_MAX_CONFIGS = 2_000_000


def check_prefix(spec, e, init_state, engine="jax-wgl",
                 engine_opts=None, cancel=None):
    """Check one encoded prefix; returns the engine's result dict
    ({"valid": True|False|"unknown", ...}). Exceptions become
    "unknown": a monitor bug must never abort a healthy run."""
    if len(e) == 0 or e.n_ok == 0:
        return {"valid": True, "configs_explored": 0, "engine": engine}
    try:
        if engine == "linear":
            from ..checker import linear
            return linear.check_encoded(
                spec, e, init_state, max_configs=LINEAR_MAX_CONFIGS,
                cancel=cancel)
        if engine == "wgl":
            from ..checker import wgl
            return wgl.check_encoded(
                spec, e, init_state, max_configs=WGL_MAX_CONFIGS,
                cancel=cancel)
        if engine == "streamlin":
            from ..checker import streamlin
            opts = dict(engine_opts or {})
            return streamlin.check_encoded(
                spec, e, init_state,
                max_configs=int(opts.get("frontier-cap")
                                or streamlin.DEFAULT_FRONTIER_CAP),
                cancel=cancel)
        from ..checker import jax_wgl
        opts = dict(engine_opts or {})
        # the mesh/checkpoint machinery is offline-only; a monitor
        # check is short-lived and re-runs every chunk
        for k in ("mesh", "checkpoint", "checkpoint_every_s", "confirm"):
            opts.pop(k, None)
        return jax_wgl.check_encoded(spec, e, init_state, cancel=cancel,
                                     **opts)
    except Exception as exc:  # noqa: BLE001 - contained per check
        logger.warning("monitor prefix check crashed", exc_info=True)
        return {"valid": "unknown", "error": repr(exc), "engine": engine}


def check_txn_prefix(history, workload="append", opts=None, cancel=None):
    """family="txn" dispatch: run the full offline ``cycle/`` analysis
    over a consumed txn prefix -- the verdict of record the streaming
    frontier's suspicion defers to (monitor/txn.py only calls this when
    the incremental closure closed a cycle or inference flagged an
    anomaly). Same containment as ``check_prefix``: exceptions become
    "unknown", never an abort."""
    opts = dict(opts or {})
    try:
        if workload == "wr":
            from ..cycle import wr
            return wr.analyze(list(history), opts)
        from ..cycle import DEFAULT_ANOMALIES
        from ..cycle import append as app
        return app.analyze(
            list(history),
            tuple(opts.get("anomalies", DEFAULT_ANOMALIES)),
            realtime=opts.get("realtime", True),
            process=opts.get("process", False),
            skew_bound=opts.get("skew-bound",
                                opts.get("skew_bound", 0)))
    except Exception as exc:  # noqa: BLE001 - contained per check
        logger.warning("monitor txn prefix check crashed", exc_info=True)
        return {"valid": "unknown", "error": repr(exc),
                "engine": f"txn-{workload}"}
