"""Streaming transactional (cycle-family) monitor: family="txn".

The WGL monitor re-proves linearizability per chunk; the txn family's
equivalent would be a from-scratch O(N^3 log N) transitive closure per
chunk. Instead the monitor keeps the encoded adjacency matrix and its
closure *frontier* resident across chunks (`cycle.IncrementalClosure`,
device-resident above the host threshold): folding a chunk of newly
committed txns in is one row/col delta OR plus a couple of squaring
passes back to fixpoint -- the incremental-frontier formulation of
arxiv 2410.04581 applied to reachability instead of linearizations.

Verdict semantics mirror the WGL monitor's prefix contract:

* a chunk with NO closed cycle in the frontier and NO inference-level
  anomaly is exactly what the offline ``cycle/`` check would call valid
  on the same cut (every Adya class needs a cycle; the inference-level
  classes -- duplicates, incompatible-order, G1a, G1b, ... -- all land
  in ``infer``'s found map), so the monitor answers True without ever
  classifying;
* suspicion (a closed cycle, or any inference anomaly) defers to the
  full offline analysis (`engine.check_txn_prefix`) -- so False
  verdicts, witnesses, and anomaly names are ALWAYS the offline
  engine's. A cycle outside the requested anomaly classes leaves the
  verdict True and the suspicion standing (documented cost, never a
  verdict change);
* garbage reads alone are "unknown", counted, never aborting.

The first False flips the same ChainedLatch (reason
``monitor-violation``) as the WGL monitor; the acceptance property is
verdict equivalence with the offline check at chunks 1/8/64, with
per-chunk closure cost asserted by counting squaring passes
(`cycle.closure_passes`), not wall clock.
"""

from __future__ import annotations

import collections
import logging
import threading
import time as _time

from .. import obs
from .. import robust
from ..cycle import DEFAULT_ANOMALIES, IncrementalClosure
from . import engine as mengine
from .core import ABORT_REASON, CANCEL_JOIN_S, DEFAULT_CHUNK, STOP_JOIN_S

logger = logging.getLogger(__name__)

__all__ = ["TxnCheck", "TxnMonitor", "install_txn"]


class TxnCheck:
    """Synchronous chunk-check core: consume ops, maintain the
    incremental frontier, answer offline-equivalent verdicts per
    chunk. Thread-free so equivalence tests drive chunks 1/8/64
    deterministically; `TxnMonitor` wraps it in the monitor-thread
    contract."""

    def __init__(self, workload="append", anomalies=None, realtime=True,
                 process=False, skew_bound=0, lo=64):
        if workload not in mengine.TXN_WORKLOADS:
            raise ValueError(f"unknown txn workload {workload!r}; "
                             f"expected one of {mengine.TXN_WORKLOADS}")
        self.workload = workload
        self.anomalies = tuple(anomalies or DEFAULT_ANOMALIES)
        self.realtime = bool(realtime)
        self.process = bool(process)
        self.skew_bound = int(skew_bound or 0)
        self.frontier = IncrementalClosure(lo=lo)
        self._hist = []
        self.n_txns = 0

    def _opts(self):
        return {"anomalies": self.anomalies, "realtime": self.realtime,
                "process": self.process, "skew-bound": self.skew_bound}

    def offer(self, op):
        """Append one history event (invokes included: realtime edges
        need invocation times)."""
        self._hist.append(op)

    def _infer(self):
        from ..cycle import append as cycle_append
        from ..cycle import wr as cycle_wr
        if self.workload == "wr":
            return cycle_wr.infer(self._hist, self._opts())
        graph, found, oks = cycle_append.infer(
            self._hist, self.anomalies, self.realtime, self.process,
            self.skew_bound)
        return graph, found, oks, found.get("garbage-read") or []

    def check(self, cancel=None):
        """One chunk check over the consumed prefix. Returns the
        offline-shaped verdict dict for this cut."""
        graph, found, oks, garbage = self._infer()
        self.n_txns = len(oks)
        self.frontier.update(graph.adj > 0)
        suspicious = set(found) - {"garbage-read"}
        if suspicious or self.frontier.has_cycle():
            # the offline engine owns every False: witness, anomaly
            # names, and the requested-subset semantics all come from
            # the same code path the final checker runs
            return mengine.check_txn_prefix(
                self._hist, self.workload, self._opts(), cancel=cancel)
        if garbage:
            return {"valid": "unknown", "anomaly_types": [],
                    "anomalies": {"garbage-read": garbage}}
        return {"valid": True, "anomaly_types": [], "anomalies": {}}

    @property
    def history(self):
        return self._hist


class TxnMonitor:
    """One run's streaming txn monitor: the WGL `Monitor`'s threading
    contract (O(1) offer on the event-loop thread, one daemon chunk
    thread, bounded idempotent stop) over a `TxnCheck` core."""

    family = "txn"
    #: finalize() parks evidence as dict(evidence, spec=mon.spec);
    #: the txn family has no WGL spec
    spec = None

    def __init__(self, latch, chunk=DEFAULT_CHUNK, workload="append",
                 anomalies=None, realtime=True, process=False,
                 skew_bound=0):
        self.latch = latch
        self.chunk = max(1, int(chunk))
        self.core = TxnCheck(workload=workload, anomalies=anomalies,
                             realtime=realtime, process=process,
                             skew_bound=skew_bound)
        self.engine = f"txn-{workload}"
        self.violation = None
        self.evidence = None
        self._tr, self._reg = obs.current_sinks()
        self._cancel = threading.Event()
        self._cond = threading.Condition()
        self._queue = collections.deque()
        self._pending_completions = 0
        self._n_seen = 0
        self._stopping = False
        self._finish = True
        self._last_verdict = True
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="jepsen txn monitor")
        self.ops_consumed = 0
        self.chunks = 0
        self.checks = 0
        self.unknown_checks = 0
        self._t_start = _time.monotonic()
        self._t_first_verdict = None

    # -- interpreter side --------------------------------------------------

    def offer(self, op):
        """Op-sink entry: O(1); never raises."""
        try:
            with self._cond:
                idx = self._n_seen
                self._n_seen += 1
                if self.violation is not None or self._stopping:
                    return
                self._queue.append((op, idx, _time.monotonic()))
                if op.get("type") != "invoke" \
                        and isinstance(op.get("process"), int):
                    self._pending_completions += 1
                    if self._pending_completions >= self.chunk:
                        self._cond.notify()
        except Exception:  # noqa: BLE001 - must never hurt the run
            logger.warning("txn monitor offer failed", exc_info=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread.start()
        return self

    def stop(self, finish=True, timeout_s=STOP_JOIN_S):
        with self._cond:
            self._stopping = True
            self._finish = self._finish and finish
            self._cond.notify_all()
        if not self._thread.is_alive():
            return
        self._thread.join(timeout_s)
        if self._thread.is_alive():
            self._cancel.set()
            self._thread.join(CANCEL_JOIN_S)
            if self._thread.is_alive():
                logger.warning("txn monitor thread did not exit; "
                               "abandoning")
                self._inc("robust.leaked_threads")

    # -- summary -----------------------------------------------------------

    def summary(self):
        """The ``results["monitor"]`` block."""
        verdict = False if self.violation is not None \
            else self._last_verdict
        out = {
            "verdict": verdict,
            "family": "txn",
            "workload": self.core.workload,
            "engine": self.engine,
            "chunk": self.chunk,
            "ops_consumed": self.ops_consumed,
            "chunks": self.chunks,
            "checks": self.checks,
            "unknown_checks": self.unknown_checks,
            "txns": self.core.n_txns,
            "closure_rebuilds": self.core.frontier.rebuilds,
            "time_to_first_verdict_s": self._t_first_verdict,
        }
        if self.core.skew_bound:
            out["skew_bound"] = self.core.skew_bound
        if self.violation is not None:
            out.update(self.violation)
        return out

    # -- monitor thread ----------------------------------------------------

    def _inc(self, name, n=1, **labels):
        if self._reg is not None:
            self._reg.inc(name, n, **labels)

    def _step(self, t_newest=None):
        with self._cond:
            batch = list(self._queue)
            self._queue.clear()
            self._pending_completions = 0
        if not batch:
            return
        newest = 0.0
        for op, idx, t in batch:
            self.core.offer(op)
            self.ops_consumed += 1
            newest = max(newest, t)
        self._inc("monitor.ops_consumed", len(batch))
        self.chunks += 1
        self._inc("monitor.chunks")
        t0 = _time.monotonic()
        res = self.core.check(cancel=self._cancel)
        dt = _time.monotonic() - t0
        self.checks += 1
        valid = res.get("valid")
        self._inc("monitor.checks", valid=str(valid))
        if self._reg is not None:
            self._reg.observe("monitor.check_s", dt)
        if self._t_first_verdict is None and valid in (True, False):
            self._t_first_verdict = round(
                _time.monotonic() - self._t_start, 4)
            if self._reg is not None:
                self._reg.set_gauge("monitor.time_to_first_verdict_s",
                                    self._t_first_verdict)
        if valid == "unknown":
            self.unknown_checks += 1
            if self._last_verdict is not False:
                self._last_verdict = "unknown"
            return
        self._last_verdict = valid
        if valid is False and self.violation is None:
            latency = max(0.0, _time.monotonic() - newest)
            self.violation = {
                "detected_at_index": self._n_seen - 1,
                "detection_latency_s": round(latency, 4),
                "checked_ops": len(self.core.history),
                "anomaly_types": list(res.get("anomaly_types") or ()),
            }
            self.evidence = {
                "family": "txn",
                "workload": self.core.workload,
                "opts": self.core._opts(),
                "history": list(self.core.history),
                "result": res,
            }
            self._inc("monitor.violations")
            if self._reg is not None:
                self._reg.set_gauge(
                    "monitor.detection_latency_s",
                    self.violation["detection_latency_s"])
            if self._tr is not None:
                self._tr.instant("monitor.violation", cat="monitor",
                                 args=dict(self.violation))
            logger.warning(
                "MONITOR: txn anomaly %s detected at history index %d "
                "(%.3fs after the op landed); aborting run",
                ",".join(self.violation["anomaly_types"]) or "?",
                self._n_seen - 1, latency)
            self.latch.set(ABORT_REASON)

    def _run(self):
        with obs.sink_scope(self._tr, self._reg):
            while True:
                with self._cond:
                    while (self._pending_completions < self.chunk
                           and not self._stopping
                           and self.violation is None):
                        self._cond.wait(0.25)
                    stopping = self._stopping
                if self.violation is not None:
                    break
                if stopping:
                    if self._finish and not self._cancel.is_set():
                        self._step()
                    break
                self._step()


def install_txn(test, cfg):
    """Wire a TxnMonitor from a normalized monitor config with
    ``family: "txn"`` (core.install dispatches here). Chains the run's
    abort latch and subscribes to the op-sink list exactly like the WGL
    path. Returns the started monitor, or None (never raises)."""
    try:
        latch = robust.ChainedLatch(test.get("abort"))
        test["abort"] = latch
        mon = TxnMonitor(
            latch=latch,
            chunk=cfg.get("chunk") or DEFAULT_CHUNK,
            workload=cfg.get("workload", "append"),
            anomalies=cfg.get("anomalies"),
            realtime=cfg.get("realtime", True),
            process=cfg.get("process", False),
            skew_bound=cfg.get("skew-bound", cfg.get("skew_bound", 0)))
        test.setdefault("op-sinks", []).append(mon.offer)
        obs.inc("monitor.installed", engine=mon.engine)
        return mon.start()
    except Exception:  # noqa: BLE001 - a monitor bug must not kill runs
        logger.warning("txn monitor install failed; continuing "
                       "unmonitored", exc_info=True)
        return None
