"""Incremental EncodedHistory construction over a live op stream.

``history.encode_history`` walks a finished history: it pairs invokes
with completions, drops fails, and encodes one dense row per logical
op. The monitor can't wait for "finished" -- ops arrive one event at a
time -- so `StreamEncoder` maintains the same row set incrementally:

* an ``invoke`` appends an *open* row (return ``INF_TIME``, result
  unknown) -- precisely the info-op encoding the offline checker would
  use if the history were cut right here;
* an ``ok`` completion re-encodes its row in place with the now-known
  result and closes it;
* a ``fail`` completion marks the row dead -- filtered out at
  materialize (knossos semantics: the op definitely did not happen);
* an ``info`` completion leaves the row open forever.

``materialize()`` therefore yields an EncodedHistory whose semantics
match ``spec.encode(prefix)`` for the event prefix consumed so far:
the monitor's chunk checks and the offline checker see the same
history through the same encoding rules. Rows append in invocation
order, which is already the engines' required sort order.

Values are interned through one persistent `models.base.Interner` --
codes are assigned in first-seen order, which is the same order the
offline encoding would see, and verdicts never depend on code values.
"""

from __future__ import annotations

import numpy as np

from ..history import INF_TIME, NIL, EncodedHistory
from ..models import base as mbase

__all__ = ["StreamEncoder"]


class _Row:
    """One logical op row, mutable until its completion lands."""

    __slots__ = ("invoke_idx", "return_idx", "f", "args", "ret", "is_ok",
                 "process", "inv", "comp", "dead")

    def __init__(self, invoke_idx, f, args, ret, process, inv):
        self.invoke_idx = invoke_idx
        self.return_idx = INF_TIME
        self.f = f
        self.args = args
        self.ret = ret
        self.is_ok = False
        self.process = process
        self.inv = inv
        self.comp = None
        #: fail completions mark their row dead instead of removing it
        #: (list.remove is a linear scan -- quadratic on fail-heavy
        #: workloads); materialize() filters
        self.dead = False


class StreamEncoder:
    """Feed indexed client ops in history order; materialize the
    encoded prefix on demand.

    ``offer(op, index)`` must be called with a monotonically increasing
    history ``index`` (the monitor assigns them as ops stream in, so
    they agree with ``history.index`` at analyze time). ``init_ops``
    are prepended as already-completed pairs at negative indices --
    the same synthetic rows ``Linearizable.prepare_history`` builds.
    """

    def __init__(self, spec, init_ops=()):
        self.spec = spec
        self.interner = mbase.Interner()
        self._enc = spec.encode_op or mbase.ModelSpec.default_encode_op
        self.rows = []
        self._open = {}          # process -> open _Row
        #: history index of the newest event consumed (for detection
        #: reporting); -1 until the first op lands
        self.last_index = -1
        #: events that could not be paired/encoded (malformed stream);
        #: counted, never fatal -- histlint owns structural complaints
        self.skipped = 0
        for j, op in enumerate(init_ops or ()):
            base = -2 * (len(init_ops) - j)
            inv = {"type": "invoke", "process": -1, "f": op["f"],
                   "value": op.get("value"), "index": base}
            row = self._encode_row(base, op["f"], op.get("value"), None,
                                   -1, inv)
            row.return_idx = base + 1
            row.is_ok = True
            row.comp = {**inv, "type": "ok", "index": base + 1}
            self.rows.append(row)

    def _pad(self, xs):
        xs = list(xs)[:self.spec.arg_width]
        return xs + [NIL] * (self.spec.arg_width - len(xs))

    def _encode_row(self, invoke_idx, f, value, ret_value, process, inv):
        fcode, args, ret = self._enc(self.spec, self.interner, f, value,
                                     ret_value)
        return _Row(invoke_idx, fcode, self._pad(args), self._pad(ret),
                    process, inv)

    def offer(self, op, index):
        """Consume one history event. Returns True when the event
        completed a logical op (the monitor's chunk counter)."""
        self.last_index = index
        t = op.get("type")
        p = op.get("process")
        if t == "invoke":
            if p in self._open:
                # overlapping invoke on one process: malformed; keep
                # the old op open and skip (histlint HL002 territory)
                self.skipped += 1
                return False
            try:
                row = self._encode_row(index, op.get("f"),
                                       op.get("value"), None, p, op)
            except Exception:  # noqa: BLE001 - unknown f etc.
                self.skipped += 1
                return False
            self._open[p] = row
            self.rows.append(row)
            return False
        if t not in ("ok", "fail", "info"):
            return False
        row = self._open.pop(p, None)
        if row is None:
            # bare completion (nemesis style): not a logical client op
            self.skipped += 1
            return False
        if t == "fail":
            row.dead = True
            return True
        if t == "info":
            row.comp = op
            return True
        try:
            fresh = self._encode_row(row.invoke_idx, row.inv.get("f"),
                                     row.inv.get("value"),
                                     op.get("value"), p, row.inv)
        except Exception:  # noqa: BLE001 - leave the row open (info)
            self.skipped += 1
            row.comp = op
            return True
        row.f, row.args, row.ret = fresh.f, fresh.args, fresh.ret
        row.return_idx = index
        row.is_ok = True
        row.comp = op
        return True

    def __len__(self):
        return sum(1 for r in self.rows if not r.dead)

    def truncate_before(self, cut_invoke_idx, seed_invoke_idx=None):
        """Quiescent-cut carry (analysis/searchplan.py stream_cut):
        drop rows that invoked before ``cut_invoke_idx``, keeping the
        sealing seed row (its completed pair re-establishes the state
        the prefix linearization ended in). Only sound right after a
        prefix check returned True — the monitor enforces that. Rows
        still open (in ``_open``) always invoke at/after a valid cut,
        so the open map stays consistent. Returns the number of rows
        dropped."""
        keep = []
        dropped = 0
        for r in self.rows:
            if r.invoke_idx >= cut_invoke_idx \
                    or r.invoke_idx == seed_invoke_idx:
                keep.append(r)
            else:
                dropped += 1
        if dropped:
            self.rows = keep
        return dropped

    def materialize(self):
        """The encoded prefix: (EncodedHistory, init_state). Open rows
        appear as info ops, exactly like an offline encoding of the
        same cut; failed (dead) rows are filtered out here."""
        rows = [r for r in self.rows if not r.dead]
        A = self.spec.arg_width
        if not rows:
            z = np.zeros(0)
            za = np.zeros((0, A))
            e = EncodedHistory(z, z, z, za, za, np.zeros(0, bool), z,
                               ops=[])
        else:
            e = EncodedHistory(
                [r.invoke_idx for r in rows],
                [r.return_idx for r in rows],
                [r.f for r in rows],
                [r.args for r in rows],
                [r.ret for r in rows],
                [r.is_ok for r in rows],
                [r.process if isinstance(r.process, int) else -1
                 for r in rows],
                ops=[(r.inv, r.comp) for r in rows])
        s = self.spec.state_size(e)
        return e, np.asarray(self.spec.init_state(e, s), np.int32)
