"""Operations and histories.

A *history* is an ordered sequence of operation events. Each logical operation
appears as an ``invoke`` event followed (possibly much later) by a completion
event of type ``ok``, ``fail``, or ``info``:

- ``ok``   -- the operation definitely happened.
- ``fail`` -- the operation definitely did not happen.
- ``info`` -- indeterminate: it may or may not have taken effect, at any time
  after its invocation (e.g. a timed-out network call).

This module reproduces the op/history surface jepsen borrows from knossos
(reference: jepsen/src/jepsen/core.clj:227-228 `history/index`,
jepsen/src/jepsen/checker.clj:157-163 `op/ok?` etc.,
jepsen/src/jepsen/checker/timeline.clj:7 `history/pairs`), plus the dense
tensor encoding the TPU checker consumes.

Ops are dict-subclasses so "tests are data" carries over from the reference:
checkers, generators and clients all traffic in plain mappings.
"""

from __future__ import annotations

import numpy as np

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

#: Sentinel for "no / unknown value" in tensor encodings (int32 min).
NIL = -(2**31)

#: Sentinel "return time" for operations that never return (info ops).
INF_TIME = np.iinfo(np.int64).max


class HistoryError(ValueError):
    """A structurally malformed history: the checkers' preconditions do
    not hold, so any verdict computed from it would be meaningless.
    Carries the offending ``process`` and event ``index`` when known;
    ``analysis.histlint`` reports the same defects as diagnostics
    without raising."""

    def __init__(self, message, process=None, index=None):
        super().__init__(message)
        self.process = process
        self.index = index


class Op(dict):
    """An operation event: a dict with attribute access.

    Standard keys: ``type`` (invoke/ok/fail/info), ``process`` (int or
    'nemesis'), ``f`` (operation function, e.g. 'read'), ``value``,
    ``time`` (nanoseconds, relative), ``index`` (position in history).
    """

    __slots__ = ()

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        self[name] = value

    def copy(self):
        return Op(self)

    def assoc(self, **kw):
        o = Op(self)
        o.update(kw)
        return o


def op(type=INVOKE, process=0, f=None, value=None, **kw) -> Op:  # noqa: A002,E501 - mirrors the reference op keys
    """Construct an op event."""
    o = Op(type=type, process=process, f=f, value=value)
    o.update(kw)
    return o


def invoke_op(process, f, value=None, **kw):
    return op(INVOKE, process, f, value, **kw)


def ok_op(process, f, value=None, **kw):
    return op(OK, process, f, value, **kw)


def fail_op(process, f, value=None, **kw):
    return op(FAIL, process, f, value, **kw)


def info_op(process, f, value=None, **kw):
    return op(INFO, process, f, value, **kw)


# -- predicates (knossos.op surface) ----------------------------------------

def invoke(o) -> bool:
    return o["type"] == INVOKE


def ok(o) -> bool:
    return o["type"] == OK


def fail(o) -> bool:
    return o["type"] == FAIL


def info(o) -> bool:
    return o["type"] == INFO


# Aliases matching knossos.op/{invoke? ok? fail? info?}
invoke_ = invoke
ok_ = ok
fail_ = fail
info_ = info


# -- history utilities (knossos.history surface) ----------------------------

class History(list):
    """An indexed event list that memoizes derived passes.

    ``checker.core.check`` used to re-walk the full history per
    analyzer run: every subchecker fanned out by Compose re-ran
    ``ensure_indexed`` (an O(n) rebuild per subchecker). Returning a
    History makes that idempotent — the same object flows to every
    subchecker, histlint, and the search planner. The ``pairs`` memo
    additionally lets passes that receive the SAME History share one
    pairing walk — the search planner's per-part segmentation sweep
    and config estimates do (build_plan History-wraps each part);
    call sites that derive fresh lists (client_ops, complete) still
    pay their own walk. Caches are only attached to History instances
    (created at check time, after which the history no longer
    mutates); plain lists behave as before."""

    __slots__ = ("_pairs",)

    def __init__(self, *args):
        super().__init__(*args)
        self._pairs = None


def index(history):
    """Assign each event a monotone ``index`` (knossos.history/index;
    called from reference core.clj:227-228 before checking). Returns a new
    History of Ops; existing indices are overwritten."""
    out = History()
    for i, o in enumerate(history):
        o = Op(o)
        o["index"] = i
        out.append(o)
    return out


def ensure_indexed(history):
    """Index the history unless every event already carries an index.
    Idempotent: an already-indexed History returns unchanged (with its
    memoized passes intact).

    Raises HistoryError (naming the offending position) on events that
    are not mappings -- Op(non-dict) used to fail later with an opaque
    ValueError from dict()."""
    if isinstance(history, History):
        return history
    for i, o in enumerate(history):
        if not isinstance(o, dict):
            raise HistoryError(
                f"history event #{i} is not a mapping: {o!r}", index=i)
    if all("index" in o for o in history):
        return History(o if isinstance(o, Op) else Op(o)
                       for o in history)
    return index(history)


def pairs(history):
    """Yield (invocation, completion) pairs. Invocations without a completion
    yield (invocation, None); completion may be ok/fail/info.
    (knossos.history/pairs equivalent, used by timeline.clj:7.)

    Events pair by process: a completion matches the most recent open
    invocation on the same process.

    Raises HistoryError on an invoke while the same process already has
    an open invocation: processes are logically single-threaded, and
    silently dropping the earlier invocation (the old behavior) changes
    which ops the checker sees.

    The result is memoized on History instances (ensure_indexed
    returns one): timeline, the search planner, and encoders all share
    one pairing walk per checked history. Callers must treat the
    returned list as read-only.
    """
    if isinstance(history, History) and history._pairs is not None:
        return history._pairs
    open_by_process = {}
    out = []
    order = []
    for o in history:
        t = o["type"]
        p = o["process"]
        if t == INVOKE:
            if p in open_by_process:
                prev = open_by_process[p]
                raise HistoryError(
                    f"process {p!r} invoked {o.get('f')!r} at index "
                    f"{o.get('index', '?')} while its invocation of "
                    f"{prev.get('f')!r} (index {prev.get('index', '?')})"
                    " is still open: processes are logically "
                    "single-threaded",
                    process=p, index=o.get("index"))
            open_by_process[p] = o
            order.append(p)
        elif t in (OK, FAIL, INFO):
            inv = open_by_process.pop(p, None)
            if inv is not None:
                out.append((inv, o))
                order.remove(p)
            else:
                # Completion without invocation (e.g. nemesis info): own pair.
                out.append((None, o))
    for p in order:
        out.append((open_by_process[p], None))
    if isinstance(history, History):
        history._pairs = out
    return out


def complete(history):
    """Fill in missing invocation values from completions (knossos
    history/complete): for ok pairs, the invocation's value is replaced by the
    completion's value (reads learn what they read); invocations whose
    completion failed are marked ``fails?`` so checkers can drop the whole
    pair. Info invocations keep their value. Returns a new event list."""
    history = ensure_indexed(history)
    out = [Op(o) for o in history]
    open_by_process = {}
    for i, o in enumerate(out):
        t = o["type"]
        p = o["process"]
        if t == INVOKE:
            open_by_process[p] = i
        elif t in (OK, FAIL, INFO):
            j = open_by_process.pop(p, None)
            if j is not None and t == OK:
                out[j]["value"] = o["value"]
            elif j is not None and t == FAIL:
                out[j]["fails?"] = True
    return out


def invocations(history):
    return [o for o in history if invoke(o)]


def completions(history):
    return [o for o in history if not invoke(o)]


def client_ops(history):
    """Ops performed by client processes (integer process ids)."""
    return [o for o in history if isinstance(o.get("process"), int)]


def oks(history):
    return [o for o in history if ok(o)]


def infos(history):
    return [o for o in history if info(o)]


def fails(history):
    return [o for o in history if fail(o)]


# -- dense tensor encoding ---------------------------------------------------

class EncodedHistory:
    """A history of paired operations as dense arrays, one row per operation.

    Arrays (n rows, numpy):
      invoke_idx  int64      -- event index of the invocation
      return_idx  int64      -- event index of the completion; INF_TIME for
                                operations that never complete or complete
                                with :info (indeterminate -- they stay
                                concurrent with everything after them)
      f           int32      -- model-specific op-function code
      args        int32[n,A] -- encoded argument vector; NIL where absent
      ret         int32[n,A] -- encoded result vector; NIL where unknown
      is_ok       bool       -- completion was :ok (must be linearized)
      process     int64      -- logical process id

    Failed operations (type fail -- definitely did not happen) are excluded
    at encoding time, matching knossos semantics.
    """

    def __init__(self, invoke_idx, return_idx, f, args, ret, is_ok,
                 process, ops=None):
        self.invoke_idx = np.asarray(invoke_idx, np.int64)
        self.return_idx = np.asarray(return_idx, np.int64)
        self.f = np.asarray(f, np.int32)
        self.args = np.asarray(args, np.int32)
        self.ret = np.asarray(ret, np.int32)
        self.is_ok = np.asarray(is_ok, bool)
        self.process = np.asarray(process, np.int64)
        #: original (invocation, completion) pairs, for witness decoding
        self.ops = ops

    def __len__(self):
        return len(self.invoke_idx)

    @property
    def n_ok(self):
        return int(self.is_ok.sum())

    def sorted_by_invoke(self):
        """Return a copy with rows sorted by invocation index (the order the
        checker requires)."""
        order = np.argsort(self.invoke_idx, kind="stable")
        return EncodedHistory(
            self.invoke_idx[order], self.return_idx[order], self.f[order],
            self.args[order], self.ret[order],
            self.is_ok[order], self.process[order],
            ops=[self.ops[i] for i in order] if self.ops is not None else None)


def encode_history(history, encode_op, arg_width) -> EncodedHistory:
    """Encode an event history into an EncodedHistory.

    ``encode_op(f, value, completion_value) -> (fcode, args_list, ret_list)``
    is the model-specific encoder (see models/*.ModelSpec.encode_op);
    args/ret lists are padded with NIL to ``arg_width``. Completion value is
    None for info ops whose outcome is unknown.

    Rules (knossos semantics):
      * fail ops are dropped (they didn't happen);
      * info ops get return_idx = INF_TIME and an unknown result;
      * invocations with no completion at all are treated as info.
    """
    def pad(xs):
        xs = list(xs)[:arg_width]
        return xs + [NIL] * (arg_width - len(xs))

    history = ensure_indexed(history)
    rows = []
    for inv, comp in pairs(history):
        if inv is None:
            continue  # nemesis-style bare completion; not a client op
        if comp is not None and comp["type"] == FAIL:
            continue
        if comp is not None and comp["type"] == OK:
            fcode, args, ret = encode_op(inv["f"], inv.get("value"),
                                         comp.get("value"))
            rows.append((inv["index"], comp["index"], fcode, pad(args),
                         pad(ret), True, inv["process"], (inv, comp)))
        else:
            # info or missing completion: indeterminate
            fcode, args, ret = encode_op(inv["f"], inv.get("value"), None)
            rows.append((inv["index"], INF_TIME, fcode, pad(args), pad(ret),
                         False, inv["process"], (inv, comp)))
    if not rows:
        z = np.zeros(0)
        za = np.zeros((0, arg_width))
        return EncodedHistory(z, z, z, za, za, np.zeros(0, bool), z, ops=[])
    cols = list(zip(*rows))
    return EncodedHistory(cols[0], cols[1], cols[2], cols[3], cols[4],
                          cols[5], cols[6],
                          ops=list(cols[7])).sorted_by_invoke()


def parse_history_edn_like(rows):
    """Build a history from compact tuples ``(type, process, f, value)`` --
    convenience for tests and golden histories."""
    return index([op(t, p, f, v) for (t, p, f, v) in rows])
