"""Unified observability: tracing + metrics for the whole stack.

One module gives every layer (control → interpreter → nemesis →
checker → store → web → bench, plus the device WGL search) the same two
primitives:

* a span-based **tracer** (`trace.Tracer`) emitting Chrome-trace /
  Perfetto-compatible ``trace.jsonl``;
* a **metrics registry** (`metrics.Registry`) of counters, gauges, and
  latency histograms serialized to ``metrics.json``.

Binding is a module-global pair set by `bind()` — *not* a contextvar —
because instrumented code runs on threads the binder never created
(interpreter workers, checker-competition racers, web handlers); all of
them must see the active sinks. Span *nesting* still flows through a
contextvar (trace._span_stack), so parentage follows the
`contextvars.copy_context()` snapshots the thread fan-outs already
take.

Every facade function below is a safe no-op while nothing is bound:
off-by-default, one global read + falsy check per call site, so the
uninstrumented hot paths pay nothing measurable. `core.run` binds a
fresh pair per test run (opt out with ``test["obs?"] = False``) and
store.py persists both artifacts next to ``results.json``.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time as _time

from .metrics import (DEFAULT_LATENCY_BUCKETS_S, Histogram, Registry,
                      load_metrics_journal, render_prometheus)
from .trace import Tracer, current_span, load_trace, trace_meta

__all__ = [
    "Tracer", "Registry", "Histogram", "DEFAULT_LATENCY_BUCKETS_S",
    "bind", "run_scope", "sink_scope", "tracer", "registry",
    "current_sinks", "run_config", "live_registries", "enabled",
    "current_span",
    "load_trace", "trace_meta", "load_metrics_journal",
    "render_prometheus", "span", "instant", "complete", "counter_track",
    "window_start", "window_end", "name_thread", "now_ns", "inc",
    "set_gauge", "max_gauge", "observe", "observe_many", "gen_event",
    "flush",
]

_lock = threading.Lock()
_tracer = None
_registry = None
#: active bind() scopes in bind order; the newest is the live pair.
#: Exit removes a scope's OWN entry wherever it sits, so overlapping
#: scopes (campaign cells overlap core.runs) unwind in any order
#: without severing a live sibling or leaking a dead pair.
_bind_stack = []

#: run-scoped sinks: (tracer, registry, config) for the RUN this
#: logical context belongs to. The global pair above is
#: last-binder-wins, so with OVERLAPPING campaign cells a device
#: search that read the globals could capture a SIBLING cell's sinks
#: and fold its heartbeat counters into the wrong {campaign, cell}
#: series. The contextvar rides the `contextvars.copy_context()`
#: snapshots the thread fan-outs already take, so code on a run's own
#: threads resolves the run's own pair; threads outside any run fall
#: back to the globals.
_ctx_sinks = contextvars.ContextVar("jepsen_obs_run_sinks",
                                    default=None)


def tracer():
    """The active Tracer, or None."""
    return _tracer


def registry():
    """The active Registry, or None."""
    return _registry


def current_sinks():
    """(tracer, registry) for THIS logical context: the run-scoped
    pair when inside a run (correct even while a sibling campaign
    cell holds the process-global binding), else the globals. The
    device search sessions (obs.search.capture) resolve through this
    so two concurrent cells' heartbeat counters stop folding into one
    series."""
    ctx = _ctx_sinks.get()
    if ctx is not None:
        return ctx[0], ctx[1]
    return _tracer, _registry


def run_config():
    """The run-scoped obs config mapping (progress-interval-s, ...),
    or {} outside any run scope."""
    ctx = _ctx_sinks.get()
    return ctx[2] if ctx is not None and ctx[2] else {}


@contextlib.contextmanager
def sink_scope(tr, reg, config=None):
    """Pin (tracer, registry) as THIS context's run-scoped sinks
    without touching the process-global binding — how a thread that
    captured its run's pair at construction (the monitor) makes the
    search sessions it drives resolve that pair instead of whatever
    the globals currently say."""
    token = _ctx_sinks.set((tr, reg, dict(config or {})))
    try:
        yield (tr, reg)
    finally:
        _ctx_sinks.reset(token)


def live_registries():
    """Every registry with an open bind() scope, oldest first,
    deduped. /api/metrics renders ALL of them (each run's registry
    carries its own {campaign, cell} default labels, so concurrent
    cells expose distinct series), not just the newest binder's."""
    with _lock:
        pairs = list(_bind_stack)
    out = []
    for _tr, reg in pairs:
        if reg is not None and all(reg is not r for r in out):
            out.append(reg)
    return out


def enabled():
    return _tracer is not None or _registry is not None


@contextlib.contextmanager
def bind(tr=None, reg=None):
    """Install (tracer, registry) as the process-wide sinks for the
    duration. Re-entrant for same-thread nesting: the previous pair is
    restored on exit.

    OVERLAPPING binds (campaign cells run core.run concurrently) get
    last-binder-wins semantics: the live pair is the newest still-open
    scope's, and a scope's exit removes its OWN stack entry wherever
    it sits — so the first cell to FINISH can no longer null out a
    still-running sibling's binding mid-run (telemetry then
    cross-attributes to the newest binder, documented best-effort,
    instead of silently vanishing), and the last scope out always
    unbinds cleanly."""
    global _tracer, _registry
    entry = (tr, reg)
    with _lock:
        _bind_stack.append(entry)
        _tracer, _registry = tr, reg
    try:
        yield (tr, reg)
    finally:
        with _lock:
            for i in range(len(_bind_stack) - 1, -1, -1):
                if _bind_stack[i] is entry:
                    del _bind_stack[i]
                    break
            _tracer, _registry = _bind_stack[-1] if _bind_stack \
                else (None, None)


def run_scope(test):
    """The per-test-run binding `core.run` uses: creates a fresh tracer
    + registry (unless ``test["obs?"]`` is falsy), parks them in
    ``test["obs"]`` so store.write_obs can persist them, and binds them
    for the run's duration.

    ``test["obs-context"]`` (set by the campaign scheduler / fleet
    worker: ``{campaign, cell, worker}``) becomes the tracer's
    trace_meta context AND the registry's default labels, so every
    span and metric the run emits stays attributable after the
    campaign-level merge.

    The pair is ALSO pinned as this context's run-scoped sinks
    (`sink_scope`), so the run's own threads — checker competition
    racers, the device search host loops — resolve this run's pair
    through `current_sinks` even while an overlapping sibling cell
    holds the process-global binding."""
    if not test.get("obs?", True):
        test.pop("obs", None)
        return contextlib.nullcontext((None, None))
    ctx = test.get("obs-context")
    tr = Tracer(context=ctx)
    reg = Registry(default_labels=ctx)
    test["obs"] = {"tracer": tr, "registry": reg}
    cfg = {k: test[k] for k in ("progress-interval-s", "phases?")
           if test.get(k) is not None}

    @contextlib.contextmanager
    def scope():
        with bind(tr, reg):
            with sink_scope(tr, reg, cfg):
                yield (tr, reg)

    return scope()


# ---------------------------------------------------------------------------
# tracing facade (no-ops while unbound)

def now_ns():
    tr = _tracer
    return tr.now_ns() if tr is not None else _time.monotonic_ns()


def span(name, cat="lifecycle", tid=None, **args):
    """Context manager: a nested trace span (no-op while unbound)."""
    tr = _tracer
    if tr is None:
        return contextlib.nullcontext()
    return tr.span(name, cat=cat, tid=tid, args=args or None)


def instant(name, cat="default", tid=None, **args):
    tr = _tracer
    if tr is not None:
        tr.instant(name, cat=cat, tid=tid, args=args or None)


def complete(name, ts_ns, dur_ns, cat="default", tid=None, **args):
    tr = _tracer
    if tr is not None:
        tr.complete(name, ts_ns, dur_ns, cat=cat, tid=tid,
                    args=args or None)


def counter_track(name, cat="default", **values):
    tr = _tracer
    if tr is not None:
        tr.counter(name, values, cat=cat)


def window_start(name, wid, cat="nemesis", **args):
    tr = _tracer
    if tr is not None:
        tr.async_begin(name, wid, cat=cat, args=args or None)


def window_end(name, wid, cat="nemesis", **args):
    tr = _tracer
    if tr is not None:
        tr.async_end(name, wid, cat=cat, args=args or None)


def name_thread(tid, name):
    tr = _tracer
    if tr is not None:
        tr.name_thread(tid, name)


def flush(force_metrics=True):
    """Force the bound sinks' journals to disk (no-op when unbound or
    unjournaled): the facade for code that just produced something a
    crash must not lose."""
    tr, reg = _tracer, _registry
    if tr is not None:
        tr.flush_journal()
    if reg is not None and force_metrics:
        reg.journal_now()


def gen_event(tag, kind, payload):
    """The generator.trace combinator's tap: one instant event per
    traced op/update, alongside its existing log line. The repr is
    capped like every other instrumentation site — traced generators
    over large values must not bloat the event buffer."""
    tr = _tracer
    if tr is not None:
        tr.instant(f"gen.{tag}", cat="generator",
                   args={"kind": kind, "event": repr(payload)[:200]})


# ---------------------------------------------------------------------------
# metrics facade (no-ops while unbound)

def inc(name, n=1, **labels):
    reg = _registry
    if reg is not None:
        reg.inc(name, n, **labels)


def set_gauge(name, value, **labels):
    reg = _registry
    if reg is not None:
        reg.set_gauge(name, value, **labels)


def max_gauge(name, value, **labels):
    reg = _registry
    if reg is not None:
        reg.max_gauge(name, value, **labels)


def observe(name, value, buckets=None, **labels):
    reg = _registry
    if reg is not None:
        reg.observe(name, value, buckets=buckets, **labels)


def observe_many(name, values, buckets=None, **labels):
    """Batch form of `observe`: one lock acquisition for the lot."""
    reg = _registry
    if reg is not None:
        reg.observe_many(name, values, buckets=buckets, **labels)
