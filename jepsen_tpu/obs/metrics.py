"""Metrics registry: counters, gauges, and fixed-bucket histograms.

Prometheus-shaped but dependency-free: a `Registry` holds named series
(with optional labels), and `snapshot()` renders everything to one
plain-JSON dict — the ``metrics.json`` artifact store.py writes next to
``results.json``. All mutation is lock-protected; instrumented hot
paths (one op completion = one counter bump + one histogram observe)
stay cheap.
"""

from __future__ import annotations

import math
import threading

#: fixed latency buckets, seconds: ~log-spaced from 100 µs to 2 min.
#: Counts are PER-BUCKET (not cumulative); values above the last bound
#: land in one overflow bucket, so len(counts) == len(bounds) + 1.
DEFAULT_LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def bucket_index(bounds, value):
    """Index of the bucket ``value`` falls in: first i with value <=
    bounds[i], else len(bounds) (the overflow bucket)."""
    for i, b in enumerate(bounds):
        if value <= b:
            return i
    return len(bounds)


class Histogram:
    """Fixed-bucket histogram with sum/count/min/max."""

    def __init__(self, bounds=DEFAULT_LATENCY_BUCKETS_S):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value):
        value = float(value)
        self.counts[bucket_index(self.bounds, value)] += 1
        self.sum += value
        self.count += 1
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def quantile(self, q):
        """Estimated q-quantile (0..1) by linear walk over the buckets;
        None when empty. Values in the overflow bucket report the max."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                if i == len(self.bounds):
                    return self.max
                return self.bounds[i]
        return self.max

    def to_dict(self):
        return {"buckets_le": list(self.bounds),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.count,
                "min": None if self.count == 0 else self.min,
                "max": None if self.count == 0 else self.max}


def _key(name, labels):
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Registry:
    """Thread-safe home for counters, gauges, and histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}

    def inc(self, name, n=1, **labels):
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + n

    def set_gauge(self, name, value, **labels):
        k = _key(name, labels)
        with self._lock:
            self._gauges[k] = value

    def max_gauge(self, name, value, **labels):
        """Set a gauge to max(current, value) — high-water marks."""
        k = _key(name, labels)
        with self._lock:
            cur = self._gauges.get(k)
            if cur is None or value > cur:
                self._gauges[k] = value

    def observe(self, name, value, buckets=None, **labels):
        k = _key(name, labels)
        with self._lock:
            hist = self._histograms.get(k)
            if hist is None:
                hist = self._histograms[k] = Histogram(
                    buckets or DEFAULT_LATENCY_BUCKETS_S)
            hist.observe(value)

    def histogram(self, name, **labels):
        with self._lock:
            return self._histograms.get(_key(name, labels))

    def counter_value(self, name, **labels):
        with self._lock:
            return self._counters.get(_key(name, labels), 0)

    def gauge_value(self, name, **labels):
        with self._lock:
            return self._gauges.get(_key(name, labels))

    def snapshot(self):
        """One plain-JSON dict of everything: the metrics.json payload."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.to_dict()
                               for k, h in self._histograms.items()},
            }
