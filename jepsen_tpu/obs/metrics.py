"""Metrics registry: counters, gauges, and fixed-bucket histograms.

Prometheus-shaped but dependency-free: a `Registry` holds named series
(with optional labels), and `snapshot()` renders everything to one
plain-JSON dict — the ``metrics.json`` artifact store.py writes next to
``results.json``. All mutation is lock-protected; instrumented hot
paths (one op completion = one counter bump + one histogram observe)
stay cheap.

Fleet-plane additions (doc/observability.md):

* **Default labels.** A registry built with ``default_labels``
  (``{campaign, cell, worker}`` for fleet runs) merges them into every
  series key, so a worker's metrics stay attributable after the
  campaign-level fold without call sites threading identity around.
* **Crash-safe journal.** `attach_journal` appends a full snapshot
  line at most every ``flush_s`` seconds (and on `journal_now`), so a
  kill -9'd process leaves its last metrics snapshot on disk;
  `load_metrics_journal` reads the last parseable line back
  (torn-tail tolerant). The atomic ``metrics.json`` dump stays the
  finalize; `close_journal(remove=True)` retires the journal.
* **Exposition.** `render_prometheus` renders registries (and
  structured gauge/counter sections) in the Prometheus text format —
  the body of the fleet service's ``GET /api/metrics``.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time as _time

#: fixed latency buckets, seconds: ~log-spaced from 100 µs to 2 min.
#: Counts are PER-BUCKET (not cumulative); values above the last bound
#: land in one overflow bucket, so len(counts) == len(bounds) + 1.
DEFAULT_LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def bucket_index(bounds, value):
    """Index of the bucket ``value`` falls in: first i with value <=
    bounds[i], else len(bounds) (the overflow bucket)."""
    for i, b in enumerate(bounds):
        if value <= b:
            return i
    return len(bounds)


class Histogram:
    """Fixed-bucket histogram with sum/count/min/max."""

    def __init__(self, bounds=DEFAULT_LATENCY_BUCKETS_S):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value):
        value = float(value)
        self.counts[bucket_index(self.bounds, value)] += 1
        self.sum += value
        self.count += 1
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def quantile(self, q):
        """Estimated q-quantile (0..1) by linear walk over the buckets;
        None when empty. Values in the overflow bucket report the max."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                if i == len(self.bounds):
                    return self.max
                return self.bounds[i]
        return self.max

    def to_dict(self):
        return {"buckets_le": list(self.bounds),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.count,
                "min": None if self.count == 0 else self.min,
                "max": None if self.count == 0 else self.max}


def _key_str(name, labels):
    """The flattened ``name{k=v,...}`` form (labels sorted) used in
    snapshot()/metrics.json — unchanged on-disk shape."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


def _key(name, labels):
    """Back-compat helper: flattened key from a labels mapping."""
    return _key_str(name, tuple(sorted(
        (str(k), str(v)) for k, v in (labels or {}).items())))


def parse_flat_key(key):
    """``name{k=v,...}`` -> (name, {k: v}) — the inverse of the
    snapshot()/metrics.json flattened keys, shared by every consumer
    (web's utilization table, the campaign metrics fold, the fleet
    dispatcher's live re-fold). Best effort: label VALUES containing
    ``=``/``,`` parse wrong, which costs one folded cell, not data."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for part in rest.rstrip("}").split(","):
        k, _, v = part.partition("=")
        labels[k] = v
    return name, labels


class Registry:
    """Thread-safe home for counters, gauges, and histograms.

    Series are keyed internally by ``(name, ((label, value), ...))``
    tuples (labels sorted), so the exposition renderer never has to
    re-parse flattened key strings whose label VALUES may themselves
    contain ``=``/``,`` (campaign cell ids do). ``snapshot()`` still
    emits the flattened ``name{k=v,...}`` strings metrics.json always
    had."""

    def __init__(self, default_labels=None):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}
        self._defaults = {str(k): str(v)
                          for k, v in (default_labels or {}).items()
                          if v is not None}
        self._journal = None
        self._journal_path = None
        self._journal_flush_s = 0.5
        self._journal_last = 0.0
        self._journal_stop = None
        #: mutation counter; the background flusher skips the snapshot
        #: when nothing changed since its last write
        self._mut = 0
        self._journal_mut = -1
        #: (name, raw label items) -> built key. Instrumented hot
        #: paths hit the same few (name, labels) shapes thousands of
        #: times per run; caching skips the default-merge + sort +
        #: str() walk. Bounded so a high-cardinality label can't leak.
        self._kcache = {}

    def _k(self, name, labels):
        try:
            ck = (name, tuple(labels.items()))
            k = self._kcache.get(ck)
        except TypeError:       # unhashable label value
            ck = k = None
        if k is None:
            if self._defaults:
                labels = {**self._defaults, **labels}
            k = (str(name), tuple(sorted(
                (str(kk), str(v)) for kk, v in labels.items())))
            if ck is not None and len(self._kcache) < 4096:
                self._kcache[ck] = k
        return k

    def inc(self, name, n=1, **labels):
        k = self._k(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + n
            self._mut += 1
            self._maybe_journal()

    def set_gauge(self, name, value, **labels):
        k = self._k(name, labels)
        with self._lock:
            self._gauges[k] = value
            self._mut += 1
            self._maybe_journal()

    def max_gauge(self, name, value, **labels):
        """Set a gauge to max(current, value) — high-water marks."""
        k = self._k(name, labels)
        with self._lock:
            cur = self._gauges.get(k)
            if cur is None or value > cur:
                self._gauges[k] = value
            self._mut += 1
            self._maybe_journal()

    def observe(self, name, value, buckets=None, **labels):
        k = self._k(name, labels)
        with self._lock:
            hist = self._histograms.get(k)
            if hist is None:
                hist = self._histograms[k] = Histogram(
                    buckets or DEFAULT_LATENCY_BUCKETS_S)
            hist.observe(value)
            self._mut += 1
            self._maybe_journal()

    def observe_many(self, name, values, buckets=None, **labels):
        """Fold a batch of observations into one histogram under a
        single lock acquisition + key construction — the interpreter's
        per-op telemetry fold uses this so the op hot path never
        touches the registry."""
        if not values:
            return
        k = self._k(name, labels)
        with self._lock:
            hist = self._histograms.get(k)
            if hist is None:
                hist = self._histograms[k] = Histogram(
                    buckets or DEFAULT_LATENCY_BUCKETS_S)
            for v in values:
                hist.observe(v)
            self._mut += 1
            self._maybe_journal()

    def histogram(self, name, **labels):
        with self._lock:
            return self._histograms.get(self._k(name, labels))

    def counter_value(self, name, **labels):
        with self._lock:
            return self._counters.get(self._k(name, labels), 0)

    def gauge_value(self, name, **labels):
        with self._lock:
            return self._gauges.get(self._k(name, labels))

    def _snapshot_locked(self):
        return {
            "counters": {_key_str(n, lb): v
                         for (n, lb), v in self._counters.items()},
            "gauges": {_key_str(n, lb): v
                       for (n, lb), v in self._gauges.items()},
            "histograms": {_key_str(n, lb): h.to_dict()
                           for (n, lb), h in self._histograms.items()},
        }

    def snapshot(self):
        """One plain-JSON dict of everything: the metrics.json payload."""
        with self._lock:
            return self._snapshot_locked()

    def series(self):
        """The structured view the Prometheus renderer consumes:
        {"counters"/"gauges": {(name, labels): value}, "histograms":
        {(name, labels): to_dict()}}."""
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "histograms": {k: h.to_dict()
                                   for k, h in
                                   self._histograms.items()}}

    # -- crash-safe journal ---------------------------------------------

    def attach_journal(self, path, flush_s=0.5):
        """Start journaling snapshots to ``path``: one full-snapshot
        JSON line immediately, then at most one per ``flush_s``
        seconds as mutations land. Contained: journaling failures drop
        the journal, never the run."""
        import os
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._lock:
            self._close_journal_locked()
            try:
                self._journal = open(path, "w")
            except OSError:
                return None
            self._journal_path = path
            self._journal_flush_s = max(0.0, float(flush_s))
            self._journal_last = 0.0
            self._journal_write_locked(_time.monotonic())
            if self._journal_flush_s > 0:
                stop = self._journal_stop = threading.Event()
                threading.Thread(
                    target=self._journal_loop, args=(stop,),
                    name="obs-metrics-journal", daemon=True).start()
            return path

    def _journal_loop(self, stop):
        """Background flusher: one snapshot line per flush interval,
        skipped while nothing mutated. Keeps the mutation hot paths to
        a counter bump — no inline serialization, no interval check —
        and snapshots a quiet-but-alive registry's final state even
        when no further mutation ever lands."""
        while not stop.wait(self._journal_flush_s):
            with self._lock:
                if self._journal is None or self._journal_stop is not stop:
                    return
                if self._mut != self._journal_mut:
                    self._journal_write_locked(_time.monotonic())

    def _maybe_journal(self):
        # flush_s <= 0 = synchronous per-mutation durability; with a
        # positive interval the background flusher owns the writes
        if self._journal is not None and self._journal_flush_s <= 0:
            self._journal_write_locked(_time.monotonic())

    def _journal_write_locked(self, now):
        try:
            self._journal.write(
                json.dumps(self._snapshot_locked(), default=str) + "\n")
            self._journal.flush()
            self._journal_last = now
            self._journal_mut = self._mut
        except (OSError, ValueError, TypeError):
            self._journal = None

    def journaling(self):
        """True while an incremental journal is attached and healthy."""
        return self._journal is not None

    def journal_now(self):
        """Force one snapshot line to disk regardless of the flush
        interval (search heartbeats call this so a watchdog-killed
        search leaves its last counters readable)."""
        with self._lock:
            if self._journal is not None:
                self._journal_write_locked(_time.monotonic())

    def _close_journal_locked(self):
        if self._journal_stop is not None:
            self._journal_stop.set()
            self._journal_stop = None
        f, self._journal = self._journal, None
        if f is not None:
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass

    def close_journal(self, remove=False):
        with self._lock:
            self._close_journal_locked()
            path, self._journal_path = self._journal_path, None
        if remove and path:
            import os
            try:
                os.remove(path)
            except OSError:
                pass


def load_metrics_journal(path):
    """The LAST parseable snapshot line of a metrics journal, or None.
    A process killed mid-append leaves a torn final line; the line
    before it is the freshest complete snapshot."""
    last = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    snap = json.loads(line)
                except ValueError:
                    continue
                if isinstance(snap, dict):
                    last = snap
    except OSError:
        return None
    return last


# ---------------------------------------------------------------------------
# Prometheus text exposition (the /api/metrics body)

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name, prefix="jepsen"):
    n = _PROM_NAME_RE.sub("_", str(name))
    if prefix:
        n = f"{prefix}_{n}"
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _prom_escape(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels, extra=()):
    items = tuple(labels) + tuple(extra)
    if not items:
        return ""
    inner = ",".join(f'{_PROM_NAME_RE.sub("_", str(k))}='
                     f'"{_prom_escape(v)}"' for k, v in items)
    return "{" + inner + "}"


def _norm_series(section):
    """One section -> (counters, gauges, histograms) with
    ``(name, ((k, v), ...))`` keys. Accepts a Registry or a structured
    dict whose keys may be plain names (no labels) or key tuples."""
    if isinstance(section, Registry):
        s = section.series()
    else:
        s = section or {}

    def norm(d):
        out = {}
        for k, v in (d or {}).items():
            if isinstance(k, tuple):
                out[(str(k[0]), tuple(k[1]))] = v
            else:
                out[(str(k), ())] = v
        return out

    return (norm(s.get("counters")), norm(s.get("gauges")),
            norm(s.get("histograms")))


def _num(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def render_prometheus(sections, prefix="jepsen"):
    """Render registries/sections in the Prometheus text exposition
    format (version 0.0.4). ``sections`` is an iterable of Registry
    instances or structured dicts ({"counters": {...}, "gauges":
    {...}, "histograms": {...}}); later sections win on exact key
    collisions. Histograms convert to cumulative ``_bucket`` series
    (+Inf included) plus ``_sum``/``_count``. Output is sorted —
    deterministic for identical inputs — and non-numeric gauge values
    are skipped (a path-valued gauge has no exposition)."""
    counters, gauges, histograms = {}, {}, {}
    for section in sections:
        c, g, h = _norm_series(section)
        counters.update(c)
        gauges.update(g)
        histograms.update(h)

    lines = []

    def family(kind, series, suffix=""):
        by_name = {}
        for (name, labels), v in series.items():
            n = _num(v)
            if n is None:   # a path-valued gauge has no exposition --
                continue    # and must not leave a dangling TYPE line
            by_name.setdefault(name, []).append((labels, n))
        for name in sorted(by_name):
            pname = _prom_name(name, prefix)
            lines.append(f"# TYPE {pname} {kind}")
            for labels, n in sorted(by_name[name]):
                body = int(n) if float(n).is_integer() else n
                lines.append(
                    f"{pname}{suffix}{_prom_labels(labels)} {body}")

    family("counter", counters)
    family("gauge", gauges)

    by_name = {}
    for (name, labels), h in histograms.items():
        by_name.setdefault(name, []).append((labels, h))
    for name in sorted(by_name):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} histogram")
        for labels, h in sorted(by_name[name],
                                key=lambda lh: lh[0]):
            if isinstance(h, Histogram):
                h = h.to_dict()
            bounds = h.get("buckets_le") or []
            cum = 0
            for b, c in zip(bounds, h.get("counts") or []):
                cum += c
                lines.append(f"{pname}_bucket"
                             f"{_prom_labels(labels, ((('le'), f'{b:g}'),))}"
                             f" {cum}")
            lines.append(f"{pname}_bucket"
                         f"{_prom_labels(labels, (('le', '+Inf'),))}"
                         f" {h.get('count', 0)}")
            lines.append(f"{pname}_sum{_prom_labels(labels)} "
                         f"{h.get('sum', 0.0)}")
            lines.append(f"{pname}_count{_prom_labels(labels)} "
                         f"{h.get('count', 0)}")
    return "\n".join(lines) + "\n"
