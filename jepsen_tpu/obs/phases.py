"""Per-dispatch phase attribution for the device WGL host loops.

PR 13's introspection plane answers "is the search moving?" but not
"where does the wall go?": ``wgl.device_busy_s`` bracketed the whole
host-side dispatch chunk, so transfer, compile, and host expansion were
invisible inside the "busy" number. This module splits every device
dispatch — in the single-key loop (checker/jax_wgl.py), the key batch
(parallel/keyshard.py), the mesh shard (parallel/searchshard.py), and
the coalescer/monitor paths that ride them — into named phase spans:

========  ===========================================================
phase     covers
========  ===========================================================
encode    history -> op-table encoding, fast paths, pruning
plan      bucket/size planning, kernel build, compile-ledger note
h2d       host->device transfer of op columns and the initial carry
compile   the first device dispatch after a compile-ledger MISS (its
          wall is dominated by XLA compile, not stepping)
device    the device-compute bracket proper: dispatch ->
          ``block_until_ready`` on the donated carry
d2h       the batched progress ``device_get`` + final harvest reads
host      everything else on the host between dispatches: heartbeat
          bookkeeping, quantum adaptation, expansion/dedup of
          results, batch compaction rebuilds, verdict interpretation
wait      slot/queue wait (coalescer queue latency, the monitor's
          device-semaphore acquisition) — emitted via `note_wait`
========  ===========================================================

Each lap lands twice: a ``cat="phase"`` complete span on the trace
(``wgl.phase.<name>``, so obs/bubbles.py can walk a lane and classify
every idle gap) and a ``wgl.phase_s{phase,engine}`` counter in the
registry (so the campaign fold and ``/api/metrics`` carry the same
breakdown without a trace in hand).

The session is a CURSOR, not a stack: ``lap(name)`` attributes all
wall since the previous lap/mark to ``name`` and advances the cursor,
so consecutive spans are exactly contiguous and non-overlapping by
construction — the invariant the bubble ledger's >=95% attribution
target rests on. The cursor lives in ``monotonic_ns`` and is mapped
onto the tracer's clock through one constant offset captured at
session start, so contiguity survives the conversion exactly.

Cost discipline: when obs is unbound (or the run sets ``phases?:
false``) a session is two ``monotonic_ns`` reads per lap and the
engines skip the extra ``block_until_ready`` sync entirely — the
dispatch loops' own device syncs dominate regardless.
"""

from __future__ import annotations

import time as _time

from . import current_sinks, run_config

__all__ = ["PHASES", "CAT", "METRIC", "capture", "note_wait",
           "PhaseSession"]

#: the closed phase vocabulary (PL022 and the bubble fold key off it)
PHASES = ("encode", "plan", "h2d", "compile", "device", "d2h", "host",
          "wait")

#: trace category of every phase span (the bubble fold's filter)
CAT = "phase"

#: registry counter: seconds per {phase, engine}
METRIC = "wgl.phase_s"


def capture(engine):
    """Snapshot this context's sinks into a phase session for one
    search. Honors the run's ``phases?`` knob (default on whenever obs
    is bound): a disabled session measures nothing extra and emits
    nothing."""
    tr, reg = current_sinks()
    if run_config().get("phases?") is False:
        tr = reg = None
    return PhaseSession(engine, tr, reg)


def note_wait(engine, wait_s, **args):
    """Emit ONE slot/queue-wait span ending now against the caller's
    current sinks: the coalescer's enqueue->dispatch latency, the
    monitor's device-semaphore wait. These phases are measured by
    their owners (the wait brackets code outside any engine's
    session), so they enter the attribution plane through this module
    function instead of a session lap."""
    tr, reg = current_sinks()
    if run_config().get("phases?") is False:
        return
    try:
        wait_s = max(0.0, float(wait_s))
    except (TypeError, ValueError):
        return
    if reg is not None:
        reg.inc(METRIC, wait_s, phase="wait", engine=engine)
    if tr is not None:
        dur_ns = int(wait_s * 1e9)
        tr.complete("wgl.phase.wait", max(0, tr.now_ns() - dur_ns),
                    dur_ns, cat=CAT,
                    args={"engine": engine, **args})


class PhaseSession:
    """One search's phase cursor (see module docstring).

    ``totals`` accumulates seconds per phase for the session —
    engines fold it into their result diagnostics and tests pin the
    contiguity invariants against it."""

    def __init__(self, engine, tr, reg):
        self.engine = engine
        self._tr = tr
        self._reg = reg
        self.enabled = tr is not None or reg is not None
        self._cursor = _time.monotonic_ns()
        # constant monotonic->tracer clock offset: applied to every
        # span start so consecutive laps stay EXACTLY contiguous
        self._off = (tr.now_ns() - _time.monotonic_ns()) \
            if tr is not None else 0
        self._compile_pending = False
        self.totals = {}

    def note_compile(self, miss):
        """Arm the compile phase: the NEXT device lap is attributed to
        ``compile`` instead (the compile-ledger said this shape was
        never traced in this process, so that dispatch's wall is XLA's,
        not the kernel's). Hits arm nothing."""
        if miss:
            self._compile_pending = True

    def mark(self):
        """Reset the cursor to now, dropping the wall since the last
        lap from attribution (used only at session start)."""
        self._cursor = _time.monotonic_ns()

    def lap(self, phase, **args):
        """Attribute all wall since the previous lap/mark to ``phase``
        and advance the cursor. Returns the lap's seconds (measured
        even when disabled, so callers can reuse the number)."""
        now = _time.monotonic_ns()
        d_ns = now - self._cursor
        ts_ns = self._cursor + self._off
        self._cursor = now
        if d_ns < 0:
            return 0.0
        dt = d_ns / 1e9
        if not self.enabled:
            return dt
        if phase == "device" and self._compile_pending:
            phase = "compile"
            self._compile_pending = False
        self.totals[phase] = self.totals.get(phase, 0.0) + dt
        if self._reg is not None:
            self._reg.inc(METRIC, dt, phase=phase, engine=self.engine)
        if self._tr is not None:
            self._tr.complete(f"wgl.phase.{phase}", ts_ns, d_ns,
                              cat=CAT,
                              args={"engine": self.engine, **args})
        return dt

    def sync(self, *arrays):
        """``block_until_ready`` the given device values — but ONLY
        when the session is enabled: with phases off the dispatch loop
        keeps its original async shape (the progress ``device_get``
        remains the only sync) and pays nothing."""
        if self.enabled:
            import jax
            for a in arrays:
                if a is not None:
                    jax.block_until_ready(a)
