"""Span-based tracer emitting Chrome-trace / Perfetto-compatible events.

The reference framework has no tracing at all; every perf claim in this
repo used to rest on ad-hoc timers (SURVEY.md §5). This tracer turns a
test run into a self-evidencing artifact: `Tracer` collects events in
memory (thread-safe, bounded) and `dump()` writes them in the Chrome
Trace Event JSON format — one event object per line, so the file is
simultaneously grep/`jq`-able line-by-line JSONL *and* loadable as-is in
`chrome://tracing` and Perfetto's JSON importer (the format spec makes
the enclosing ``[``/``]`` optional and tolerates trailing commas; the
dump writes a leading ``[`` line and a trailing comma per event).

Event kinds used here:

* ``X`` complete events — spans with a start timestamp and duration
  (lifecycle phases, per-op invoke→complete, remote exec calls).
* ``i`` instant events — point-in-time markers (generator trace taps,
  search heartbeats).
* ``C`` counter events — numeric series Perfetto renders as tracks
  (WGL frontier depth, states explored).
* ``b``/``e`` async events — durations that start and end on different
  threads (nemesis fault windows: the ``start`` and ``stop`` ops run as
  separate nemesis invocations).
* ``M`` metadata events — thread names for the logical-worker tids.

Timestamps are microseconds relative to the tracer's creation
(``time.monotonic_ns`` based, like util.relative_time). Span *nesting*
propagates through a contextvar stack, so `contextvars.copy_context()`
— which the interpreter's worker spawn and control's on_nodes fan-out
already use — carries the parent span across threads for free.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time as _time

#: parent-span stack: a tuple of span names, carried across threads by
#: the contextvars snapshots the interpreter/control fan-outs already
#: take (empty tuple = root)
_span_stack = contextvars.ContextVar("obs_span_stack", default=())

#: hard cap on buffered events: a runaway heartbeat loop must not eat
#: the host's memory; overflow increments ``dropped`` instead
MAX_EVENTS = 1_000_000


def current_span():
    """Name of the innermost active span, or None at the root."""
    stack = _span_stack.get()
    return stack[-1] if stack else None


class Tracer:
    """Collects Chrome-trace events; `dump(path)` persists them."""

    def __init__(self, max_events=MAX_EVENTS):
        self._events = []
        self._lock = threading.Lock()
        self._t0 = _time.monotonic_ns()
        self._pid = os.getpid()
        self._named_tids = set()
        self._max_events = max_events
        self.dropped = 0

    # -- clock ----------------------------------------------------------

    def now_ns(self):
        """ns since this tracer's epoch (monotonic)."""
        return _time.monotonic_ns() - self._t0

    # -- raw emission ---------------------------------------------------

    def _emit(self, ev):
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def _base(self, name, ph, cat, ts_ns, tid):
        if tid is None:
            tid = threading.get_ident()
        return {"name": name, "ph": ph, "cat": cat,
                "ts": ts_ns / 1e3,            # Chrome trace: microseconds
                "pid": self._pid, "tid": tid}

    def name_thread(self, tid, name):
        """Emit a thread-name metadata event once per tid (Perfetto shows
        these as track labels — e.g. logical worker ids)."""
        with self._lock:
            if tid in self._named_tids:
                return
            self._named_tids.add(tid)
        ev = self._base("thread_name", "M", "__metadata", 0, tid)
        ev["args"] = {"name": str(name)}
        self._emit(ev)

    # -- event kinds ----------------------------------------------------

    def complete(self, name, ts_ns, dur_ns, cat="default", tid=None,
                 args=None):
        """An ``X`` span with an externally measured start/duration (the
        interpreter measures op latency itself; the tracer just
        records)."""
        ev = self._base(name, "X", cat, ts_ns, tid)
        ev["dur"] = max(0, dur_ns) / 1e3
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name, cat="default", tid=None, args=None):
        ev = self._base(name, "i", cat, self.now_ns(), tid)
        ev["s"] = "t"                         # thread-scoped instant
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name, values, cat="default"):
        """A ``C`` event: {series: number} rendered as counter tracks."""
        ev = self._base(name, "C", cat, self.now_ns(), self._pid)
        ev["args"] = {k: float(v) for k, v in values.items()}
        self._emit(ev)

    def async_begin(self, name, wid, cat="default", args=None):
        ev = self._base(name, "b", cat, self.now_ns(),
                        threading.get_ident())
        ev["id"] = str(wid)
        if args:
            ev["args"] = args
        self._emit(ev)

    def async_end(self, name, wid, cat="default", args=None):
        ev = self._base(name, "e", cat, self.now_ns(),
                        threading.get_ident())
        ev["id"] = str(wid)
        if args:
            ev["args"] = args
        self._emit(ev)

    @contextlib.contextmanager
    def span(self, name, cat="lifecycle", tid=None, args=None):
        """A nested span: records an ``X`` event on exit and pushes the
        name onto the contextvar parent stack for the duration, so spans
        opened inside (including in threads spawned from a context
        snapshot taken inside) carry ``args.parent``."""
        stack = _span_stack.get()
        token = _span_stack.set(stack + (name,))
        t0 = self.now_ns()
        try:
            yield
        finally:
            _span_stack.reset(token)
            a = dict(args or {})
            if stack:
                a["parent"] = stack[-1]
            self.complete(name, t0, self.now_ns() - t0, cat=cat,
                          tid=tid, args=a or None)

    # -- persistence ----------------------------------------------------

    def events(self):
        with self._lock:
            return list(self._events)

    def dump(self, path):
        """Write trace.jsonl: a ``[`` line, then one event per line with
        a trailing comma. Loads directly in chrome://tracing / Perfetto
        (the JSON array format's closing bracket is optional) and stays
        line-parseable (strip the trailing comma). A buffer overflow is
        recorded IN the file (a final ``trace_truncated`` instant with
        the dropped count) — a silently truncated trace reads as
        "activity stopped here", which is exactly the wrong conclusion
        during a stall diagnosis."""
        events = self.events()
        if self.dropped:
            ev = self._base("trace_truncated", "i", "__metadata",
                            self.now_ns(), self._pid)
            ev["s"] = "g"
            ev["args"] = {"dropped_events": self.dropped,
                          "max_events": self._max_events}
            events.append(ev)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("[\n")
            for ev in events:
                f.write(json.dumps(ev) + ",\n")
        os.replace(tmp, path)
        return path


def load_trace(path):
    """Parse a trace.jsonl back into a list of event dicts (tolerant of
    the leading ``[`` and trailing commas — i.e. exactly what dump
    writes, and also plain one-object-per-line JSONL)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            events.append(json.loads(line))
    return events
