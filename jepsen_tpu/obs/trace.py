"""Span-based tracer emitting Chrome-trace / Perfetto-compatible events.

The reference framework has no tracing at all; every perf claim in this
repo used to rest on ad-hoc timers (SURVEY.md §5). This tracer turns a
test run into a self-evidencing artifact: `Tracer` collects events in
memory (thread-safe, bounded) and `dump()` writes them in the Chrome
Trace Event JSON format — one event object per line, so the file is
simultaneously grep/`jq`-able line-by-line JSONL *and* loadable as-is in
`chrome://tracing` and Perfetto's JSON importer (the format spec makes
the enclosing ``[``/``]`` optional and tolerates trailing commas; the
dump writes a leading ``[`` line and a trailing comma per event).

Event kinds used here:

* ``X`` complete events — spans with a start timestamp and duration
  (lifecycle phases, per-op invoke→complete, remote exec calls).
* ``i`` instant events — point-in-time markers (generator trace taps,
  search heartbeats).
* ``C`` counter events — numeric series Perfetto renders as tracks
  (WGL frontier depth, states explored).
* ``b``/``e`` async events — durations that start and end on different
  threads (nemesis fault windows: the ``start`` and ``stop`` ops run as
  separate nemesis invocations).
* ``M`` metadata events — thread names for the logical-worker tids.

Timestamps are microseconds relative to the tracer's creation
(``time.monotonic_ns`` based, like util.relative_time). Span *nesting*
propagates through a contextvar stack, so `contextvars.copy_context()`
— which the interpreter's worker spawn and control's on_nodes fan-out
already use — carries the parent span across threads for free.

Two additions for the FLEET telemetry plane (doc/observability.md):

* **Wall-clock anchor + context.** Every tracer records the wall epoch
  (``time.time_ns``) at creation and an optional ``context`` mapping
  ({campaign, cell, worker} for fleet runs). Both ride in a
  ``trace_meta`` metadata event at the head of the dump/journal, which
  is what lets ``obs.merge`` place per-worker traces on one normalized
  timeline and attribute whole files to their campaign cell without
  per-event label bloat.
* **Crash-safe journal.** `attach_journal` mirrors every event to an
  append+flush journal file (``trace.jsonl.journal``, the
  store.HistoryJournal discipline): a kill -9'd process leaves
  everything up to the kill on disk, torn final line dropped on read.
  `dump()` stays the atomic finalize; once it succeeds the caller
  retires the journal (`close_journal(remove=True)`).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time as _time

#: parent-span stack: a tuple of span names, carried across threads by
#: the contextvars snapshots the interpreter/control fan-outs already
#: take (empty tuple = root)
_span_stack = contextvars.ContextVar("obs_span_stack", default=())

#: hard cap on buffered events: a runaway heartbeat loop must not eat
#: the host's memory; overflow increments ``dropped`` instead
MAX_EVENTS = 1_000_000


def current_span():
    """Name of the innermost active span, or None at the root."""
    stack = _span_stack.get()
    return stack[-1] if stack else None


class Tracer:
    """Collects Chrome-trace events; `dump(path)` persists them."""

    def __init__(self, max_events=MAX_EVENTS, context=None):
        self._events = []
        self._lock = threading.Lock()
        self._t0 = _time.monotonic_ns()
        #: wall-clock anchor for cross-process merging: the wall time
        #: this tracer's ts=0 corresponds to (best effort -- a time
        #: nemesis stepping the wall clock skews it, which is exactly
        #: what the merge's handshake-based normalization corrects)
        self.epoch_ns = _time.time_ns()
        self.context = dict(context or {})
        self._pid = os.getpid()
        self._named_tids = set()
        self._max_events = max_events
        self.dropped = 0
        self._journal = None
        self._journal_path = None
        self._journal_flush_s = 0.0
        self._journal_last = 0.0
        self._journal_stop = None
        #: serialized forms of ``_events[:len(_ser)]`` — filled lazily
        #: in batches by `_serialized_upto` (events are never mutated
        #: after _emit, so deferring is safe). Each event is JSON-
        #: encoded exactly ONCE and the string is shared by the
        #: journal's incremental appends and the final dump().
        self._ser = []
        #: how many events the journal has on disk already
        self._journal_written = 0

    # -- clock ----------------------------------------------------------

    def now_ns(self):
        """ns since this tracer's epoch (monotonic)."""
        return _time.monotonic_ns() - self._t0

    # -- raw emission ---------------------------------------------------

    def _emit(self, ev):
        # lock-free: CPython's list.append is atomic, and the
        # serialization cache / journal / dump only ever read a
        # length-prefix snapshot taken under the lock. The cap check
        # may overshoot by a few racing events (it is a memory guard,
        # not a contract) and a racing dropped count may undercount —
        # both harmless, and the hot path pays one append.
        if len(self._events) >= self._max_events:
            self.dropped += 1
            return
        self._events.append(ev)
        # flush_s <= 0 = synchronous per-event durability; with a
        # positive interval the background flusher owns the writes.
        # The unlocked peek is a fast-path filter only -- the journal
        # handle is re-checked under the lock, so a close racing this
        # emit can't flush into a None/closed file
        if self._journal is not None and self._journal_flush_s <= 0:
            with self._lock:
                if self._journal is not None:
                    self._journal_flush_locked(_time.monotonic())

    def _serialized_upto(self, n):
        """Extend the one-shot serialization cache to cover the first
        ``n`` events and return it (lock held)."""
        ser, events = self._ser, self._events
        while len(ser) < n:
            ser.append(json.dumps(events[len(ser)],
                                  separators=(",", ":")))
        return ser

    # -- crash-safe journal ---------------------------------------------

    def meta_event(self):
        """The ``trace_meta`` metadata event: wall epoch + context.
        Written at the head of every dump/journal (never buffered, so
        it doesn't count against the event cap)."""
        ev = self._base("trace_meta", "i", "__metadata", 0, self._pid)
        ev["s"] = "g"
        ev["args"] = {"epoch_ns": self.epoch_ns}
        if self.context:
            ev["args"]["context"] = dict(self.context)
        return ev

    def attach_journal(self, path, flush_s=0.5):
        """Start mirroring events to an incremental journal at ``path``
        (one JSON line per event, HistoryJournal discipline): already
        buffered events are backfilled, then every `_emit` enqueues.
        The hot path pays one list append; serialization + write +
        flush happen in batches at most every ``flush_s`` seconds
        (<= 0 = every event); `flush_journal` forces one. Failures are
        contained -- the journal is crash insurance, never
        load-bearing."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._lock:
            self.close_journal_locked()
            try:
                f = open(path, "w")
                n = len(self._events)
                f.write(json.dumps(self.meta_event()) + "\n")
                f.write("".join(s + "\n"
                                for s in self._serialized_upto(n)[:n]))
                f.flush()
            except OSError:
                return None
            self._journal = f
            self._journal_path = path
            self._journal_written = n
            self._journal_flush_s = max(0.0, float(flush_s))
            self._journal_last = _time.monotonic()
            if self._journal_flush_s > 0:
                stop = self._journal_stop = threading.Event()
                threading.Thread(
                    target=self._journal_loop, args=(stop,),
                    name="obs-trace-journal", daemon=True).start()
            return path

    def _journal_loop(self, stop):
        """Background flusher: every flush interval, serialize + write
        whatever the hot path appended since the last pass. Keeps the
        emit path to a single list append and — unlike the old
        on-mutation check — flushes the tail even while the tracer is
        idle (a wedged run's last events still reach disk)."""
        while not stop.wait(self._journal_flush_s):
            with self._lock:
                if self._journal is None or self._journal_stop is not stop:
                    return
                self._journal_flush_locked(_time.monotonic())

    def _journal_flush_locked(self, now):
        """Serialize + append everything not yet on disk, then flush
        (lock held). A failed write drops the journal rather than the
        run."""
        try:
            n = len(self._events)
            if self._journal_written < n:
                ser = self._serialized_upto(n)
                self._journal.write("".join(
                    s + "\n"
                    for s in ser[self._journal_written:n]))
                self._journal_written = n
            self._journal.flush()
            self._journal_last = now
        except (OSError, ValueError):
            self._journal = None

    def journaling(self):
        """True while an incremental journal is attached and healthy."""
        return self._journal is not None

    def flush_journal(self):
        """Force the journal's buffered tail to disk (search
        heartbeats call this: a wedged search killed by the watchdog
        must leave its LAST heartbeat readable)."""
        with self._lock:
            if self._journal is None:
                return
            self._journal_flush_locked(_time.monotonic())

    def close_journal_locked(self):
        if self._journal_stop is not None:
            self._journal_stop.set()
            self._journal_stop = None
        f, self._journal = self._journal, None
        if f is not None:
            try:
                n = len(self._events)
                if self._journal_written < n:
                    ser = self._serialized_upto(n)
                    f.write("".join(
                        s + "\n"
                        for s in ser[self._journal_written:n]))
                    self._journal_written = n
                f.close()
            except (OSError, ValueError):  # pragma: no cover
                pass

    def close_journal(self, remove=False):
        """Stop journaling; with ``remove``, delete the journal file
        (the finalize step once the atomic dump exists)."""
        with self._lock:
            self.close_journal_locked()
            path, self._journal_path = self._journal_path, None
        if remove and path:
            try:
                os.remove(path)
            except OSError:
                pass

    def _base(self, name, ph, cat, ts_ns, tid):
        if tid is None:
            tid = threading.get_ident()
        return {"name": name, "ph": ph, "cat": cat,
                "ts": ts_ns / 1e3,            # Chrome trace: microseconds
                "pid": self._pid, "tid": tid}

    def name_thread(self, tid, name):
        """Emit a thread-name metadata event once per tid (Perfetto shows
        these as track labels — e.g. logical worker ids)."""
        with self._lock:
            if tid in self._named_tids:
                return
            self._named_tids.add(tid)
        ev = self._base("thread_name", "M", "__metadata", 0, tid)
        ev["args"] = {"name": str(name)}
        self._emit(ev)

    # -- event kinds ----------------------------------------------------

    def complete(self, name, ts_ns, dur_ns, cat="default", tid=None,
                 args=None):
        """An ``X`` span with an externally measured start/duration (the
        interpreter measures op latency itself; the tracer just
        records). Built as one dict literal — this is the per-op hot
        path and `_base` + mutation costs a measurable fraction of a
        noop op."""
        ev = {"name": name, "ph": "X", "cat": cat, "ts": ts_ns / 1e3,
              "pid": self._pid,
              "tid": threading.get_ident() if tid is None else tid,
              "dur": dur_ns / 1e3 if dur_ns > 0 else 0.0}
        if args:
            ev["args"] = args
        self._emit(ev)

    def instant(self, name, cat="default", tid=None, args=None):
        ev = self._base(name, "i", cat, self.now_ns(), tid)
        ev["s"] = "t"                         # thread-scoped instant
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name, values, cat="default"):
        """A ``C`` event: {series: number} rendered as counter tracks."""
        ev = self._base(name, "C", cat, self.now_ns(), self._pid)
        ev["args"] = {k: float(v) for k, v in values.items()}
        self._emit(ev)

    def async_begin(self, name, wid, cat="default", args=None):
        ev = self._base(name, "b", cat, self.now_ns(),
                        threading.get_ident())
        ev["id"] = str(wid)
        if args:
            ev["args"] = args
        self._emit(ev)

    def async_end(self, name, wid, cat="default", args=None):
        ev = self._base(name, "e", cat, self.now_ns(),
                        threading.get_ident())
        ev["id"] = str(wid)
        if args:
            ev["args"] = args
        self._emit(ev)

    @contextlib.contextmanager
    def span(self, name, cat="lifecycle", tid=None, args=None):
        """A nested span: records an ``X`` event on exit and pushes the
        name onto the contextvar parent stack for the duration, so spans
        opened inside (including in threads spawned from a context
        snapshot taken inside) carry ``args.parent``."""
        stack = _span_stack.get()
        token = _span_stack.set(stack + (name,))
        t0 = self.now_ns()
        try:
            yield
        finally:
            _span_stack.reset(token)
            a = dict(args or {})
            if stack:
                a["parent"] = stack[-1]
            self.complete(name, t0, self.now_ns() - t0, cat=cat,
                          tid=tid, args=a or None)

    # -- persistence ----------------------------------------------------

    def events(self):
        with self._lock:
            return list(self._events)

    def dump(self, path):
        """Write trace.jsonl: a ``[`` line, then one event per line with
        a trailing comma. Loads directly in chrome://tracing / Perfetto
        (the JSON array format's closing bracket is optional) and stays
        line-parseable (strip the trailing comma). A buffer overflow is
        recorded IN the file (a final ``trace_truncated`` instant with
        the dropped count) — a silently truncated trace reads as
        "activity stopped here", which is exactly the wrong conclusion
        during a stall diagnosis."""
        with self._lock:
            n = len(self._events)
            lines = [json.dumps(self.meta_event(),
                                separators=(",", ":"))]
            lines += self._serialized_upto(n)[:n]
        if self.dropped:
            ev = self._base("trace_truncated", "i", "__metadata",
                            self.now_ns(), self._pid)
            ev["s"] = "g"
            ev["args"] = {"dropped_events": self.dropped,
                          "max_events": self._max_events}
            lines.append(json.dumps(ev, separators=(",", ":")))
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("[\n")
            f.write("".join(s + ",\n" for s in lines))
        os.replace(tmp, path)
        return path


def load_trace(path):
    """Parse a trace.jsonl back into a list of event dicts (tolerant of
    the leading ``[`` and trailing commas — i.e. exactly what dump
    writes, and also plain one-object-per-line JSONL). Unparseable
    lines are DROPPED with a warning, not fatal: an incremental
    journal's torn final line (killed mid-append) must not make the
    surviving telemetry unreadable."""
    import logging
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                logging.getLogger(__name__).warning(
                    "dropping unparseable trace line in %s", path)
    return events


def trace_meta(events):
    """The ``trace_meta`` args of a loaded trace (epoch_ns + context),
    or None for traces predating the anchor."""
    for ev in events:
        if ev.get("name") == "trace_meta":
            return ev.get("args") or {}
    return None
