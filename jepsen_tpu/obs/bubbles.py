"""Idle-bubble ledger: classify every non-device-compute gap in a
trace's device lanes into named phases.

The duty-cycle headline (``wgl.device_busy_s`` / makespan) says HOW
MUCH of the wall the device computed for; this module says WHERE the
rest went. It walks the phase spans obs/phases.py emitted
(``cat="phase"``, names ``wgl.phase.<name>``), groups them into
per-thread lanes, and folds:

* **device time** — the ``device`` spans (the ``block_until_ready``
  bracket): the duty-cycle numerator, excluded from idle;
* **attributed idle** — every other phase span (encode, plan, h2d,
  compile, d2h, host, wait): idle wall with a name on it;
* **residual** — gaps between consecutive phase spans inside an
  episode: host wall nobody bracketed. The acceptance target is that
  this stays under 5% of idle (phases are emitted by a contiguous
  cursor, so residual is only the glue between sessions);
* **inter-episode time** — a lane's quiet stretches longer than
  ``EPISODE_GAP_S`` between spans (a worker thread waiting for its
  next check entirely outside the dispatch pipeline). Reported, but
  excluded from the attribution denominator: the ledger explains the
  dispatch pipeline, not the workload's think time.

Artifact discipline matches fleet_analysis.json / metrics_fold.json:
floats rounded, keys sorted, no wall stamps, atomic tmp+rename —
folding the same trace twice yields byte-identical
``bubble_ledger.json`` (the re-fold test pins this).
"""

from __future__ import annotations

import json
import os

from .trace import load_trace
from .merge import MERGED_TRACE_FILE, _load_run_events

__all__ = ["BUBBLE_FILE", "EPISODE_GAP_S", "fold_events", "fold_run",
           "fold_campaign", "write_ledger", "dumps"]

BUBBLE_FILE = "bubble_ledger.json"

#: a gap this long between consecutive phase spans on one lane ends
#: the episode: dispatch-internal gaps are microseconds (the phase
#: cursor is contiguous), while between-check quiet time is unbounded
EPISODE_GAP_S = 1.0

_PREFIX = "wgl.phase."


def _phase_spans(events):
    """(lane, ts_us, dur_us, phase, engine) for every phase span."""
    out = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("cat") != "phase":
            continue
        name = str(ev.get("name", ""))
        if not name.startswith(_PREFIX):
            continue
        try:
            ts = float(ev.get("ts", 0.0))
            dur = max(0.0, float(ev.get("dur", 0.0)))
        except (TypeError, ValueError):
            continue
        engine = str((ev.get("args") or {}).get("engine", "?"))
        out.append(((ev.get("pid", 0), str(ev.get("tid", ""))),
                    ts, dur, name[len(_PREFIX):], engine))
    return out


def fold_events(events, gap_s=EPISODE_GAP_S):
    """Fold a trace's (run or merged-campaign) events into one bubble
    ledger dict. Deterministic for a given event list."""
    spans = sorted(_phase_spans(events),
                   key=lambda s: (s[0], s[1], s[2], s[3]))
    lanes = {}
    for lane, ts, dur, phase, engine in spans:
        lanes.setdefault(lane, []).append((ts, dur, phase, engine))

    device_s = idle_s = attributed_s = residual_s = 0.0
    inter_episode_s = 0.0
    episodes = 0
    phases = {}
    engines = {}
    t_min = t_max = None
    gap_us = gap_s * 1e6

    for lane_spans in lanes.values():
        # split the lane into episodes at quiet stretches > gap_s
        groups = []
        for s in lane_spans:
            if groups and s[0] - groups[-1][-1][0] - groups[-1][-1][1] \
                    <= gap_us:
                groups[-1].append(s)
            else:
                if groups:
                    prev = groups[-1][-1]
                    inter_episode_s += max(
                        0.0, (s[0] - prev[0] - prev[1]) / 1e6)
                groups.append([s])
        for g in groups:
            episodes += 1
            start = g[0][0]
            end = max(ts + dur for ts, dur, _, _ in g)
            t_min = start if t_min is None else min(t_min, start)
            t_max = end if t_max is None else max(t_max, end)
            extent = (end - start) / 1e6
            dev = attr = 0.0
            for ts, dur, phase, engine in g:
                sec = dur / 1e6
                phases[phase] = phases.get(phase, 0.0) + sec
                est = engines.setdefault(
                    engine, {"device_s": 0.0, "phases": {}})
                est["phases"][phase] = \
                    est["phases"].get(phase, 0.0) + sec
                if phase == "device":
                    dev += sec
                    est["device_s"] += sec
                else:
                    attr += sec
            device_s += dev
            idle = max(0.0, extent - dev)
            idle_s += idle
            attributed_s += min(attr, idle)
            residual_s += max(0.0, idle - attr)

    ledger = {
        "lanes": len(lanes),
        "episodes": episodes,
        "episode_gap_s": gap_s,
        "makespan_s": round(((t_max - t_min) / 1e6)
                            if t_min is not None else 0.0, 6),
        "device_s": round(device_s, 6),
        "idle_s": round(idle_s, 6),
        "attributed_s": round(attributed_s, 6),
        "residual_s": round(residual_s, 6),
        "inter_episode_s": round(inter_episode_s, 6),
        "attribution_frac": round(attributed_s / idle_s, 6)
        if idle_s > 0 else 1.0,
        "phases": {p: round(s, 6) for p, s in sorted(phases.items())},
        "engines": {e: {"device_s": round(st["device_s"], 6),
                        "phases": {p: round(s, 6) for p, s in
                                   sorted(st["phases"].items())}}
                    for e, st in sorted(engines.items())},
    }
    return ledger


def fold_run(run_dir, gap_s=EPISODE_GAP_S):
    """Bubble ledger for one run directory (finalized trace.jsonl or
    journal fallback)."""
    return fold_events(_load_run_events(run_dir), gap_s=gap_s)


def fold_campaign(campaign_id, persist=True, gap_s=EPISODE_GAP_S):
    """Fold a campaign's MERGED trace (campaign_trace.jsonl — run
    merge_campaign first) into ``store/campaigns/<id>/
    bubble_ledger.json``. Returns the ledger; with ``persist`` the
    artifact's path rides in ``ledger["path"]`` (excluded from the
    written bytes, like the metrics fold)."""
    from .. import store
    p = store.campaign_path(campaign_id, MERGED_TRACE_FILE)
    events = load_trace(p) if os.path.exists(p) else []
    ledger = fold_events(events, gap_s=gap_s)
    if persist:
        out = store.campaign_path(campaign_id, BUBBLE_FILE)
        write_ledger(ledger, out)
        ledger["path"] = out
    return ledger


def dumps(ledger):
    """The ledger's canonical bytes (sorted keys, no wall stamps) —
    what byte-identical re-folds are measured against."""
    clean = {k: v for k, v in ledger.items() if k != "path"}
    return json.dumps(clean, indent=1, sort_keys=True) + "\n"


def write_ledger(ledger, out_path):
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(dumps(ledger))
    os.replace(tmp, out_path)
    return out_path
