"""Persistent bench trend log + noise-aware regression gate.

Nothing in the repo tracked perf ACROSS runs: a duty-cycle or ops/s
regression could only be noticed by a human rereading BENCH_*.json.
This module gives every bench rung a durable trend record and a
comparator a CI job can gate on:

* ``record()`` appends one JSON line per bench run to
  ``store/bench/trend.jsonl``: the rung metrics (best value + the raw
  repeat samples), and an environment **fingerprint** (jax version,
  platform, device count, hostname, JAX_PLATFORMS). trend.jsonl is a
  log, not a deterministic artifact — wall stamps are fine here.
* ``compare()`` is the gate. It reuses bench rung 11's quiet-floor
  noise methodology: a metric's signal is the BEST of its repeat
  samples (min wall <=> max rate — the quiet floor is what the
  machine can do, everything above it is scheduler noise), and the
  baseline's own spread ``(best - worst) / best`` is the measured
  noise floor. A regression fires only when the current quiet floor
  drops below ``baseline_best * (1 - max(threshold, noise))`` — so
  back-to-back A/A runs pass with zero false regressions while a
  genuine slowdown (the CI job injects one via
  ``JEPSEN_BENCH_INJECT_SLEEP_MS``) lands well outside the floor.
* Comparisons REFUSE to gate across differing fingerprints: a faster
  box is not a perf win and a slower one is not a regression.
  Mismatched baseline records are skipped and counted; planlint PL022
  warns ahead of time when ``trend-baseline`` points at records from
  another environment.

``mini_bench()`` is the self-contained CPU rung the CI ``perf-trend``
job records: a small cas-register key batch through
``keyshard.check_batch_encoded``, warm (so the compile ledger is hot
and XLA compile never pollutes the samples), min-of-N over the
repeats. The sleep knob is honored INSIDE the measured region, so the
injected run is slower in exactly the way a real host-loop regression
would be.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time as _time

__all__ = ["TREND_FILE", "GATE_KEYS", "fingerprint", "trend_path",
           "record", "load", "compare", "mini_bench", "main"]

TREND_FILE = "trend.jsonl"

#: metrics the gate compares (higher is better); everything else in a
#: record is context for humans reading the trend
GATE_KEYS = ("ops_per_s",)

#: default regression allowance when the baseline's measured noise
#: floor is smaller (CPU CI boxes jitter; the injected-slowdown CI
#: case lands far below 1 - this)
DEFAULT_THRESHOLD = 0.2

INJECT_ENV = "JEPSEN_BENCH_INJECT_SLEEP_MS"


def fingerprint():
    """The environment identity a trend record is only comparable
    within. Backend probing is contained: an uninitializable jax
    still fingerprints (platform/devices become None)."""
    fp = {"hostname": socket.gethostname(),
          "jax_platforms": os.environ.get("JAX_PLATFORMS"),
          "jax": None, "platform": None, "device_count": None}
    try:
        import jax
        fp["jax"] = jax.__version__
        devs = jax.devices()
        fp["platform"] = devs[0].platform if devs else None
        fp["device_count"] = len(devs)
    except Exception:
        pass
    return fp


def trend_path():
    from .. import store
    return os.path.join(store.base_dir, "bench", TREND_FILE)


def record(rungs, path=None, fp=None, label=None):
    """Append one trend record ``{"t", "fingerprint", "rungs"}``.
    ``rungs`` is {rung_name: {"metrics": {k: best}, "samples":
    {k: [per-repeat values]}}}."""
    path = path or trend_path()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    rec = {"t": round(_time.time(), 3),
           "fingerprint": fp or fingerprint(), "rungs": rungs}
    if label:
        rec["label"] = str(label)
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def load(path=None):
    """All parseable records in a trend log (missing file -> [])."""
    path = path or trend_path()
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip().rstrip(",")
                if not line or line in ("[", "]"):
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "rungs" in rec:
                    out.append(rec)
    except OSError:
        return []
    return out


def _samples(rec, rung, key):
    r = (rec.get("rungs") or {}).get(rung) or {}
    vals = [v for v in (r.get("samples") or {}).get(key, [])
            if isinstance(v, (int, float))]
    if not vals:
        m = (r.get("metrics") or {}).get(key)
        if isinstance(m, (int, float)):
            vals = [m]
    return vals


def compare(baseline, current, threshold=DEFAULT_THRESHOLD,
            keys=GATE_KEYS):
    """Gate ``current`` (one record) against ``baseline`` (a list of
    records). Returns a verdict dict; ``regressions`` is empty iff the
    gate passes. Baseline records whose fingerprint differs from the
    current record's are REFUSED (skipped + counted), never
    compared."""
    if threshold is None:
        threshold = DEFAULT_THRESHOLD
    threshold = float(threshold)
    cur_fp = current.get("fingerprint")
    usable = [r for r in baseline if r.get("fingerprint") == cur_fp]
    out = {"compared": 0, "regressions": [],
           "baseline_records": len(usable),
           "skipped_mismatched_env": len(baseline) - len(usable),
           "threshold": threshold}
    for rung in sorted((current.get("rungs") or {})):
        for key in keys:
            c_vals = _samples(current, rung, key)
            b_vals = [v for r in usable
                      for v in _samples(r, rung, key)]
            if not c_vals or not b_vals:
                continue
            b_best, c_best = max(b_vals), max(c_vals)
            if b_best <= 0:
                continue
            noise = (b_best - min(b_vals)) / b_best
            allowed = max(threshold, noise)
            out["compared"] += 1
            if c_best < b_best * (1.0 - allowed):
                out["regressions"].append({
                    "rung": rung, "metric": key,
                    "baseline": round(b_best, 4),
                    "current": round(c_best, 4),
                    "drop_frac": round(1.0 - c_best / b_best, 4),
                    "allowed_frac": round(allowed, 4)})
    return out


# ---------------------------------------------------------------------------
# the CI rung

def mini_bench(n_keys=6, n_ops=120, repeats=5, seed=3):
    """One small cas-register key batch, warm, min-of-N: the rung the
    CI perf-trend job records and gates. Returns the ``rungs`` map
    ``record()`` expects, with duty cycle and the phase breakdown
    folded in as context metrics."""
    import random as _r

    from .. import obs
    from ..models import cas_register_spec
    from ..obs.metrics import parse_flat_key
    from ..parallel import keyshard
    from ..simulate import random_history

    sleep_s = 0.0
    try:
        sleep_s = max(0.0, float(os.environ.get(INJECT_ENV) or 0.0)
                      / 1e3)
    except ValueError:
        pass

    pairs = [cas_register_spec.encode(
        random_history(_r.Random(seed + i), "cas-register",
                       n_procs=4, n_ops=n_ops, crash_p=0.0))
        for i in range(n_keys)]
    total_ops = sum(len(e) for e, _ in pairs)

    keyshard.check_batch_encoded(cas_register_spec, pairs,
                                 chunk_iters=64)  # warm: ledger hot
    reg = obs.Registry()
    samples = []
    with obs.bind(None, reg):
        for _ in range(max(1, int(repeats))):
            t0 = _time.monotonic()
            keyshard.check_batch_encoded(cas_register_spec, pairs,
                                         chunk_iters=64)
            if sleep_s:
                _time.sleep(sleep_s)
            samples.append(total_ops / (_time.monotonic() - t0))
    wall_s = sum(total_ops / s for s in samples)
    snap = reg.snapshot()["counters"]
    busy = sum(v for k, v in snap.items()
               if parse_flat_key(k)[0] == "wgl.device_busy_s")
    phase_s = {}
    for k, v in snap.items():
        name, labels = parse_flat_key(k)
        if name == "wgl.phase_s":
            p = labels.get("phase") or "?"
            phase_s[p] = round(phase_s.get(p, 0.0) + float(v), 6)
    metrics = {"ops_per_s": round(max(samples), 2),
               "duty_cycle": round(busy / wall_s, 4) if wall_s else 0.0,
               "ops": total_ops, "keys": n_keys}
    return {"mini-cas-batch": {
        "metrics": metrics,
        "samples": {"ops_per_s": [round(s, 2) for s in samples]},
        "phase_s": dict(sorted(phase_s.items()))}}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m jepsen_tpu.obs.trend",
        description="bench trend log: record a rung, gate the latest "
                    "record against its history")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rec = sub.add_parser("record", help="run mini_bench, append one "
                                        "trend record")
    rec.add_argument("--path", default=None)
    rec.add_argument("--repeats", type=int, default=5)
    rec.add_argument("--label", default=None)
    gate = sub.add_parser("gate", help="compare the newest record "
                                       "against prior ones")
    gate.add_argument("--path", default=None)
    gate.add_argument("--threshold", type=float,
                      default=DEFAULT_THRESHOLD)
    gate.add_argument("--window", type=int, default=8,
                      help="how many prior records form the baseline")
    ns = ap.parse_args(argv)

    if ns.cmd == "record":
        rungs = mini_bench(repeats=ns.repeats)
        rec = record(rungs, path=ns.path, label=ns.label)
        print(json.dumps(rec, sort_keys=True))
        return 0

    records = load(ns.path)
    if len(records) < 2:
        print(json.dumps({"gate": "refused",
                          "reason": "need >= 2 trend records",
                          "records": len(records)}))
        return 0
    current = records[-1]
    baseline = records[:-1][-max(1, ns.window):]
    verdict = compare(baseline, current, threshold=ns.threshold)
    verdict["gate"] = "fail" if verdict["regressions"] else (
        "refused-env" if not verdict["baseline_records"] else "pass")
    print(json.dumps(verdict, sort_keys=True))
    return 1 if verdict["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
