"""Opt-in XLA profiler capture around a run's device searches.

The telemetry plane says WHAT the search did (heartbeats, padding
accounting, duty cycle); the XLA profiler says WHY a dispatch cost
what it did — per-op device timelines, fusion shapes, HBM traffic.
``--profile`` (``test["profile?"]``) wraps the analyze phase — the
run's device searches — in ``jax.profiler`` trace capture persisted
NEXT TO ``trace.jsonl``:

* **Layout.** Captures land in ``<run dir>/profile/`` (or an explicit
  ``test["profile-dir"]``); XLA writes its TensorBoard-shaped tree
  under ``plugins/profile/<ts>/``. A ``profile.json`` marker beside it
  records the capture's status — the web UI links both.
* **Bounded.** ``test["profile-max-s"]`` (default 120 s) arms a timer
  that stops the capture even when the search wedges: an unbounded
  profile of a stuck multi-hour search would fill the disk the run's
  own artifacts need. Best effort: ``jax.profiler.stop_trace`` from
  the timer thread blocks until in-flight device dispatches quiesce
  (measured: it returns the moment the dispatch loop pauses), so the
  bound takes effect at the next dispatch boundary, not mid-kernel —
  and profiling LARGE multi-compile workloads (e.g. a keyed demo's
  hundreds of per-key checks) multiplies their compile wall; profile
  compact runs.
* **Crash-tolerant (journal discipline).** The marker is written
  ``status: "capturing"`` + flushed BEFORE the profiler starts and
  atomically rewritten at stop, so a kill -9 mid-capture leaves a
  readable marker naming the partial capture directory — the same
  append-then-finalize contract the trace/metrics journals follow.
* **Contained.** Every failure path — jax.profiler missing, an
  unwritable directory, a start/stop error, a second concurrent
  capture (the profiler is process-global) — degrades to a marker
  with the reason; the run itself NEVER fails because profiling
  could not (the CI profile smoke pins this).

``JEPSEN_NO_PROFILER=1`` forces `available()` False — how the
containment path is exercised deterministically in CI.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time as _time

logger = logging.getLogger(__name__)

__all__ = ["available", "scope", "profile_dir_for", "MARKER_FILE",
           "PROFILE_DIR", "DEFAULT_MAX_S"]

#: subdirectory of the run dir the capture lands in
PROFILE_DIR = "profile"
#: the crash-tolerant status marker written next to trace.jsonl
MARKER_FILE = "profile.json"
#: capture wall bound: a wedged search must not grow the capture
#: forever (the stop timer fires mid-search and the run continues)
DEFAULT_MAX_S = 120.0

#: the profiler is process-global state; a second concurrent capture
#: (overlapping campaign cells) must refuse, not corrupt the first
_capture_lock = threading.Lock()
_capturing = False


def available():
    """Whether jax.profiler trace capture can run here. Env
    ``JEPSEN_NO_PROFILER=1`` forces False (containment smoke)."""
    if os.environ.get("JEPSEN_NO_PROFILER"):
        return False
    try:
        from jax import profiler as _p
        return callable(getattr(_p, "start_trace", None)) \
            and callable(getattr(_p, "stop_trace", None))
    except Exception:  # noqa: BLE001 - no jax / broken install
        return False


def profile_dir_for(test):
    """Where this test's capture would land: the explicit
    ``profile-dir``, else ``<run dir>/profile`` for named tests, else
    None (nowhere to persist — planlint PL019 flags it ahead of
    time)."""
    d = test.get("profile-dir")
    if d:
        return str(d)
    if test.get("name"):
        from .. import store
        try:
            return store.path(test, PROFILE_DIR)
        except Exception:  # noqa: BLE001 - store layout problems
            return None
    return None


def _write_marker(path, payload):
    """Atomic marker write (tmp + rename), flushed to disk: the
    ``status: capturing`` line must survive a kill -9 an instant
    later."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _capture_files(pdir):
    n = 0
    for _root, _dirs, files in os.walk(pdir):
        n += len(files)
    return n


@contextlib.contextmanager
def scope(test):
    """Capture the XLA profile around the body when
    ``test["profile?"]`` is set; a no-op context otherwise. Never
    raises — profiling is a byproduct, and the verdict must not
    depend on it."""
    global _capturing
    if not isinstance(test, dict) or not test.get("profile?"):
        yield None
        return
    pdir = profile_dir_for(test)
    marker = None
    state = {"status": "unavailable", "dir": pdir}
    if test.get("name"):
        # the marker belongs NEXT TO trace.jsonl whatever directory
        # the capture itself lands in (an explicit profile-dir may
        # point anywhere; web links the run dir's marker)
        try:
            from .. import store
            marker = store.path(test, MARKER_FILE)
        except Exception:  # noqa: BLE001 - store layout problems
            marker = None
    if marker is None and pdir is not None:
        marker = os.path.join(os.path.dirname(pdir) or ".",
                              MARKER_FILE)
    try:
        max_s = float(test.get("profile-max-s") or DEFAULT_MAX_S)
    except (TypeError, ValueError):
        max_s = DEFAULT_MAX_S
    started = False
    timer = None
    stop_lock = threading.Lock()

    def _stop(reason):
        """Stop the capture exactly once (body exit or the bound
        timer, whichever first)."""
        nonlocal started
        global _capturing
        with stop_lock:
            if not started:
                return
            started = False
        try:
            from jax import profiler as _p
            _p.stop_trace()
            state["status"] = "done"
        except Exception as exc:  # noqa: BLE001 - contained
            state["status"] = "failed"
            state["error"] = repr(exc)[:300]
            logger.warning("profiler stop failed", exc_info=True)
        with _capture_lock:
            _capturing = False
        state["stopped_by"] = reason

    try:
        if pdir is None:
            state["error"] = ("no profile directory: name the test or "
                              "pass profile-dir")
        elif not available():
            state["error"] = "jax.profiler unavailable"
        else:
            with _capture_lock:
                if _capturing:
                    state["status"] = "skipped"
                    state["error"] = ("another capture is already "
                                      "running (the profiler is "
                                      "process-global)")
                else:
                    _capturing = True
                    started = True
            if started:
                os.makedirs(pdir, exist_ok=True)
                if marker:
                    _write_marker(marker, {"status": "capturing",
                                           "dir": pdir,
                                           "max_s": max_s,
                                           "started":
                                               _time.strftime(
                                                   "%Y%m%dT%H%M%S")})
                from jax import profiler as _p
                try:
                    _p.start_trace(pdir)
                except Exception as exc:  # noqa: BLE001 - contained
                    with _capture_lock:
                        _capturing = False
                    started = False
                    state["status"] = "failed"
                    state["error"] = repr(exc)[:300]
                    logger.warning("profiler start failed",
                                   exc_info=True)
                if started:
                    state["status"] = "capturing"
                    timer = threading.Timer(
                        max_s, _stop, args=("max-s-bound",))
                    timer.daemon = True
                    timer.start()
    except Exception as exc:  # noqa: BLE001 - setup must not kill runs
        # a failure between claiming the capture slot and start_trace
        # (makedirs, the marker write) must release the claim AND
        # clear started, or the finally's _stop would call stop_trace
        # on a never-started trace and overwrite this (root-cause)
        # error with the bogus stop error. status == "capturing"
        # means start_trace already succeeded (a timer failure landed
        # here): keep started so the finally stops the live trace.
        if started and state.get("status") != "capturing":
            with _capture_lock:
                _capturing = False
            started = False
        state["status"] = "failed"
        state["error"] = repr(exc)[:300]
        logger.warning("profiler setup failed", exc_info=True)
    t0 = _time.monotonic()
    try:
        yield pdir if started else None
    finally:
        if timer is not None:
            timer.cancel()
        _stop("scope-exit")
        state["wall_s"] = round(_time.monotonic() - t0, 3)
        if state.get("status") == "done" and pdir is not None:
            try:
                state["files"] = _capture_files(pdir)
            except OSError:
                pass
        if marker:
            try:
                _write_marker(marker, state)
            except Exception:  # noqa: BLE001 - marker is best effort
                logger.warning("couldn't write the profile marker",
                               exc_info=True)
        if state.get("status") != "done":
            logger.warning("XLA profile capture: %s (%s)",
                           state.get("status"), state.get("error"))
