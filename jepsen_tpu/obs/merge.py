"""Campaign trace merge: fold N per-run traces into ONE Perfetto
timeline with a process lane per worker and normalized clocks.

A fleet campaign's story is scattered across the coordinator's own
``store/campaigns/<id>/trace.jsonl`` (dispatch, leases, syncs) and one
``trace.jsonl`` per cell run, each timestamped against its OWN
process's monotonic clock — and, for remote workers, its own wall
clock. This module reassembles them:

* **Anchoring.** Every tracer stamps a ``trace_meta`` event with the
  wall epoch (``epoch_ns``) its ts=0 corresponds to, so a run's
  relative microseconds map onto that host's wall clock.
* **Skew normalization.** Worker wall clocks are NOT trusted. The
  lease handshake records four stamps — the coordinator's send time,
  the worker's spec-receipt time, the worker's result-print time, the
  coordinator's result-receipt time (``rec["clock"]``, journaled on
  the outcome record). The two legs bound the offset:

      worker_done - coord_recv  <=  offset  <=  worker_recv - coord_sent

  but they are wildly ASYMMETRIC here: the forward leg contains the
  worker interpreter's boot and the box's scheduling delay (seconds
  under load — measured +6 s on a busy 2-core host), while the return
  leg is print -> process-exit -> parse (tens of ms). The classic
  symmetric midpoint would split that boot time into a fake seconds-
  scale offset for a LOOPBACK worker, so the estimate uses the tight
  return leg alone: ``offset = worker_done - coord_recv``, biased by
  only the return latency. The per-worker offset is the median over
  that worker's cells, and every event of that worker's runs is
  shifted by it onto the coordinator's clock. This is what makes
  reported detection latencies honest across hosts (the monitoring
  papers' metric — arxiv 2509.17795, 2410.04581 — is meaningless
  under uncorrected skew).
* **Lanes.** The merged trace remaps ``pid``: lane 1 is the
  coordinator, lanes 2.. are workers (sorted by id), each named via a
  ``process_name`` metadata event — Perfetto renders one process
  track per worker with the original thread tracks nested inside.
* **Determinism.** Events are sorted by (ts, lane, tid, ph, name) and
  serialized with sorted keys: the same inputs produce a byte-identical
  ``campaign_trace.jsonl`` (the merge-twice test pins this), so the
  artifact is diffable across resumes.

Runs whose ``trace.jsonl`` never finalized fall back to the
incremental ``trace.jsonl.journal`` (torn tail dropped) — a kill -9'd
worker still contributes everything up to the kill. Runs whose
artifacts were never mirrored home (``synced: false``) are skipped and
counted in the summary, not fatal: planlint PL017 warns ahead of time
when a merge is requested with artifact sync off.
"""

from __future__ import annotations

import json
import logging
import os
import statistics

from .trace import load_trace, trace_meta

logger = logging.getLogger(__name__)

__all__ = ["MERGED_TRACE_FILE", "FOLDED_METRICS_FILE",
           "worker_offsets", "clock_offset", "merge_campaign",
           "fold_campaign_metrics", "introspection_summary"]

MERGED_TRACE_FILE = "campaign_trace.jsonl"
FOLDED_METRICS_FILE = "metrics_fold.json"


def clock_offset(clock):
    """The worker-minus-coordinator wall offset (seconds) from one
    lease handshake, or None when the needed stamps are missing.

    Return-leg estimate: the worker's result stamp measures
    ``offset - d2`` against the coordinator's receipt stamp, where d2
    is the result's print -> exit -> parse latency (tens of ms). The
    forward leg is deliberately NOT averaged in — it contains the
    worker interpreter's boot and scheduling delay (seconds under
    load), and the symmetric midpoint would hand a loopback worker a
    fake seconds-scale offset (see the module docstring)."""
    if not isinstance(clock, dict):
        return None
    try:
        wd = float(clock["worker-result-epoch"])
        cr = float(clock["coord-received-epoch"])
    except (KeyError, TypeError, ValueError):
        return None
    return wd - cr


def worker_offsets(records):
    """{worker_id: offset_s} — the median handshake offset per worker
    over its cell records. Workers with no usable handshake get 0.0
    (loopback workers share the coordinator's clock anyway)."""
    samples = {}
    for rec in records:
        off = clock_offset(rec.get("clock"))
        if off is None:
            continue
        samples.setdefault(str(rec.get("worker")), []).append(off)
    return {w: statistics.median(s) for w, s in samples.items()}


def _load_run_events(run_dir):
    """A run dir's trace events (finalized file or journal fallback);
    [] when neither exists."""
    for name in ("trace.jsonl", "trace.jsonl.journal"):
        p = os.path.join(str(run_dir), name)
        if os.path.exists(p):
            try:
                return load_trace(p)
            except OSError:
                return []
    return []


def _lane_meta(lane, name):
    return {"name": "process_name", "ph": "M", "cat": "__metadata",
            "ts": 0.0, "pid": lane, "tid": 0,
            "args": {"name": str(name)}}


def _shift(events, lane, shift_us):
    """Re-lane and re-clock one trace's events; trace_meta is dropped
    (its anchor is consumed here) and thread-name metadata keeps
    ts=0."""
    out = []
    for ev in events:
        if ev.get("name") == "trace_meta":
            continue
        ev = dict(ev)
        ev["pid"] = lane
        if ev.get("ph") != "M":
            try:
                ev["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 3)
            except (TypeError, ValueError):
                ev["ts"] = 0.0
        out.append(ev)
    return out


def _sort_key(ev):
    return (float(ev.get("ts", 0.0)) if ev.get("ph") != "M" else -1.0,
            int(ev.get("pid", 0)), str(ev.get("tid", "")),
            str(ev.get("ph", "")), str(ev.get("name", "")))


def merge_campaign(campaign_id, out_path=None):
    """Merge one campaign's traces into
    ``store/campaigns/<id>/campaign_trace.jsonl``. Returns a summary
    dict: event count, per-worker lane/offset/cell counts, runs
    skipped for missing artifacts. Raises FileNotFoundError for an
    unknown campaign; everything per-run is contained."""
    from .. import store

    meta = None
    try:
        with open(store.campaign_path(campaign_id,
                                      "campaign.json")) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        raise FileNotFoundError(
            f"campaign {campaign_id!r} has no campaign.json") from None
    records = store.latest_campaign_records(campaign_id)
    offsets = worker_offsets(records)

    # -- coordinator lane ----------------------------------------------
    coord_events = _load_run_events(store.campaign_path(campaign_id))
    coord_meta = trace_meta(coord_events) or {}
    coord_epoch_ns = coord_meta.get("epoch_ns")
    merged = [_lane_meta(1, "coordinator")]
    merged += _shift(coord_events, 1, 0.0)

    # -- one lane per worker -------------------------------------------
    workers = sorted({str(r.get("worker") or "local") for r in records})
    lanes = {w: i + 2 for i, w in enumerate(workers)}
    for w in workers:
        merged.append(_lane_meta(lanes[w], f"worker {w}"))

    skipped = 0
    cells_merged = {w: 0 for w in workers}
    for rec in sorted(records, key=lambda r: str(r.get("cell"))):
        run_dir = rec.get("path")
        if not run_dir or not os.path.isdir(str(run_dir)):
            skipped += 1
            continue
        events = _load_run_events(run_dir)
        if not events:
            skipped += 1
            continue
        w = str(rec.get("worker") or "local")
        run_meta = trace_meta(events) or {}
        run_epoch_ns = run_meta.get("epoch_ns")
        off_s = offsets.get(w, 0.0)
        if run_epoch_ns is None or coord_epoch_ns is None:
            # no anchor (pre-plane trace): place at the coordinator's
            # origin, un-normalized but visible
            shift_us = 0.0
        else:
            shift_us = (run_epoch_ns - off_s * 1e9
                        - coord_epoch_ns) / 1e3
        merged += _shift(events, lanes[w], shift_us)
        cells_merged[w] += 1

    merged.sort(key=_sort_key)
    out_path = out_path or store.campaign_path(campaign_id,
                                               MERGED_TRACE_FILE)
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        f.write("[\n")
        for ev in merged:
            f.write(json.dumps(ev, sort_keys=True) + ",\n")
    os.replace(tmp, out_path)
    return {"path": out_path, "events": len(merged),
            "cells": len(records) - skipped, "skipped": skipped,
            "workers": {w: {"lane": lanes[w],
                            "cells": cells_merged[w],
                            "offset_s": round(offsets.get(w, 0.0), 6)}
                        for w in workers},
            "status": (meta or {}).get("status")}


# ---------------------------------------------------------------------------
# campaign metrics fold: per-run metrics.json -> one campaign snapshot

def _fold_histogram(acc, h):
    """Merge one histogram dict into the accumulator (same on-disk
    shape as obs.metrics.Histogram.to_dict). Different bucket bounds
    (a knob changed between cells) keep the first cell's bounds and
    fold sum/count only — counts from mismatched bounds would lie."""
    if acc is None:
        return {k: (list(v) if isinstance(v, list) else v)
                for k, v in h.items()}
    if list(acc.get("buckets_le") or []) == list(h.get("buckets_le")
                                                 or []):
        acc["counts"] = [a + b for a, b in
                         zip(acc.get("counts") or [],
                             h.get("counts") or [])]
    acc["sum"] = (acc.get("sum") or 0.0) + (h.get("sum") or 0.0)
    acc["count"] = (acc.get("count") or 0) + (h.get("count") or 0)
    for k, pick in (("min", min), ("max", max)):
        vals = [v for v in (acc.get(k), h.get(k)) if v is not None]
        acc[k] = pick(vals) if vals else None
    return acc


def fold_campaign_metrics(campaign_id, persist=True):
    """Fold the coordinator's and every cell run's metrics snapshots
    into ONE campaign-level view: counters sum, numeric gauges keep
    their max (they are occupancy/high-water series), histograms
    merge. Snapshots come through ``store.load_run_metrics`` (journal
    fallback included, so kill -9'd cells still contribute). With
    ``persist`` the fold lands as deterministic sorted-key
    ``store/campaigns/<id>/metrics_fold.json``.

    This is what turns the per-cell padding/duty-cycle accounting
    (``wgl.cells_real``/``wgl.cells_padded`` per n-bucket,
    ``wgl.device_busy_s``) into the campaign's waste table — each
    cell's series carry their {campaign, cell, worker} default
    labels, so the summed fold stays attributable AND aggregable.

    The coordinator's own snapshot is folded WITHOUT its
    cell-labelled series: those are the dispatcher's live per-cell
    re-folds (``_fold_worker_metrics``) of the very run metrics this
    fold reads directly — summing both would double every re-folded
    counter."""
    from .metrics import parse_flat_key
    from .. import store

    counters, gauges, hists = {}, {}, {}
    records = store.latest_campaign_records(campaign_id)
    dirs = [(store.campaign_path(campaign_id), True)]
    seen = set()
    for rec in records:
        p = rec.get("path")
        if p and os.path.isdir(str(p)) and str(p) not in seen:
            seen.add(str(p))
            dirs.append((str(p), False))
    runs_folded = 0
    for d, coordinator in dirs:
        m = store.load_run_metrics(d)
        if not isinstance(m, dict):
            continue
        runs_folded += 1

        def relevant(k):
            return not (coordinator
                        and "cell" in parse_flat_key(k)[1])

        for k, v in (m.get("counters") or {}).items():
            if not relevant(k):
                continue
            try:
                counters[k] = counters.get(k, 0) + v
            except TypeError:
                continue
        for k, v in (m.get("gauges") or {}).items():
            if not relevant(k):
                continue
            try:
                gauges[k] = v if k not in gauges \
                    else max(gauges[k], v)
            except TypeError:
                gauges.setdefault(k, v)
        for k, h in (m.get("histograms") or {}).items():
            if isinstance(h, dict) and relevant(k):
                hists[k] = _fold_histogram(hists.get(k), h)
    fold = {"campaign": str(campaign_id), "runs_folded": runs_folded,
            "counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": dict(sorted(hists.items()))}
    if persist:
        out = store.campaign_path(campaign_id, FOLDED_METRICS_FILE)
        d = os.path.dirname(out)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(fold, f, indent=1, sort_keys=True, default=str)
            f.write("\n")
        os.replace(tmp, out)
        fold["path"] = out
    return fold


def introspection_summary(fold, makespan_s=None):
    """The device-introspection headline from a metrics fold (or any
    snapshot dict): the per-bucket padding-waste table, the device
    duty cycle, and the phase breakdown.

    * ``padding``: {bucket: {real, padded, waste_frac}} summed over
      engines — how many padded batch rows per power-of-two n-bucket
      were real ops vs inert lanes.
    * ``device_busy_s``: summed per engine (device-COMPUTE wall — the
      obs.phases bracket — when phase attribution ran, else the full
      chunk wall); ``duty_cycle`` = total busy wall / ``makespan_s``
      when the caller knows the campaign makespan (the trace summary
      does).
    * ``chunk_s``: per-engine SUM of the host-side dispatch-chunk
      wall (the ``wgl.chunk_s`` histogram — the pre-phase meaning of
      "busy"); busy <= chunk always, and the gap is the per-dispatch
      transfer/harvest overhead.
    * ``phase_s``: {engine: {phase: s}} from the ``wgl.phase_s``
      counters — where the non-device wall went."""
    from .metrics import parse_flat_key
    counters = (fold or {}).get("counters") or {}
    buckets = {}
    busy = {}
    phases = {}
    for k, v in counters.items():
        name, labels = parse_flat_key(k)
        if name in ("wgl.cells_real", "wgl.cells_padded"):
            b = labels.get("bucket") or "?"
            st = buckets.setdefault(b, {"real": 0, "padded": 0})
            st["real" if name.endswith("real") else "padded"] += int(v)
        elif name == "wgl.device_busy_s":
            eng = labels.get("engine") or "?"
            busy[eng] = busy.get(eng, 0.0) + float(v)
        elif name == "wgl.phase_s":
            eng = labels.get("engine") or "?"
            p = labels.get("phase") or "?"
            ep = phases.setdefault(eng, {})
            ep[p] = ep.get(p, 0.0) + float(v)
    chunk = {}
    for k, h in ((fold or {}).get("histograms") or {}).items():
        name, labels = parse_flat_key(k)
        if name == "wgl.chunk_s" and isinstance(h, dict):
            eng = labels.get("engine") or "?"
            chunk[eng] = chunk.get(eng, 0.0) + float(h.get("sum")
                                                     or 0.0)
    for st in buckets.values():
        total = st["real"] + st["padded"]
        st["waste_frac"] = round(st["padded"] / total, 4) if total \
            else 0.0
    out = {"padding": {b: buckets[b] for b in
                       sorted(buckets, key=lambda x:
                              int(x) if str(x).isdigit() else 0)},
           "device_busy_s": {e: round(s, 3)
                             for e, s in sorted(busy.items())},
           "device_busy_total_s": round(sum(busy.values()), 3)}
    if chunk:
        out["chunk_s"] = {e: round(s, 3)
                          for e, s in sorted(chunk.items())}
    if phases:
        out["phase_s"] = {e: {p: round(s, 3)
                              for p, s in sorted(ep.items())}
                          for e, ep in sorted(phases.items())}
    if makespan_s and makespan_s > 0:
        out["duty_cycle"] = round(sum(busy.values()) / makespan_s, 4)
    return out
