"""Search telemetry for the device WGL engines.

A long linearizability search used to be a silent multi-minute `jit`
black box: the host loop dispatched bounded chunks and nothing was
observable until the verdict (or a watchdog kill). These helpers give
the three host loops — the single-key search (checker/jax_wgl.py), the
multi-key batch (parallel/keyshard.py), and the mesh-sharded single
search (parallel/searchshard.py) — one cheap call per dispatch:

* `plan()` records, once per search, the padded batch's composition —
  real vs padding rows per power-of-two n-bucket — so per-bucket
  padding waste is a measured series, not a guess;
* `heartbeat()` emits an instant trace event + counter tracks
  (frontier depth, states explored, deepest linearized op, keys still
  running, shard balance), updates gauges, and accumulates the
  device-busy wall (`wgl.device_busy_s` — the duty-cycle numerator:
  the device-compute phase bracket when obs.phases measured one for
  the dispatch, else the full chunk wall, whose per-dispatch
  distribution `wgl.chunk_s` keeps either way),
  so a stalled search is diagnosable mid-flight from trace.jsonl and
  a live scrape of ``GET /api/metrics`` shows monotonically-increasing
  explored/frontier series mid-search;
* `summary()` records the final verdict's telemetry (states explored,
  chunk count, iteration count, dedup-table load / insert failures,
  per-shard work split) into the metrics registry.

Engines call `capture()` ONCE at search entry and use the returned
session for every emission. The session pins the sinks resolved
through ``obs.current_sinks()`` when the search STARTED: the
RUN-SCOPED pair when inside a run scope (two concurrent campaign
cells' searches each write their own {campaign, cell}-labelled
series instead of folding into whichever cell bound last), else the
process globals. The checker competition abandons losing engine
threads after a 0.5 s join (they may still be mid device-compile),
and a straggler reading the process-global sinks per call would
write phantom heartbeats into the NEXT run's artifacts. With
captured sinks a straggler keeps streaming into its own (already
discarded) buffers — harmless.

Everything no-ops while obs is unbound, so the engines pay one global
read per search plus cheap None checks per dispatched chunk when
tracing is off — the loops' own device syncs dominate by orders of
magnitude.
"""

from __future__ import annotations

import time as _time

from . import current_sinks, run_config

__all__ = ["capture", "enabled", "SearchObs",
           "HEARTBEAT_MIN_INTERVAL_S"]

#: wall-time buckets for per-chunk dispatch latency: chunks target
#: ~1-3 s; the tail buckets catch TPU-tunnel stalls (observed: single
#: dispatches of 100+ s)
CHUNK_BUCKETS_S = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0, 600.0)

#: the fastest cadence heartbeats can fire at: one per host→device
#: dispatch, and the batch loop targets ~1 s dispatches. A
#: ``progress-interval-s`` below this cannot make progress telemetry
#: any fresher (planlint PL019 warns on it).
HEARTBEAT_MIN_INTERVAL_S = 1.0


def enabled():
    """Whether obs sinks are currently resolvable (for gating extra
    host work like device reads before a `capture()`d session
    exists)."""
    tr, reg = current_sinks()
    return tr is not None or reg is not None


def capture():
    """Snapshot this context's sinks (run-scoped when inside a run,
    else the globals) into a search session, along with the run's
    progress-telemetry cadence config."""
    tr, reg = current_sinks()
    cfg = run_config()
    return SearchObs(tr, reg,
                     min_interval_s=cfg.get("progress-interval-s"))


class SearchObs:
    """One search's telemetry channel, pinned to the sinks resolved at
    search start (see module docstring for why not per-call globals).

    ``min_interval_s`` throttles the per-dispatch TRACE emission +
    journal flush (the disk-touching parts) to at most one per
    interval; registry counters/gauges always update, so the busy-wall
    and explored accounting stay exact whatever the cadence."""

    def __init__(self, tr, reg, min_interval_s=None):
        self._tr = tr
        self._reg = reg
        try:
            self._min_interval = max(0.0, float(min_interval_s or 0.0))
        except (TypeError, ValueError):
            self._min_interval = 0.0
        # None until the first emission: 0.0 would throttle the
        # FIRST heartbeat on a freshly-booted host (monotonic()
        # counts from boot, so now - 0.0 can sit under a long
        # interval for the machine's first hours)
        self._last_emit = None

    def enabled(self):
        return self._tr is not None or self._reg is not None

    def plan(self, engine, n_bucket, rows_real, rows_total, keys=None,
             lanes=None, owners=None):
        """Record one search's padded-batch composition, once at
        entry: ``rows_real`` real op rows landed in a padded batch of
        ``rows_total`` rows (``lanes`` x ``n_bucket`` for the key
        batch). The per-bucket real/padded counters are what the
        campaign fold renders as the padding-waste table. ``owners``
        is the distinct-tenant count of a cross-tenant service batch
        (keyshard passes it through; absent everywhere else)."""
        tr, reg = self._tr, self._reg
        if tr is None and reg is None:
            return
        rows_real = int(rows_real)
        rows_total = int(rows_total)
        padded = max(0, rows_total - rows_real)
        if reg is not None:
            b = str(int(n_bucket))
            reg.inc("wgl.cells_real", rows_real, engine=engine,
                    bucket=b)
            reg.inc("wgl.cells_padded", padded, engine=engine,
                    bucket=b)
        if tr is not None:
            fields = {"bucket": int(n_bucket), "rows_real": rows_real,
                      "rows_padded": padded,
                      "waste_frac": round(padded / rows_total, 4)
                      if rows_total else 0.0}
            if keys is not None:
                fields["keys"] = int(keys)
            if lanes is not None:
                fields["lanes"] = int(lanes)
            if owners is not None:
                fields["owners"] = int(owners)
            tr.instant(f"wgl.plan.{engine}", cat="search", args=fields)

    def heartbeat(self, engine, iteration, chunk_s, device_s=None,
                  frontier=None, explored=None, depth=None,
                  keys_alive=None, keys_running=None, compactions=None,
                  shard_tops=None, **extra):
        """One call per host→device dispatch. ``frontier`` is the DFS
        stack depth (scalar, or summed over keys), ``explored`` the
        cumulative states-explored counter, ``depth`` the deepest
        linearized-ok-op count reached so far (the "wedged at op K
        with frontier F" watchdog signal — progress toward n_ok),
        ``shard_tops`` the per-shard frontier sizes (the steal-ring
        balance signal). ``device_s`` is the device-compute bracket
        (the phase plane's ``block_until_ready`` measurement): when
        given, it — not the full chunk wall — feeds the duty-cycle
        numerator."""
        tr, reg = self._tr, self._reg
        if tr is None and reg is None:
            return
        if reg is not None:
            reg.inc("wgl.chunks", engine=engine)
            reg.observe("wgl.chunk_s", chunk_s,
                        buckets=CHUNK_BUCKETS_S, engine=engine)
            # duty-cycle numerator: the DEVICE-COMPUTE wall when the
            # engine measured one (obs.phases bracket), else the full
            # chunk wall (phase attribution off: the dispatch's sync
            # rides the progress device_get, so chunk_s is the only
            # device-occupancy bound available — the pre-phase
            # behavior). Either way busy <= the wgl.chunk_s sum.
            reg.inc("wgl.device_busy_s",
                    float(device_s if device_s is not None
                          else chunk_s), engine=engine)
        fields = {"iteration": iteration, "chunk_s": round(chunk_s, 4)}
        if device_s is not None:
            fields["device_s"] = round(float(device_s), 4)
        track = {}
        if frontier is not None:
            fields["frontier"] = track["frontier"] = int(frontier)
            if reg is not None:
                reg.set_gauge("wgl.frontier_depth", int(frontier),
                              engine=engine)
                reg.max_gauge("wgl.frontier_depth_max", int(frontier),
                              engine=engine)
        if explored is not None:
            fields["explored"] = track["explored"] = int(explored)
            if reg is not None:
                reg.set_gauge("wgl.states_explored", int(explored),
                              engine=engine)
        if depth is not None:
            fields["depth"] = track["depth"] = int(depth)
            if reg is not None:
                # the deepest linearized-ok count is monotone per
                # search; max_gauge keeps it monotone across the
                # compaction rebuilds of the batch path too
                reg.max_gauge("wgl.search_depth", int(depth),
                              engine=engine)
        if keys_alive is not None:
            fields["keys_alive"] = int(keys_alive)
        if keys_running is not None:
            fields["keys_running"] = track["keys_running"] = \
                int(keys_running)
            if reg is not None:
                reg.set_gauge("wgl.keys_running", int(keys_running),
                              engine=engine)
        if compactions is not None:
            fields["compactions"] = int(compactions)
        if shard_tops is not None:
            tops = [int(t) for t in shard_tops]
            fields["shard_tops"] = tops
            busy = sum(1 for t in tops if t > 0)
            fields["shards_with_work"] = track["shards_with_work"] = busy
            if reg is not None:
                reg.set_gauge("wgl.shards_with_work", busy,
                              engine=engine)
        fields.update(extra)
        # trace emission + journal flush throttle: registry state
        # above is already current, so skipping the disk-touching
        # tail only coarsens the TRACE's sampling of it
        now = _time.monotonic()
        if self._min_interval and self._last_emit is not None \
                and now - self._last_emit < self._min_interval:
            return
        self._last_emit = now
        if tr is not None:
            tr.instant(f"wgl.heartbeat.{engine}", cat="search",
                       args=fields)
            if track:
                tr.counter(f"wgl.{engine}", track, cat="search")
        # push the journals' buffered tail to disk NOW: heartbeats
        # used to be snapshot-at-end only, so a wedged search the
        # watchdog killed left no trace of how far it got. With the
        # incremental journals attached (store.open_obs_journals) the
        # last heartbeat before the kill is always readable. One
        # flush per host->device dispatch (~seconds apart): noise
        # next to the device sync it rides behind.
        if tr is not None:
            tr.flush_journal()
        if reg is not None:
            reg.journal_now()

    def summary(self, engine, result, keys=None, shard_explored=None):
        """Record a finished search's telemetry from its result dict."""
        tr, reg = self._tr, self._reg
        if tr is None and reg is None:
            return
        verdict = result.get("valid")
        if reg is not None:
            reg.inc("wgl.searches", engine=engine)
            reg.inc("wgl.verdicts", engine=engine, valid=str(verdict))
            if result.get("configs_explored") is not None:
                reg.inc("wgl.states_explored_total",
                        int(result["configs_explored"]), engine=engine)
            if result.get("iterations") is not None:
                reg.inc("wgl.iterations_total",
                        int(result["iterations"]), engine=engine)
            if result.get("table_load") is not None:
                reg.set_gauge("wgl.table_load", result["table_load"],
                              engine=engine)
            if result.get("table_insert_failures") is not None:
                reg.inc("wgl.table_insert_failures",
                        int(result["table_insert_failures"]),
                        engine=engine)
        if tr is not None:
            fields = {k: result.get(k) for k in
                      ("valid", "configs_explored", "iterations",
                       "engine", "table_load", "table_insert_failures",
                       "error")
                      if result.get(k) is not None}
            if keys is not None:
                fields["keys"] = int(keys)
            if shard_explored is not None:
                fields["shard_explored"] = [int(x)
                                            for x in shard_explored]
                # work-split imbalance: max shard share of the total
                total = sum(fields["shard_explored"]) or 1
                fields["shard_max_share"] = round(
                    max(fields["shard_explored"]) / total, 4)
            fields["valid"] = str(verdict)
            tr.instant(f"wgl.done.{engine}", cat="search", args=fields)
