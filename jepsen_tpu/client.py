"""Client protocol: applies operations to a database (reference
jepsen/src/jepsen/client.clj).

A client is opened per (node, process): ``open`` makes a connection without
touching logical state, ``setup``/``teardown`` manage database state,
``invoke`` applies one op and returns its completion. Crashed clients are
closed and reopened for the successor process unless ``reusable`` says
otherwise (client.clj:29-44)."""

from __future__ import annotations


class Client:
    """Lifecycle: open -> setup -> invoke* -> teardown -> close
    (client.clj:9-27)."""

    def open(self, test, node):
        """Connect to node; returns a ready client. Must not affect logical
        state."""
        return self

    def close(self, test):
        """Release the connection. Must not affect logical state."""

    def setup(self, test):
        """Set up database state for testing."""

    def invoke(self, test, op):
        """Apply op; return the completed op (type ok/fail/info)."""
        raise NotImplementedError

    def teardown(self, test):
        """Tear down database state."""

    def reusable(self, test):
        """May a crashed client be reused by the successor process?
        (client.clj Reusable, :29-44)"""
        return False


class _Noop(Client):
    """Does nothing (client.clj:46-53)."""

    def invoke(self, test, op):
        out = dict(op)
        out["type"] = "ok"
        return out


noop = _Noop()


class InvalidCompletion(Exception):
    pass


class Validate(Client):
    """Asserts completions are well-formed: a dict with type ok/info/fail
    and unchanged process/f (client.clj:64-109)."""

    def __init__(self, client):
        self.client = client

    def open(self, test, node):
        res = self.client.open(test, node)
        if not isinstance(res, Client):
            raise InvalidCompletion(
                f"expected open to return a Client, got {res!r}")
        return Validate(res)

    def close(self, test):
        self.client.close(test)

    def setup(self, test):
        self.client.setup(test)
        return self

    def invoke(self, test, op):
        out = self.client.invoke(test, op)
        problems = []
        if not isinstance(out, dict):
            problems.append("should be a dict")
        else:
            if out.get("type") not in ("ok", "info", "fail"):
                problems.append("type should be ok, info, or fail")
            if out.get("process") != op.get("process"):
                problems.append("process should be the same")
            if out.get("f") != op.get("f"):
                problems.append("f should be the same")
        if problems:
            raise InvalidCompletion(
                f"invalid completion {out!r} for {op!r}: "
                + "; ".join(problems))
        return out

    def teardown(self, test):
        self.client.teardown(test)

    def reusable(self, test):
        return self.client.reusable(test)


def validate(client):
    return Validate(client)


class FnClient(Client):
    """Build a client from a single invoke function (handy in tests)."""

    def __init__(self, fn):
        self.fn = fn

    def invoke(self, test, op):
        return self.fn(test, op)
