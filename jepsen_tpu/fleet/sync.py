"""Artifact sync: mirror a remote worker's run directory into the
coordinator's store over the existing control/remotes scp plane.

A fleet worker writes its run artifacts (history.jsonl, results.json,
trace.jsonl, metrics.json, monitor.json, analysis.json, jepsen.log)
into its OWN store directory; the journal record it returns names a
host-local path the coordinator's web UI can't serve. This module is
the download half the ROADMAP called for, built crash-consistent:

* **Manifest first.** Before any byte moves, the worker is asked for
  a file manifest (``find -type f -printf '%P\\t%s\\n'``): relative
  path + size for every artifact. After the download, every manifest
  entry must exist locally with a matching size -- a torn copy (a
  killed scp, a chaos-injected partial download) is *detected*, not
  trusted, and the attempt retries.
* **Atomic visibility.** Downloads land in ``store/.sync-tmp/`` (a
  reserved directory the store browser skips) and are renamed into
  place only after verification: the coordinator store NEVER shows a
  partial run directory, no matter what kills what mid-transfer.
* **Bounded retries.** One `robust.RetryPolicy` drives the attempts,
  with the whole pull bounded by ``timeout_s`` -- a wedged transport
  costs a sync failure, never a wedged coordinator.
* **Download on demand.** Runs whose sync failed terminally register
  here; ``web.py`` calls `fetch_on_demand` when a browsed path isn't
  on local disk yet, so a run link resolves the moment the worker
  host is reachable again.

The dispatcher journals every outcome as an ``artifact-sync`` event
record, which is what lets ``--resume`` re-sync a terminal cell's
artifacts without re-running the cell.
"""

from __future__ import annotations

import logging
import os
import shlex
import shutil
import threading
import time

from .. import store
from ..robust import RetryPolicy

logger = logging.getLogger(__name__)

__all__ = ["SyncError", "DEFAULT_SYNC_TIMEOUT_S", "manifest",
           "pull_run", "resolve_remote", "register_pending",
           "pending", "fetch_on_demand", "clear_pending"]

#: default wall bound for one whole run-directory pull (manifest +
#: download + verify, retries included). Keep it under the fleet
#: lease TTL: the lease is extended by exactly this much while the
#: coordinator syncs (planlint PL016 warns otherwise).
DEFAULT_SYNC_TIMEOUT_S = 120.0


class SyncError(RuntimeError):
    """One sync attempt failed (transport error, manifest mismatch,
    rename race). Retried under the policy; terminal after that.
    ``attempts`` (set by pull_run on the terminal raise) records how
    many attempts were burned, so the journal's ``artifact-sync``
    failure event can account for every injected fault it absorbed."""

    attempts = 0


def resolve_remote(kind):
    """The Remote class for a worker kind, or None for an unknown
    one. THE one worker-kind dispatch table: the fleet dispatcher,
    the on-demand fetch, and Worker.connect all resolve through it,
    so adding a kind (docker, k8s, ...) is one edit."""
    from ..control import remotes
    return {"local": remotes.LocalRemote,
            "ssh": remotes.SSHRemote}.get(str(kind))


def manifest(conn, remote_dir, timeout_s=DEFAULT_SYNC_TIMEOUT_S):
    """``{relative_path: size}`` for every file under ``remote_dir``
    on the worker, via the control plane (GNU find, which every
    supported worker OS ships). Raises SyncError on transport failure
    or an empty directory -- a completed run always has artifacts, so
    an empty manifest means the path is wrong or the host lost it."""
    cmd = (f"find {shlex.quote(str(remote_dir))} -type f "
           f"-printf '%P\\t%s\\n'")
    res = conn.execute({"timeout": timeout_s}, {"cmd": cmd})
    if not isinstance(res, dict) or res.get("exit") != 0:
        raise SyncError(
            f"manifest failed (exit {res.get('exit') if isinstance(res, dict) else res!r}): "
            f"{(res.get('err') or '')[:200] if isinstance(res, dict) else ''}")
    out = {}
    for line in (res.get("out") or "").splitlines():
        rel, sep, size = line.rpartition("\t")
        if not sep:
            continue
        try:
            out[rel] = int(size)
        except ValueError:
            continue
    if not out:
        raise SyncError(f"empty manifest for {remote_dir}: no "
                        "artifacts to sync")
    return out


def _verify(local_dir, man):
    """Every manifest entry must exist locally with a matching size;
    a partial download raises rather than going visible."""
    for rel, size in man.items():
        p = os.path.join(local_dir, rel)
        try:
            got = os.path.getsize(p)
        except OSError:
            raise SyncError(f"partial download: {rel} missing") \
                from None
        if got != size:
            raise SyncError(f"partial download: {rel} is {got} bytes, "
                            f"manifest says {size}")


def pull_run(conn, remote_dir, dest, *, timeout_s=DEFAULT_SYNC_TIMEOUT_S,
             policy=None):
    """Mirror ``remote_dir`` (on the worker behind ``conn``) to the
    local directory ``dest``, atomically: the destination either
    doesn't exist or is a complete, manifest-verified copy. Returns
    ``{"files", "bytes", "attempts", "wall_s"}`` (``"already": True``
    when the destination was mirrored before); raises SyncError after
    the retry budget."""
    dest = os.path.abspath(str(dest)).rstrip(os.sep)
    if os.path.isdir(dest):
        return {"files": 0, "bytes": 0, "attempts": 0, "wall_s": 0.0,
                "already": True}
    policy = policy or RetryPolicy.bounded(timeout_s)
    t0 = time.monotonic()
    deadline = t0 + float(timeout_s)
    attempts = 0

    def left():
        """Remaining wall budget: ONE deadline covers manifest +
        download + retries, so the whole pull really fits inside
        timeout_s (the lease is extended by exactly that much; two
        back-to-back full-timeout transport calls would overrun it)."""
        return max(1.0, deadline - time.monotonic())

    def attempt():
        nonlocal attempts
        attempts += 1
        from .. import obs
        obs.instant("fleet.sync.attempt", cat="fleet",
                    attempt=attempts, dir=str(remote_dir)[-120:])
        if os.path.isdir(dest):     # raced another syncer: their copy won
            return {"files": 0, "bytes": 0, "already": True}
        man = manifest(conn, remote_dir, timeout_s=left())
        tmp_root = store.sync_tmp_path(
            f"{os.getpid()}-{threading.get_ident()}")
        shutil.rmtree(tmp_root, ignore_errors=True)
        os.makedirs(tmp_root, exist_ok=True)
        tmp = os.path.join(tmp_root, os.path.basename(dest))
        try:
            res = conn.download({"timeout": left()}, str(remote_dir),
                                tmp)
            if not isinstance(res, dict) or res.get("exit") != 0:
                raise SyncError(
                    f"download failed (exit "
                    f"{res.get('exit') if isinstance(res, dict) else res!r}): "
                    f"{(res.get('err') or '')[:200] if isinstance(res, dict) else ''}")
            _verify(tmp, man)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            try:
                os.rename(tmp, dest)
            except OSError as e:
                if not os.path.isdir(dest):   # a real rename failure
                    raise SyncError(f"couldn't publish sync: {e}") \
                        from None
            # the manifest rides in the result so the dispatcher can
            # journal it: fleetlint re-verifies the mirrored copy
            # against these sizes post hoc (FL008)
            return {"files": len(man), "bytes": sum(man.values()),
                    "manifest": dict(man)}
        finally:
            shutil.rmtree(tmp_root, ignore_errors=True)

    try:
        out = policy.call(attempt, retry_on_exception=SyncError,
                          site="fleet.artifact_sync")
    except SyncError as e:
        e.attempts = attempts
        raise
    out["attempts"] = attempts
    out["wall_s"] = round(time.monotonic() - t0, 3)
    return out


# ---------------------------------------------------------------------------
# download on demand (web.py's fallback for not-yet-mirrored runs)

_pending = {}           # store-relative run dir -> how to fetch it
_pending_lock = threading.Lock()
_fetch_locks = {}       # pending key -> its in-flight-pull lock


def register_pending(rel, *, kind, conn_spec, remote_dir,
                     timeout_s=DEFAULT_SYNC_TIMEOUT_S):
    """Remember that the run at store-relative ``rel`` still lives on
    a worker host (sync failed terminally); web.py will pull it on
    first access."""
    with _pending_lock:
        _pending[str(rel).strip("/")] = {
            "kind": str(kind), "conn_spec": dict(conn_spec or {}),
            "remote_dir": str(remote_dir), "timeout_s": timeout_s,
        }


def pending():
    with _pending_lock:
        return dict(_pending)


def clear_pending():
    with _pending_lock:
        _pending.clear()
        _fetch_locks.clear()


def fetch_on_demand(rel):
    """If ``rel`` (a store-relative path, possibly a file inside a
    run directory) is covered by a pending registration, pull the run
    now. Returns True when the path should exist locally afterwards.
    Serialized PER RUN: two browser tabs racing the same run do one
    pull, while fetches of different runs proceed independently (one
    slow worker host must not queue every other 404-fallback)."""
    rel = str(rel).strip("/")
    with _pending_lock:
        match = next((k for k in _pending
                      if rel == k or rel.startswith(k + "/")), None)
        entry = dict(_pending[match]) if match else None
        lock = _fetch_locks.setdefault(match, threading.Lock()) \
            if match else None
    if entry is None:
        return False
    base = resolve_remote(entry["kind"])
    if base is None:
        return False
    dest = os.path.join(os.path.abspath(store.base_dir), match)
    with lock:
        if not os.path.isdir(dest):
            try:
                conn = base().connect(entry["conn_spec"])
                pull_run(conn, entry["remote_dir"], dest,
                         timeout_s=entry["timeout_s"])
            except Exception as exc:  # noqa: BLE001 - 404 instead
                logger.warning("on-demand artifact fetch of %s "
                               "failed: %s", rel, exc)
                return False
    with _pending_lock:
        _pending.pop(match, None)
        _fetch_locks.pop(match, None)
    from .. import obs
    obs.inc("fleet.artifact_fetch_on_demand")
    return True
