"""Backend failover tiering: a down accelerator degrades a campaign
to slower verdicts instead of 0.0.

The r05 bench run scored 0.0 for one reason only: the remote-TPU
tunnel was down and nothing fell back. This module is the scheduler's
answer -- an ordered ladder of backend *tiers*::

    tpu -> gpu -> cpu

each with a health probe (jax backend init in a KILLABLE subprocess,
the bench's ``_device_preflight`` lesson: a dead tunnel HANGS rather
than errors) and a cached verdict, so per-cell tier choice costs a
dict lookup, not a probe. The last tier (``cpu``) is the
unconditional floor: jax's CPU backend initializes everywhere, and
the CPU engines (linear / sequential wgl) still produce verdicts --
slower, budget-capped, but never 0.0.

Two application points:

* **in-process** (campaign scheduler): jax's platform is frozen after
  backend init, so ``apply`` degrades the CHECKER instead -- every
  ``Linearizable`` gate in the cell's checker tree is re-pointed at
  the tier's algorithm (cpu tier -> the ``linear`` event-sweep, the
  monitor's own CPU-only choice).
* **cross-process** (fleet dispatch / workers): the worker process is
  fresh, so the dispatcher additionally exports ``tier_env`` --
  ``JAX_PLATFORMS=<tier>`` -- and the worker's jax really does come up
  on the degraded platform.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time

logger = logging.getLogger(__name__)

__all__ = ["TIERS", "DEFAULT_LADDER", "tier_env", "probe", "Failover",
           "as_failover", "apply"]

#: known tiers: JAX_PLATFORMS value + the in-process checker algorithm
#: the tier degrades to (None = leave the checker's own choice alone)
TIERS = {
    "tpu": {"platforms": "tpu", "algorithm": None},
    "gpu": {"platforms": "cuda", "algorithm": None},
    "cpu": {"platforms": "cpu", "algorithm": "linear"},
}

#: the default failover ladder, best tier first
DEFAULT_LADDER = ("tpu", "gpu", "cpu")

#: how long one probe verdict stays fresh
PROBE_TTL_S = 300.0

PROBE_TIMEOUT_S = 60.0


def tier_env(tier):
    """The env a fresh worker process needs to come up on ``tier``."""
    return {"JAX_PLATFORMS": TIERS[str(tier)]["platforms"]}


def probe(tier, timeout_s=PROBE_TIMEOUT_S):
    """Is ``tier``'s jax backend reachable? Probed in a killable
    subprocess -- a dead TPU tunnel hangs backend init forever (the
    r05 failure mode), and a hang must read as "down", not block the
    scheduler. Returns None when healthy, an error string otherwise."""
    env = dict(os.environ, **tier_env(tier))
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired:
        return f"backend init hung >{timeout_s:g}s"
    except OSError as e:  # pragma: no cover - no python?!
        return repr(e)
    if p.returncode == 0:
        return None
    return (p.stderr.strip()[-300:] or "backend init failed")


class Failover:
    """The per-campaign tier chooser: probe verdicts cached with a TTL
    so the scheduler consults it per CELL for the cost of a lookup,
    while a tier that comes back up is noticed within ``ttl_s``."""

    def __init__(self, ladder=DEFAULT_LADDER, probe_fn=probe,
                 ttl_s=PROBE_TTL_S, probe_timeout_s=PROBE_TIMEOUT_S):
        ladder = [str(t) for t in ladder]
        unknown = [t for t in ladder if t not in TIERS]
        if unknown:
            raise ValueError(f"unknown backend tier(s) {unknown}; "
                             f"known: {list(TIERS)}")
        if not ladder:
            raise ValueError("failover ladder needs at least one tier")
        self.ladder = ladder
        self.probe_fn = probe_fn
        self.ttl_s = float(ttl_s)
        self.probe_timeout_s = probe_timeout_s
        self._lock = threading.Lock()
        self._probe_lock = threading.Lock()
        self._cache = {}     # tier -> (monotonic stamp, error|None)

    def _cached(self, tier):
        with self._lock:
            hit = self._cache.get(tier)
            if hit is not None \
                    and time.monotonic() - hit[0] < self.ttl_s:
                return hit
        return None

    def health(self, tier):
        """Cached probe verdict for one tier (None = healthy). Probes
        are serialized and double-checked: N worker threads missing
        the cache together must launch ONE probe subprocess, not N
        60-second interpreter boots."""
        hit = self._cached(tier)
        if hit is not None:
            return hit[1]
        with self._probe_lock:
            hit = self._cached(tier)   # a peer probed while we waited
            if hit is not None:
                return hit[1]
            err = self.probe_fn(tier, timeout_s=self.probe_timeout_s)
            if err is not None:
                logger.warning("backend tier %r unhealthy: %s", tier,
                               err)
            with self._lock:
                self._cache[tier] = (time.monotonic(), err)
            return err

    def choose(self):
        """The best healthy tier; the ladder's LAST tier is the
        unconditional floor (degraded verdicts beat none)."""
        for tier in self.ladder[:-1]:
            if self.health(tier) is None:
                return tier
        return self.ladder[-1]

    def apply(self, test, tier):
        """In-process degrade: re-point the cell's checker at the
        tier's algorithm (see module docstring)."""
        apply(test, tier)


def apply(test, tier):
    """Rewrite every Linearizable gate in ``test``'s checker tree to
    the tier's algorithm and stamp ``test["backend"]``. A tier whose
    algorithm is None (healthy accelerator) leaves the checker alone."""
    test["backend"] = str(tier)
    algorithm = TIERS[str(tier)]["algorithm"]
    if algorithm is None:
        return test
    from ..checker.checkers import Linearizable
    seen = set()

    def walk(c):
        if c is None or id(c) in seen:
            return
        seen.add(id(c))
        if isinstance(c, Linearizable):
            c.algorithm = algorithm
            return
        for attr in ("inner", "checker"):
            walk(getattr(c, attr, None))
        cmap = getattr(c, "checker_map", None)
        if isinstance(cmap, dict):
            for child in cmap.values():
                walk(child)

    walk(test.get("checker"))
    return test


def as_failover(x):
    """Coerce run_cells/run_fleet's ``backends`` argument: an existing
    Failover passes through; a tier list (or comma string) becomes the
    ladder; True means the default ladder."""
    if isinstance(x, Failover):
        return x
    if x is True:
        return Failover()
    if isinstance(x, str):
        x = [t.strip() for t in x.split(",") if t.strip()]
    return Failover(ladder=list(x))
