"""The fleet worker: run ONE campaign cell in a standalone process.

The dispatcher (dispatch.py) execs this module on a worker host over
the control plane::

    python -m jepsen_tpu.fleet.worker        # cell spec JSON on stdin

The *cell spec* is everything a fresh process needs to rebuild and run
the cell -- not the test map itself (clients/checkers/generators don't
serialize), but the recipe: an importable builder plus the options and
per-cell params the coordinator would have fed it locally::

    {"campaign": "c1", "cell": "seed=0,workload=noop",
     "builder": "jepsen_tpu.demo:demo_test",
     "options": {...},            # JSON-able base CLI options
     "params": {"seed": 0, ...},  # this cell's axis values
     "store-dir": "/abs/store",   # the coordinator's store root
     "backend": "cpu",            # fleet.backends tier (optional)
     "seed": 0}                   # RNG seed before build (optional)

The worker prints exactly one result line, prefixed with
``JEPSEN-FLEET-RESULT:``, carrying the same record shape the campaign
scheduler journals (outcome/valid/path/wall_s/error + this run's
compile-cache delta). Everything else (logging) goes to stderr. The
DISPATCHER appends the record to the campaign journal -- the worker
never touches ``cells.jsonl``, so the journal stays single-writer and
a kill -9'd worker simply produces no result line (its lease expires
and the cell is stolen).

Fault injection for the work-stealing tests rides on the spec:
``"die-once-marker": path`` makes the worker SIGKILL itself before
running the cell, exactly once per marker path -- the second lease of
the same cell finds the marker and runs normally.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
import traceback

logger = logging.getLogger(__name__)

__all__ = ["RESULT_MARKER", "resolve_builder", "run_cell_spec", "main",
           "parse_result"]

RESULT_MARKER = "JEPSEN-FLEET-RESULT:"


def resolve_builder(ref):
    """``"pkg.module:function"`` -> the callable. The builder must be
    importable on the worker host; it receives the merged options
    mapping and returns a test map (the same contract as a suite's
    test-fn)."""
    mod, sep, fn = str(ref).partition(":")
    if not sep or not mod or not fn:
        raise ValueError(f"builder {ref!r} should be 'pkg.module:fn'")
    import importlib
    return getattr(importlib.import_module(mod), fn)


def _die_once(marker):
    """SIGKILL this process unless ``marker`` already exists (creating
    it first, so only the FIRST attempt dies): the deterministic
    worker-death injection the work-stealing tests key on."""
    if not marker:
        return
    if os.path.exists(marker):
        return
    with open(marker, "w") as f:
        f.write(str(os.getpid()))
        f.flush()
        os.fsync(f.fileno())
    logger.warning("die-once-marker %s: killing self (SIGKILL)", marker)
    os.kill(os.getpid(), 9)


def run_cell_spec(spec):
    """Build and run one cell from its spec; returns the journal-shaped
    record. Crashes are contained into outcome "crashed" -- the worker
    must always produce a parseable result if it survives at all.

    Telemetry plane: the spec's ``trace`` block (minted by the
    dispatcher) is bound into the run as ``test["obs-context"]``, so
    every span and metric this process emits carries {campaign, cell,
    worker} -- and the worker stamps its OWN wall clock at spec
    receipt and result print (``rec["clock"]``), the two worker-side
    legs of the handshake ``obs.merge`` normalizes clock skew with."""
    from .. import core, store
    from ..campaign import compile_cache

    # chaos clock skew (fleet.chaos "txn-skew"): this worker's wall
    # clock reads skewed by a seeded offset; both handshake stamps
    # shift together, exactly like a host with a wrong clock
    skew_s = float(spec.get("clock-skew-s") or 0.0)
    received_epoch = time.time() + skew_s
    cid = spec.get("cell")
    params = dict(spec.get("params") or {})
    tctx = spec.get("trace") or {}
    rec = {"cell": cid, "group": spec.get("group") or cid,
           "params": params, "worker": spec.get("worker"),
           "pid": os.getpid(),
           # echo the coordinator's fencing token (fleet.ha): the
           # record names the epoch that leased it even when it is
           # relayed through a zombie coordinator's journal append
           **({"coordinator-epoch": spec["coordinator-epoch"]}
              if spec.get("coordinator-epoch") is not None else {}),
           "clock": {"worker-received-epoch": received_epoch,
                     **({"coord-sent-epoch":
                         tctx["coord-sent-epoch"]}
                        if tctx.get("coord-sent-epoch") is not None
                        else {})}}
    t0 = time.monotonic()
    test = None
    try:
        if spec.get("store-dir"):
            store.base_dir = str(spec["store-dir"])
        _die_once(spec.get("die-once-marker")
                  or params.get("die-once-marker"))
        if spec.get("ledger", True):
            from . import ledger as fledger
            fledger.attach()
        cc_before = compile_cache.stats()
        options = dict(spec.get("options") or {})
        options.update(params)
        if isinstance(options.get("concurrency"), str):
            from ..cli import parse_concurrency
            options["concurrency"] = parse_concurrency(
                options["concurrency"], options.get("nodes") or [])
        if params.get("seed") is not None:
            import random
            random.seed(params["seed"])
        build = resolve_builder(spec.get("builder")
                                or "jepsen_tpu.demo:demo_test")
        test = core.prepare_test(build(options))
        test.setdefault("campaign", {}).update(
            {"id": spec.get("campaign"), "cell": cid, "params": params,
             "worker": spec.get("worker")})
        # bind the campaign trace context into obs: the run's tracer
        # anchors trace_meta with it and the registry labels every
        # metric, so the mirrored artifacts merge attributably
        test.setdefault("obs-context", {
            "campaign": spec.get("campaign"), "cell": cid,
            "worker": spec.get("worker")})
        if options.get("telemetry-flush-ms") is not None:
            test.setdefault("telemetry-flush-ms",
                            options["telemetry-flush-ms"])
        tier = spec.get("backend")
        if tier:
            from . import backends as fbackends
            fbackends.apply(test, tier)
            rec["backend"] = tier
        finished = core.run(test)
        valid = (finished.get("results") or {}).get("valid")
        rec["valid"] = valid
        rec["outcome"] = valid if valid in (True, False) else "unknown"
        if finished.get("aborted"):
            rec["abort-reason"] = str(finished["aborted"])
        err = (finished.get("results") or {}).get("error")
        if err:
            rec["error"] = str(err)
        rec["compile-cache"] = compile_cache.delta(cc_before)
    except Exception:  # noqa: BLE001 - contained per cell
        logger.warning("fleet worker cell %s crashed\n%s", cid,
                       traceback.format_exc())
        rec["outcome"] = "crashed"
        rec["error"] = traceback.format_exc(limit=8)
    try:
        from .. import store as _store
        rec["path"] = _store.path(test) if test else None
    except (AssertionError, AttributeError, KeyError, TypeError):
        rec["path"] = None
    rec["wall_s"] = round(time.monotonic() - t0, 3)
    rec["clock"]["worker-result-epoch"] = time.time() + skew_s
    return rec


def parse_result(out):
    """Extract the record from a worker's stdout, or None when the
    worker died before printing it (the dispatcher's steal signal).
    The marker line is searched from the END: a chatty test's own
    stdout must not shadow the result."""
    for line in reversed((out or "").splitlines()):
        line = line.strip()
        if line.startswith(RESULT_MARKER):
            try:
                rec = json.loads(line[len(RESULT_MARKER):])
            except ValueError:
                return None
            # a chatty test can emit a marker-shaped line whose JSON
            # isn't a record; only a dict is a result, anything else
            # is the steal signal
            return rec if isinstance(rec, dict) else None
    return None


def main(argv=None):
    """CLI entry: read the cell spec (stdin by default), run it, print
    the result line. Exits 0 whenever a result was produced -- the
    OUTCOME rides in the record; nonzero exits are reserved for
    harness-level failure (unparseable spec), which the dispatcher
    treats as a worker fault."""
    p = argparse.ArgumentParser(prog="jepsen_tpu.fleet.worker")
    p.add_argument("--spec", default="-",
                   help="Cell spec JSON file ('-' = stdin).")
    ns = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s\t%(levelname)s\t%(name)s: %(message)s")
    try:
        if ns.spec == "-":
            spec = json.load(sys.stdin)
        else:
            with open(ns.spec) as f:
                spec = json.load(f)
    except ValueError as e:
        print(f"fleet worker: unparseable cell spec: {e}",
              file=sys.stderr)
        return 3
    rec = run_cell_spec(spec)
    from .. import store
    print(RESULT_MARKER + json.dumps(rec, cls=store._Encoder),
          flush=True)
    return 0


if __name__ == "__main__":
    code = main()
    # hard exit (cli.hard_main rationale): a still-compiling jax thread
    # can abort the C++ runtime during normal teardown and stomp the
    # exit code the dispatcher keys on; the result line is already out
    sys.stdout.flush()
    sys.stderr.flush()
    logging.shutdown()
    os._exit(code)
