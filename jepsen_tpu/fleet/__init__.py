"""The fleet layer: campaigns as a multi-host, restart-surviving,
externally-callable checking service.

The reference Jepsen is itself a distributed orchestrator -- a control
node driving N workers over SSH -- and the NP-hard core check ("On the
complexity of Linearizability", arxiv 1410.5000) makes fleet-level
parallelism over *independent* cells the honest scaling axis beyond
per-device kernels: the partition-compatibility argument
P-compositionality (arxiv 1504.00204) makes for keys applies verbatim
to campaign cells. Four pillars:

* **ledger** -- the campaign compile-reuse ledger
  (``campaign/compile_cache.py``) made disk-persistent under
  ``store/compile_ledger/``: atomic fcntl-locked appends, torn-tail
  tolerant reads, so compile-cache knowledge survives process restarts
  and is shared across concurrent campaign processes.
* **dispatch + worker** -- remote-worker campaigns: the dispatcher
  leases cells to N hosts over the *existing* ``control/remotes.py``
  SSH plane (our own L0 control plane, RetryPolicy-backed probes
  included), lease records append to the campaign journal as the
  single source of truth, and an expired or dead worker's cell is
  re-leased to another host (work stealing).
* **service** -- ``web.py`` grown from a viewer into a submission API:
  ``POST /api/check`` (history JSON -> verdict via histlint + the
  monitor's engine dispatch) and ``POST /api/campaigns`` (sweep matrix
  -> pollable campaign), with request-size limits, JSON errors, and a
  shared AbortLatch honored on shutdown.
* **backends** -- per-cell backend failover tiering (tpu -> gpu ->
  cpu) chosen by a cached health probe, so a down accelerator degrades
  a campaign to slower verdicts instead of 0.0.

Submodules that pull in the heavy harness chain load lazily;
``ledger`` stays dependency-light (store + fcntl only) because
``campaign.compile_cache`` imports it from inside the note path.
"""

from __future__ import annotations

_LAZY = ("ledger", "worker", "dispatch", "service", "backends",
         "sync", "chaos", "ha")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in ("run_fleet", "FleetError", "parse_workers"):
        from . import dispatch
        return getattr(dispatch, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["ledger", "worker", "dispatch", "service", "backends",
           "sync", "chaos", "ha", "run_fleet", "FleetError",
           "parse_workers"]
