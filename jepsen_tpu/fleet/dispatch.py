"""The fleet dispatcher: lease campaign cells to worker hosts over the
L0 control plane, steal work from the dead.

Execution model (the reference Jepsen's own shape -- one control node
driving workers over SSH -- turned on ourselves):

* **Workers.** Each worker is a host reached through
  ``control/remotes.py`` -- ``SSHRemote`` for real hosts, or
  ``LocalRemote`` for the loopback topology (N worker *processes* on
  one machine; how the tests and the CI smoke run). Connection
  liveness is probed through the RetryPolicy-backed ``RetryRemote``
  (the L0 plane's own flake armor); the cell exec itself uses the raw
  transport -- a cell run is not idempotent at the transport layer,
  and re-running is the LEASE machinery's decision, not the retry
  loop's.
* **Leases.** The dispatcher pops a pending cell, journals a
  ``lease`` event, and execs ``python -m jepsen_tpu.fleet.worker``
  with the cell spec on stdin and a transport timeout of the lease
  TTL. A worker that returns a result line completes the lease; one
  that dies (kill -9 -> nonzero exit, no result) or times out forfeits
  it, and the cell goes back on the queue for ANY worker to re-lease
  (work stealing), up to ``max_leases`` attempts. A
  ``robust.LeaseWatchdog`` backstops wedged transports.
* **Journal = truth.** Lease grants, expiries, and failures append to
  the campaign journal as event records; outcomes append exactly once
  per cell (a terminal-guard drops a stolen cell's late duplicate), so
  ``cells.jsonl`` alone reconstructs who ran what, who died, and what
  the verdict was -- and ``--resume`` works unchanged.
* **Abort.** One ``robust.AbortLatch`` (SIGINT/SIGTERM) stops new
  leases; in-flight execs drain to their transport timeout and the
  journal is left resumable.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import threading
import time
import traceback

from .. import obs, robust, store
from ..control import remotes
from ..obs import Registry, Tracer
from ..campaign import compile_cache
from ..campaign import report as creport
from ..campaign.journal import CampaignJournal
from ..campaign.scheduler import CampaignError, new_campaign_id

logger = logging.getLogger(__name__)

__all__ = ["FleetError", "Worker", "parse_workers", "run_fleet",
           "DEFAULT_LEASE_S", "MAX_LEASES"]

#: default lease TTL: how long one cell exec may run before its worker
#: is presumed dead and the cell is stolen
DEFAULT_LEASE_S = 600.0

#: how many leases a cell may burn before it journals as crashed
MAX_LEASES = 3

#: consecutive transport-layer failures before a worker is retired
WORKER_STRIKES = 3


class FleetError(CampaignError):
    """Fleet-level wiring failure (no workers, PL014 errors)."""


class Worker:
    """One worker host: id + the conn spec its remotes connect with."""

    def __init__(self, wid, host, kind="ssh", conn_spec=None):
        self.id = str(wid)
        self.host = str(host)
        self.kind = kind
        self.conn_spec = dict(conn_spec or {}, host=self.host)

    def __repr__(self):
        return f"Worker({self.id!r}, {self.host!r}, {self.kind})"

    def _base_remote(self):
        from .sync import resolve_remote
        base = resolve_remote(self.kind)
        return (base or remotes.SSHRemote)()

    def connect(self):
        """The raw (non-retrying) transport for cell execs."""
        return self._base_remote().connect(self.conn_spec)

    def probe(self, timeout_s=30):
        """Liveness probe through the RetryPolicy-backed transport
        (dogfooding control's flake armor where retries ARE safe).
        Returns None when healthy, an error string otherwise."""
        try:
            conn = remotes.RetryRemote(
                self._base_remote()).connect(self.conn_spec)
            res = conn.execute({"timeout": timeout_s}, {"cmd": "true"})
            if res.get("exit") != 0:
                return (f"probe exit {res.get('exit')}: "
                        f"{(res.get('err') or '')[:200]}")
            return None
        except Exception as exc:  # noqa: BLE001 - probe must not raise
            return repr(exc)


LOCAL_HOSTS = ("local", "localhost", "127.0.0.1")


def parse_workers(spec, ssh=None):
    """``"host1,host2"`` (or a list) -> [Worker]. ``name=host`` gives
    an explicit worker id; repeated bare hosts auto-suffix (``local``,
    ``local#2``) so N loopback worker processes coexist. ``local`` /
    ``localhost`` use the LocalRemote transport; anything else is an
    SSH host resolved with the suite's ssh options. A ``host:port``
    suffix overrides the ssh port per worker (the docker fleet's
    sshd containers all live on 127.0.0.1 behind different ports)."""
    if isinstance(spec, str):
        entries = [e.strip() for e in spec.split(",") if e.strip()]
    else:
        entries = [str(e).strip() for e in (spec or []) if str(e).strip()]
    ssh = ssh or {}
    conn = {k: ssh.get(k) for k in ("port", "username",
                                    "private-key-path",
                                    "strict-host-key-checking")
            if ssh.get(k) is not None}
    out, seen = [], {}
    for entry in entries:
        wid, eq, host = entry.partition("=")
        if not eq:
            wid, host = entry, entry
        seen[wid] = seen.get(wid, 0) + 1
        if seen[wid] > 1 and not eq:
            wid = f"{wid}#{seen[wid]}"
        wconn = conn
        h, sep, port = host.rpartition(":")
        if sep and port.isdigit():
            host, wconn = h, dict(conn, port=int(port))
        kind = "local" if host in LOCAL_HOSTS else "ssh"
        out.append(Worker(wid, host, kind=kind, conn_spec=wconn))
    return out


def _repo_root():
    """The directory ``python -m jepsen_tpu...`` must run from."""
    import jepsen_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(jepsen_tpu.__file__)))


def run_fleet(cells, workers, *, campaign_id=None, resume=False,
              lease_s=DEFAULT_LEASE_S, max_leases=MAX_LEASES,
              builder=None, base_options=None, latch=None, ledger=True,
              backends=None, python=None, cwd=None, serve=False,
              device_slots=1, probe=True, env=None, sync="auto",
              worker_store_dir=None, sync_timeout_s=None, chaos=None,
              serve_ip=None, auth_token=None, trace_merge=True,
              fleetlint="on", coalesce=False, coalesce_window_ms=None,
              coalesce_max_segments=None, capacity=None,
              device_mem_budget=None, capacity_plan=None,
              coordinator_lease_s=None, takeover_grace_s=None,
              ha_epoch=None):
    """Run a campaign across worker hosts; returns the report dict
    (persisted as report.json, same shape as scheduler.run_cells).

    ``cells`` are plan-style ``{"id", "group", "params"}`` maps;
    ``builder`` is the importable ``"pkg.module:fn"`` every worker
    rebuilds test maps with, fed ``base_options`` overlaid with each
    cell's params. ``serve``/``device_slots``/``serve_ip``/
    ``auth_token`` and the ``coalesce*`` knobs participate only in
    the PL014/PL016/PL020 preflight (the CLI co-launches the
    service).

    **Artifact sync** (``sync``): ``"auto"`` mirrors each remote
    cell's run directory into the coordinator store over the scp
    plane whenever the worker's store isn't this process's store (ssh
    workers, or any worker when ``worker_store_dir`` points workers
    at their own directory); ``True``/``False`` force it. Sync
    happens under the cell's lease (extended by ``sync_timeout_s``,
    default ``fleet.sync.DEFAULT_SYNC_TIMEOUT_S``), is journaled as
    ``artifact-sync`` events, and a failed sync forfeits the lease --
    the cell re-runs on another worker -- until the lease budget is
    exhausted, at which point the verdict is kept (``synced: False``)
    and ``--resume`` re-syncs instead of re-running.

    **Chaos** (``chaos``): a ``fleet.chaos`` profile (or its
    ``"name:seed"`` spec) wraps every worker transport in
    `remotes.FaultyRemote` and schedules worker kill -9s, so the
    lease/steal/sync machinery is exercised under seeded faults.

    **Telemetry** (``trace_merge``): the coordinator mints the
    campaign trace context, ships it to every worker in the cell spec
    (workers stamp their spans/metrics with {campaign, cell, worker}
    and journal them crash-safely), records the lease clock handshake
    on both sides, and — when ``trace_merge`` is on — folds every
    mirrored run trace into ``campaign_trace.jsonl`` at finalize with
    worker clocks normalized onto its own (obs.merge). The dispatch
    tracer/registry are also bound process-globally for the
    campaign's duration, so chaos injections, sync pulls, and probes
    emit first-class events, and registered /api/metrics sources
    serve the live lease/queue gauges.

    **Audit** (``fleetlint``): ``"on"`` (default) replays the
    finished campaign's artifacts against the control-plane protocol
    (analysis.fleetlint -- terminal-guard, single journal writer,
    lease lifecycle, sync manifests, trace causality, chaos
    accounting) into ``fleet_analysis.json``, and preflights
    ``--resume`` with the well-formedness subset, refusing (PL018) to
    resume a journal with duplicate terminal records or interleaved
    writers. ``"off"`` skips both. The finalize audit is CONTAINED:
    findings (and auditor crashes) are reported, never allowed to
    flip a cell outcome or the campaign's exit code -- the same rule
    searchplan follows for verdicts.

    **Capacity** (``capacity`` / ``device_mem_budget`` /
    ``capacity_plan``): with a ``--capacity`` mode (or a pre-built
    plan from the CLI), the analysis.capplan static pass predicts
    every cell's compile shapes and HBM footprint before any host is
    contacted -- PL021 lints the knobs, ``enforce`` refuses on
    CP/PL021 errors, the plan persists as ``capacity_plan.json``, a
    live service coalescer pre-registers the planned (model, bucket)
    buckets, and at finalize the prediction is diffed against the
    compile shapes the campaign actually noted (persistent-ledger
    delta + the coordinator's own) into ``report["capacity"]`` -- the
    prediction oracle. ``plan``/``warn`` are CONTAINED: findings and
    planner crashes can never flip a cell outcome or the exit code.

    **Coordinator HA** (``coordinator_lease_s`` / ``takeover_grace_s``
    / ``ha_epoch``): with a coordinator lease TTL set, THIS
    coordinator's role becomes a journaled lease (fleet.ha): it
    claims a coordinator epoch, stamps every journal append with it,
    renews a ``coordinator-lease`` event on a heartbeat, and rechecks
    the journal at the terminal-guard so a fenced (superseded)
    coordinator refuses its own late appends and stands down instead
    of finalizing. A standby that won a takeover resumes with
    ``resume=True, ha_epoch=<won epoch>``; a manual ``--resume`` of
    an HA campaign fences the prior epoch with a ``forced`` takeover
    record. The ``coordinator-kill`` chaos profile SIGKILLs this
    process right after a seeded cell's lease-grant append (die-once
    marker), which is what the HA soak and bench rung 14 recover
    from."""
    from ..analysis import planlint, render_text, errors as diag_errors
    from . import sync as fsync

    workers = [w if isinstance(w, Worker) else Worker(w, w)
               for w in (workers or [])]
    cells = list(cells)
    base_options = dict(base_options or {})
    if sync_timeout_s is None:
        sync_timeout_s = fsync.DEFAULT_SYNC_TIMEOUT_S
    if chaos is not None:
        from . import chaos as fchaos
        chaos = fchaos.parse(chaos)
    diags = planlint.lint_fleet({
        "workers": [w.id for w in workers],
        "lease-s": lease_s,
        "serve?": serve,
        "device-slots": device_slots,
        "backends": backends,
        "time-limit": base_options.get("time-limit"),
    })
    diags += planlint.lint_service({
        "serve?": serve,
        "serve-ip": serve_ip,
        "auth-token?": bool(auth_token),
        "sync-timeout-s": sync_timeout_s,
        "lease-s": lease_s,
    })
    # PL017: telemetry-plane preflight — flush knob sanity, exposed
    # /api/metrics, and a trace merge that artifact sync can't feed
    diags += planlint.lint_telemetry({
        "telemetry-flush-ms": base_options.get("telemetry-flush-ms"),
        "metrics?": serve,
        "serve-ip": serve_ip,
        "auth-token?": bool(auth_token),
        "trace-merge?": trace_merge,
        "sync?": sync if isinstance(sync, bool) else None,
    })
    # PL015 rides along like PL013/PL014: the workers rebuild test
    # maps from these base options, so searchplan knob mistakes
    # (unknown predicate names, carry disabled under the monitor)
    # surface before any host is contacted
    diags += planlint.searchplan_diags(base_options)
    # PL019 rides along over the base options like PL015: profile /
    # progress-cadence knob mistakes surface before any host is
    # contacted (workers rebuild test maps from these options)
    diags += planlint.lint_introspection(base_options)
    # PL018 (knob half): an unknown --fleetlint value is an error
    # here, not a silently-skipped audit
    diags += planlint.lint_fleetlint({"fleetlint": fleetlint})
    # PL022: phase-attribution / trend-gate knobs ride along like
    # PL019 (phases off while profile or a bubble fold needs their
    # spans, unreadable trend baselines, bad gate thresholds)
    diags += planlint.lint_trend(base_options)
    # PL023: verdict-certification knobs ride along the same way (bad
    # sample counts / budgets; the skip-offline? backstop note)
    diags += planlint.lint_certify(base_options)
    # PL020: cross-tenant coalescing knobs ride along like the other
    # serve knobs (the CLI co-launches the service; bad windows and
    # no-op configurations surface before any host is contacted)
    diags += planlint.lint_coalesce({
        "coalesce?": coalesce,
        "coalesce-window-ms": coalesce_window_ms,
        "coalesce-max-segments": coalesce_max_segments,
        "device-slots": device_slots,
        "engine": base_options.get("engine"),
    })
    # PL024: coordinator-HA knobs ride along (non-positive lease /
    # grace TTLs, a renewal interval that can't beat its own lease,
    # coordinator-kill chaos with no HA lease for a standby to fence)
    diags += planlint.lint_ha({
        "ha?": coordinator_lease_s is not None or ha_epoch is not None,
        "coordinator-lease-s": coordinator_lease_s,
        "takeover-grace-s": takeover_grace_s,
        "chaos-coordinator-kill?": bool(
            getattr(chaos, "coordinator_kill", 0))
        if chaos is not None else False,
    })
    # PL021 + the capacity plan (analysis.capplan): the static pass
    # over the cells' params x ModelSpecs -- predicted compile shapes,
    # HBM vs budget, int32 wall -- before any host is contacted. Only
    # "enforce" may refuse (CapacityError -> FleetError); in plan/warn
    # mode the capacity diagnostics are LOGGED but deliberately kept
    # out of the fatal check below -- CP/PL021 findings can never
    # refuse a non-enforce campaign (the containment rule)
    cap_diags = []
    if capacity_plan is None and (capacity is not None
                                  or device_mem_budget is not None):
        from ..analysis import capplan
        try:
            capacity_plan, cap_diags = capplan.preflight(
                cells, base=base_options, mode=capacity,
                device_mem_budget=device_mem_budget,
                device_slots=device_slots)
        except capplan.CapacityError as e:
            raise FleetError(str(e)) from None
    if diags or cap_diags:
        logger.warning("%s", render_text(diags + cap_diags,
                                         title="fleet preflight:"))
    if diag_errors(diags):
        raise FleetError(render_text(diag_errors(diags),
                                     title="fleet config invalid:"))
    ids = [c["id"] for c in cells]
    if len(set(ids)) != len(ids):
        raise FleetError(f"duplicate cell ids: "
                         f"{sorted({i for i in ids if ids.count(i) > 1})}")

    if resume and campaign_id is None:
        campaign_id = store.latest_campaign()
        if campaign_id is None:
            raise FleetError("--resume: no campaign found in the store")
    campaign_id = campaign_id or new_campaign_id()
    jr = CampaignJournal(campaign_id)
    prior = jr.load_meta()
    if resume and prior is None:
        raise FleetError(f"--resume: campaign {campaign_id!r} was "
                         "never started")
    if prior is not None and not resume:
        raise FleetError(
            f"campaign {campaign_id!r} already exists: pass --resume "
            "to continue it, or pick a new --campaign-id")
    if resume and fleetlint != "off":
        # preflight before TRUSTING the journal: the resume fold
        # (skip-terminal, re-run-aborted) is only sound over a
        # well-formed journal -- duplicate terminal records or
        # interleaved writers mean the folds lie, and resuming would
        # append new truth onto corrupt truth (PL018)
        from ..analysis import fleetlint as flint
        pf = planlint.lint_fleetlint({
            "resume?": True,
            "journal-diags": flint.preflight(campaign_id,
                                             records=jr.records())})
        if diag_errors(pf):
            raise FleetError(render_text(
                diag_errors(pf),
                title="--resume refused: journal fails the fleetlint "
                      "preflight:"))
    # coordinator HA (fleet.ha): claim an epoch BEFORE any other
    # append so every record this process writes is epoch-stamped.
    # HA is on when a lease TTL was asked for, when a standby hands
    # us its won epoch, or when the journal already carries HA events
    # (resuming an HA campaign without the flag must not silently
    # strip the fencing)
    from . import ha as fha
    ha_on = (coordinator_lease_s is not None or ha_epoch is not None
             or (resume and fha.current_epoch(jr.records()) > 0))
    ha_ctl = None
    if ha_on:
        if coordinator_lease_s is None:
            coordinator_lease_s = fha.DEFAULT_COORDINATOR_LEASE_S
        if takeover_grace_s is None:
            takeover_grace_s = fha.DEFAULT_TAKEOVER_GRACE_S
        if ha_epoch is None:
            cur = fha.current_epoch(jr.records())
            if cur and resume:
                # a MANUAL --resume of an HA campaign: the operator is
                # the takeover evidence, so fence the prior epoch with
                # a forced takeover record (FL016 skips the stamp
                # expiry requirement for forced fences)
                ha_epoch = fha.fence(jr, reason="manual-resume",
                                     forced=True)
                if ha_epoch is None:
                    raise FleetError(
                        f"--resume: lost the coordinator takeover "
                        f"race for campaign {campaign_id!r} -- another "
                        "coordinator fenced it first")
            else:
                ha_epoch = cur + 1
        jr.epoch = int(ha_epoch)

    done = jr.completed() if resume else {}
    jr.write_meta({
        "status": "running", "mode": "fleet",
        "created": (prior or {}).get("created") or store.local_time(),
        "updated": store.local_time(),
        "cells": ids,
        "workers": [w.id for w in workers],
        "lease-s": lease_s,
        "max-leases": max_leases,
        "sync-timeout-s": sync_timeout_s,
        **({"worker-store": str(worker_store_dir)}
           if worker_store_dir else {}),
        **({"coordinator-lease-s": coordinator_lease_s,
            "takeover-grace-s": takeover_grace_s,
            "ha-epoch": int(ha_epoch)} if ha_on else {}),
        **({"chaos": chaos.describe()} if chaos is not None else {}),
        "resumes": ((prior or {}).get("resumes") or 0)
        + (1 if resume else 0),
    })

    latch = latch or robust.AbortLatch()
    tr = Tracer(context={"campaign": campaign_id,
                         "role": "coordinator"})
    reg = Registry()
    # crash-safe coordinator telemetry: journal dispatch spans +
    # fleet counters next to cells.jsonl (kill -9 leaves them)
    try:
        tr.attach_journal(
            store.campaign_path(campaign_id, store.TRACE_JOURNAL_FILE))
        reg.attach_journal(
            store.campaign_path(campaign_id,
                                store.METRICS_JOURNAL_FILE))
    except Exception:  # noqa: BLE001 - journals are insurance
        logger.warning("couldn't attach fleet telemetry journals",
                       exc_info=True)
    if ha_on:
        def _on_fenced(state):
            # a standby fenced us: stop leasing immediately (the
            # latch drains in-flight cells); the terminal-guard below
            # refuses whatever results still arrive
            if not latch.is_set():
                latch.set(f"fenced: coordinator epoch {jr.epoch} "
                          f"superseded by epoch {state[0]} "
                          f"({state[1]})")
            tr.instant("fleet.ha.fenced", cat="fleet",
                       args={"epoch": jr.epoch,
                             "by-epoch": state[0],
                             "by-writer": str(state[1])})
        ha_ctl = fha.CoordinatorLease(
            jr, lease_s=coordinator_lease_s, epoch=jr.epoch,
            registry=reg, on_fenced=_on_fenced)
    led = None
    if ledger:
        try:
            from . import ledger as fledger
            led = fledger.attach()
        except Exception:  # noqa: BLE001 - persistence is optional
            logger.warning("couldn't attach the persistent compile "
                           "ledger", exc_info=True)
    if backends is not None:
        from . import backends as fbackends
        backends = fbackends.as_failover(backends)
    # loopback workers must run the coordinator's interpreter; REMOTE
    # hosts usually want an explicit python= path instead
    import sys
    python = python or (sys.executable
                        if all(w.kind == "local" for w in workers)
                        else "python3")
    cwd = cwd or _repo_root()
    store_dir = os.path.abspath(store.base_dir)
    # where the WORKERS write runs: the coordinator's store by default
    # (loopback workers share the filesystem), or worker_store_dir for
    # isolated worker stores -- the topology real remote hosts have,
    # reproducible on one machine, and the one artifact sync exists for
    worker_store = os.path.abspath(worker_store_dir) \
        if worker_store_dir else store_dir

    def needs_sync(worker):
        if sync is True:
            return True
        if sync is False:
            return False
        return worker.kind != "local" or worker_store != store_dir

    kill_cells = chaos.plan_kills(ids) if chaos is not None else set()
    # chaos coordinator-kill: one seeded cell whose lease-grant append
    # is this coordinator's last act (SIGKILL right after it hits the
    # journal). The marker file makes it die-once -- the takeover
    # coordinator resuming the same campaign+profile runs clean
    coord_kill_cell, coord_kill_marker = None, None
    if chaos is not None and getattr(chaos, "coordinator_kill", 0):
        coord_kill_marker = fha.takeover_marker(campaign_id)
        if not os.path.exists(coord_kill_marker):
            coord_kill_cell = chaos.plan_coordinator_kill(ids)
    if chaos is not None and chaos.torn_ledger_tail and led is not None:
        from . import chaos as fchaos
        fchaos.tear_ledger_tail(led)

    cond = threading.Condition()
    pending = collections.deque(c for c in cells if c["id"] not in done)
    by_id = {c["id"]: c for c in cells}
    terminal = set(done)
    alive = {w.id for w in workers}
    table = robust.LeaseTable()
    reg.set_gauge("fleet.cells_total", len(cells))
    reg.set_gauge("fleet.cells_resumed", len(done))
    reg.set_gauge("fleet.workers", len(workers))

    def finish(cid, rec):
        """Terminal-guard append: at most ONE outcome per cell, ever.
        Caller holds ``cond``."""
        if cid in terminal:
            reg.inc("fleet.stale_results")
            logger.info("dropping stale result for already-terminal "
                        "cell %s", cid)
            return False
        if ha_ctl is not None and ha_ctl.fenced(refresh=True):
            # zombie fencing: re-read the journal at the last moment
            # before the outcome append -- a takeover record means a
            # standby owns this campaign (and this cell) now, and OUR
            # append would be the exact split-brain FL016 exists to
            # catch. Refuse it and drain
            reg.inc("fleet.fenced_appends")
            logger.warning("refusing outcome append for %s: "
                           "coordinator epoch %s is fenced", cid,
                           jr.epoch)
            cond.notify_all()
            return False
        terminal.add(cid)
        jr.append_cell(rec)
        reg.inc("fleet.cells", outcome=str(rec.get("outcome")))
        if rec.get("wall_s") is not None:
            reg.observe("fleet.cell_s", rec["wall_s"])
        cond.notify_all()
        return True

    folded_cells = set()

    #: per-cell wgl counter families folded into the live fleet
    #: registry as cells finish, so GET /api/metrics serves the
    #: campaign's search-progress and padding accounting MID-RUN
    #: (bucket/engine labels survive: the flat key's label suffix is
    #: kept verbatim on the cell-labelled re-emission)
    _WGL_LIVE_COUNTERS = ("wgl.states_explored_total",
                          "wgl.cells_real", "wgl.cells_padded",
                          "wgl.device_busy_s", "wgl.chunks")

    def _fold_worker_metrics(rec):
        """Fold the headline series out of a finished cell's own
        metrics artifact (monitor detection latency + violations, and
        the device-search introspection counters: explored configs,
        real/padded batch rows per n-bucket, device-busy wall) into
        the live fleet registry, so ``GET /api/metrics`` serves them
        while the campaign is still running. Best effort: the file is
        local only for shared-store/synced cells. Folded at most ONCE
        per cell — a forfeited-sync re-run would otherwise re-inc the
        counters per attempt (detection latency is safe via
        max_gauge, the counters are not)."""
        try:
            if rec.get("cell") in folded_cells:
                return
            folded_cells.add(rec.get("cell"))
            p = rec.get("path")
            if not p or not os.path.isdir(str(p)):
                return
            m = store.load_run_metrics(str(p))
            if not m:
                return
            cid = str(rec.get("cell"))
            for k, v in (m.get("gauges") or {}).items():
                if k.startswith("monitor.detection_latency_s"):
                    reg.max_gauge("monitor.detection_latency_s",
                                  float(v), cell=cid)
            from ..obs.metrics import parse_flat_key
            for k, v in (m.get("counters") or {}).items():
                if k.startswith("monitor.violations"):
                    reg.inc("monitor.violations", int(v), cell=cid)
                    continue
                name, raw = parse_flat_key(k)
                if name in _WGL_LIVE_COUNTERS:
                    labels = {"cell": cid,
                              **{lk: lv for lk, lv in raw.items()
                                 if lk in ("engine", "bucket")}}
                    reg.inc(name, v, **labels)
        except Exception:  # noqa: BLE001 - telemetry fold only
            logger.warning("couldn't fold worker metrics",
                           exc_info=True)

    def requeue_or_fail(cid, worker_id, error):
        """A lease was forfeited: steal (requeue) or, past the attempt
        budget, journal the cell crashed. Caller holds ``cond``."""
        if cid in terminal:
            return
        if ha_ctl is not None and ha_ctl.fenced():
            return      # fenced: the standby owns the cell now
        jr.append_event({"event": "lease-failed", "cell": cid,
                         "worker": worker_id, "error": str(error)[:500],
                         "t": store.local_time()})
        tr.instant("fleet.lease.steal", cat="fleet",
                   args={"cell": cid, "worker": str(worker_id),
                         "error": str(error)[:200]})
        if table.attempts(cid) >= max_leases:
            finish(cid, {"cell": cid,
                         "group": by_id[cid].get("group") or cid,
                         "params": by_id[cid].get("params") or {},
                         "outcome": "crashed",
                         "error": f"lease budget exhausted "
                                  f"({max_leases} leases); last: "
                                  f"{str(error)[:300]}"})
        elif cid not in [c["id"] for c in pending]:
            pending.append(by_id[cid])
            reg.inc("fleet.cells_stolen", worker=str(worker_id))
            cond.notify_all()

    def on_lease_expired(lease):
        """LeaseWatchdog backstop: the transport wedged past its own
        timeout; put the cell back up for stealing."""
        reg.inc("fleet.lease_expired")
        tr.instant("fleet.lease.expired", cat="fleet",
                   args={"cell": lease.unit, "worker": lease.holder,
                         "attempt": lease.attempt,
                         "ttl_s": lease.ttl_s})
        with cond:
            jr.append_event({"event": "lease-expired",
                             "cell": lease.unit,
                             "worker": lease.holder,
                             "attempt": lease.attempt,
                             "t": store.local_time()})
            requeue_or_fail(lease.unit, lease.holder,
                            f"lease expired after {lease.ttl_s:.0f}s")

    def next_cell():
        """Block until a cell is available, all work is terminal, or
        the latch aborts; returns a cell or None."""
        with cond:
            while True:
                if latch.is_set():
                    return None
                if pending:
                    return pending.popleft()
                if len(terminal) >= len(cells) or not alive:
                    return None
                cond.wait(timeout=0.5)

    def cell_spec(cell, worker, attempt=1):
        spec = {"campaign": campaign_id, "cell": cell["id"],
                "group": cell.get("group") or cell["id"],
                "params": cell.get("params") or {},
                "options": base_options,
                "builder": builder or "jepsen_tpu.demo:demo_test",
                "store-dir": worker_store,
                "worker": worker.id,
                "ledger": bool(ledger),
                # trace-context propagation: the worker binds these
                # into obs so every span/metric it emits carries
                # {campaign, cell, worker}; the coord-sent stamp is
                # the first leg of the clock handshake obs.merge
                # normalizes worker clocks with
                "trace": {"campaign": campaign_id, "cell": cell["id"],
                          "worker": worker.id, "attempt": attempt,
                          "coord-sent-epoch": time.time()}}
        if cell["id"] in kill_cells:
            # chaos-scheduled kill -9: the die-once marker makes the
            # FIRST lease die mid-run and every later lease run clean
            safe = str(cell["id"]).replace(os.sep, "_")
            spec["die-once-marker"] = os.path.abspath(
                store.campaign_path(campaign_id, f"chaos-kill-{safe}"))
        if ha_ctl is not None:
            # the fencing token: workers echo it back on their result
            # record, so even a record relayed through a zombie
            # coordinator names the epoch that leased it
            spec["coordinator-epoch"] = jr.epoch
        if backends is not None:
            spec["backend"] = backends.choose()
        if chaos is not None:
            skew = chaos.skew_for(worker.id)
            if skew:
                # chaos clock skew: the worker shifts its handshake
                # stamps by this much (a worker whose wall clock is
                # simply wrong); obs.merge recovers it, and the bound
                # rides into the cell options so skew-aware txn
                # checkers gate their realtime edges on it
                spec["clock-skew-s"] = skew
                spec["options"] = dict(base_options,
                                       **{"skew-bound-s":
                                          chaos.skew_bound_s()})
        return spec

    def journal_sync(cell, wid, status, info=None, **extra):
        """One ``artifact-sync`` event record + metric (the sync_rec
        and resume-resync paths must journal identically). The
        verified manifest rides on success records so fleetlint can
        re-verify the mirrored copy against the journaled sizes
        (FL008); attempt counts ride on both outcomes so injected
        sync faults stay accountable (FL013)."""
        reg.inc("fleet.artifact_syncs", status=status,
                worker=str(wid))
        jr.append_event({"event": "artifact-sync", "cell": cell,
                         "worker": wid, "status": status,
                         **{k: info[k] for k in
                            ("files", "bytes", "attempts", "wall_s",
                             "manifest")
                            if info and k in info},
                         **extra, "t": store.local_time()})

    def sync_rec(worker, conn, lease, rec):
        """Mirror the finished cell's run directory into the
        coordinator store (fleet.sync): rewrites ``rec["path"]`` to
        the coordinator-local copy and journals the outcome as an
        ``artifact-sync`` event. Returns None on success (or nothing
        to do), else the error string -- the caller decides whether
        that forfeits the lease."""

        def failed(err):
            journal_sync(lease.unit, worker.id, "failed",
                         error=str(err)[:300],
                         **({"attempts": err.attempts}
                            if getattr(err, "attempts", 0) else {}))
            rec["synced"] = False
            # journal how to reach this worker's store: a later
            # --resume may run with a DIFFERENT worker list, and the
            # worker id alone is not a resolvable address
            rec["worker-kind"] = worker.kind
            rec["worker-conn"] = dict(worker.conn_spec)
            return str(err)

        src = rec.get("path")
        if not src:
            return None          # crashed before the store existed
        src = str(src)
        rel = os.path.relpath(src, worker_store)
        if rel.startswith(".."):
            rec["worker-path"] = src
            return failed(f"run path {src!r} escapes the worker store")
        dest = os.path.join(store_dir, rel)
        rec["path"] = dest
        if worker.kind == "local" and os.path.abspath(src) == dest:
            return None          # shared filesystem: already in place
        rec["worker-path"] = src
        try:
            info = fsync.pull_run(conn, src, dest,
                                  timeout_s=sync_timeout_s)
        except Exception as exc:  # noqa: BLE001 - journaled, bounded
            return failed(exc)
        reg.observe("fleet.artifact_sync_s", info.get("wall_s") or 0.0)
        journal_sync(lease.unit, worker.id, "ok", info=info, path=dest)
        rec["synced"] = True
        return None

    def run_lease(worker, conn, cell):
        cid = cell["id"]
        if ha_ctl is not None and ha_ctl.fenced():
            return False, {}    # superseded: grant nothing more
        lease = table.grant(cid, worker.id, lease_s)
        jr.append_event({"event": "lease", "cell": cid,
                         "worker": worker.id, "lease-s": lease_s,
                         "attempt": lease.attempt,
                         "t": store.local_time()})
        tr.instant("fleet.lease.grant", cat="fleet",
                   args={"cell": cid, "worker": worker.id,
                         "attempt": lease.attempt})
        if coord_kill_cell is not None and cid == coord_kill_cell:
            # chaos coordinator-kill: that grant was this process's
            # last act. Drop the die-once marker (flushed to disk so
            # the takeover coordinator never re-fires the kill), then
            # die the way a real coordinator dies -- no cleanup, no
            # journal goodbye, a live lease left dangling
            try:
                with open(coord_kill_marker, "w") as f:
                    f.write(f"{os.getpid()} {cid}\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:  # pragma: no cover - marker is best effort
                pass
            logger.warning("chaos: coordinator-kill on cell %s "
                           "(SIGKILL self)", cid)
            os.kill(os.getpid(), signal.SIGKILL)
        reg.set_gauge("fleet.lease_active", len(table.active()))
        spec = cell_spec(cell, worker, attempt=lease.attempt)
        ctx = {"dir": cwd, "timeout": lease_s}
        if env or spec.get("backend"):
            ctx["env"] = dict(env or {})
            if spec.get("backend"):
                from . import backends as fbackends
                ctx["env"].update(fbackends.tier_env(spec["backend"]))
        ok = False
        with tr.span("fleet.cell", cat="fleet",
                     args={"cell": cid, "worker": worker.id,
                           "attempt": lease.attempt}):
            try:
                res = conn.execute(
                    ctx, {"cmd": f"{python} -m jepsen_tpu.fleet.worker",
                          "in": json.dumps(spec, cls=store._Encoder)})
            except Exception:  # noqa: BLE001 - transport crash
                res = {"exit": -1, "err": traceback.format_exc(limit=4),
                       "out": ""}
        coord_received = time.time()
        from .worker import parse_result
        rec = parse_result(res.get("out")) if res.get("exit") == 0 \
            else None
        if rec is not None:
            # close the clock handshake: the worker stamped its
            # receive/result wall times into rec["clock"]; the
            # coordinator's send/receive stamps complete the four
            # obs.merge's skew estimate needs
            clock = rec.setdefault("clock", {})
            if isinstance(clock, dict):
                clock.setdefault("coord-sent-epoch",
                                 spec["trace"]["coord-sent-epoch"])
                clock["coord-received-epoch"] = coord_received
            _fold_worker_metrics(rec)
        sync_err = None
        if rec is not None and needs_sync(worker):
            # hold the watchdog off during the download (best effort:
            # if the lease already expired, the steal re-runs the cell
            # into a FRESH run dir, so a late sync can't collide).
            # Small pad past the pull's own deadline: the verify +
            # rename + journal tail must not lose a lease race. The
            # extension is journaled -- fleetlint checks that extends
            # happen only to cover an artifact sync (FL00x lease
            # lifecycle), the one legitimate reason a finished cell
            # may outlive its TTL
            table.extend(lease, sync_timeout_s + 5.0)
            jr.append_event({"event": "lease-extend", "cell": cid,
                             "worker": worker.id,
                             "ttl-s": sync_timeout_s + 5.0,
                             "reason": "artifact-sync",
                             "t": store.local_time()})
            with tr.span("fleet.artifact_sync", cat="fleet",
                         args={"cell": cid, "worker": worker.id}):
                sync_err = sync_rec(worker, conn, lease, rec)
        current = table.release(lease)
        reg.set_gauge("fleet.lease_active", len(table.active()))
        with cond:
            if rec is not None:
                if sync_err is not None and current \
                        and table.attempts(cid) < max_leases:
                    # the run finished but its artifacts are stuck on
                    # the worker: forfeit the lease so another worker
                    # re-runs the cell (fresh artifacts, fresh sync)
                    requeue_or_fail(cid, worker.id,
                                    f"artifact sync failed: "
                                    f"{sync_err}")
                    ok = False
                else:
                    # lease budget exhausted with a sync failure: the
                    # VERDICT is known (the worker reported it), so
                    # keep it, mark the record unsynced, and let
                    # --resume / web-on-demand fetch the artifacts
                    # later instead of burning the run
                    rec.setdefault("worker", worker.id)
                    rec["attempt"] = lease.attempt
                    ok = finish(cid, rec)
                    if ok and rec.get("synced") is False \
                            and rec.get("worker-path"):
                        rel = os.path.relpath(str(rec["path"]),
                                              store_dir)
                        if not rel.startswith(".."):
                            fsync.register_pending(
                                rel, kind=worker.kind,
                                conn_spec=worker.conn_spec,
                                remote_dir=rec["worker-path"],
                                timeout_s=sync_timeout_s)
            else:
                err = (res.get("err") or "")[-300:] \
                    or f"exit {res.get('exit')}, no result line"
                if current:   # the watchdog hasn't already requeued it
                    requeue_or_fail(cid, worker.id, err)
        return ok, res

    def worker_loop(worker):
        # bind the fleet pair for THIS thread's whole tenure: chaos
        # fault injections (remotes.FaultyRemote) and artifact-sync
        # pulls deep in the transport stack emit through the obs
        # facade, and without a binding they would be invisible —
        # the exact gap this plane closes. The bind stack makes N
        # worker threads pushing the same pair safe.
        with obs.bind(tr, reg):
            _worker_loop(worker)

    def _worker_loop(worker):
        try:
            conn = worker.connect()
            if chaos is not None:
                # the chaos schedule wraps the DISPATCH transport (cell
                # execs + artifact sync); the liveness probe below runs
                # on its own clean connection, so injection exercises
                # recovery paths, not the admission gate
                conn = remotes.FaultyRemote(
                    conn, chaos.faults_for(worker.id))
        except Exception as exc:  # noqa: BLE001
            conn, exc_ = None, exc
        if probe and conn is not None:
            with tr.span("fleet.probe", cat="fleet",
                         args={"worker": worker.id,
                               "kind": worker.kind}):
                perr = worker.probe()
        else:
            perr = None if conn is not None else repr(exc_)
        if perr is not None:
            logger.warning("fleet worker %s failed its liveness probe: "
                           "%s", worker.id, perr)
            jr.append_event({"event": "worker-dead", "worker": worker.id,
                             "error": str(perr)[:300],
                             "t": store.local_time()})
            reg.inc("fleet.worker_failures", worker=worker.id)
            with cond:
                alive.discard(worker.id)
                cond.notify_all()
            return
        strikes = 0
        try:
            while True:
                cell = next_cell()
                if cell is None:
                    break
                try:
                    ok, res = run_lease(worker, conn, cell)
                except Exception:  # noqa: BLE001 - thread must live
                    # an unexpected dispatch bug is a forfeited lease,
                    # never a silently-dead worker thread (the cell
                    # would otherwise hang until the lease watchdog)
                    logger.warning("fleet worker %s: lease handling "
                                   "crashed for %s", worker.id,
                                   cell["id"], exc_info=True)
                    with cond:
                        requeue_or_fail(cell["id"], worker.id,
                                        traceback.format_exc(limit=4))
                    ok, res = False, {}
                if ok or not remotes.transport_failed(res):
                    strikes = 0
                    continue
                strikes += 1
                reg.inc("fleet.worker_failures", worker=worker.id)
                if strikes >= WORKER_STRIKES:
                    logger.warning("retiring fleet worker %s after %d "
                                   "consecutive transport failures",
                                   worker.id, strikes)
                    jr.append_event({"event": "worker-dead",
                                     "worker": worker.id,
                                     "error": f"{strikes} consecutive "
                                              "transport failures",
                                     "t": store.local_time()})
                    break
        finally:
            with cond:
                alive.discard(worker.id)
                cond.notify_all()

    def resync_done_cells():
        """--resume re-SYNCS instead of re-running: a terminal cell
        whose record says ``synced: False`` kept its verdict but left
        its artifacts on the worker; pull them now (clean transport,
        no chaos) and journal the outcome. Already-mirrored runs (a
        prior resume, or web's on-demand fetch) are left alone."""
        by_worker = {w.id: w for w in workers}

        def resync_one(cid, rec):
            dest = str(rec.get("path") or "")
            rel = os.path.relpath(dest, store_dir) if dest else ".."
            if not dest or rel.startswith("..") or os.path.isdir(dest):
                return
            w = by_worker.get(str(rec.get("worker")))
            if w is not None:
                kind, conn_spec = w.kind, w.conn_spec
            elif rec.get("worker-conn"):
                # the worker isn't in THIS fleet's list, but its
                # terminal record journaled how to reach it
                kind = rec.get("worker-kind") or "ssh"
                conn_spec = rec["worker-conn"]
            else:
                logger.warning("can't re-sync %s: worker %r isn't in "
                               "this fleet and its record carries no "
                               "conn spec", cid, rec.get("worker"))
                return
            wid = str(rec.get("worker"))
            try:
                base = fsync.resolve_remote(kind)
                if base is None:
                    raise FleetError(f"unknown worker kind {kind!r}")
                info = fsync.pull_run(base().connect(conn_spec),
                                      rec["worker-path"], dest,
                                      timeout_s=sync_timeout_s)
            except Exception as exc:  # noqa: BLE001 - per-cell
                journal_sync(cid, wid, "failed",
                             error=str(exc)[:300],
                             **({"attempts": exc.attempts}
                                if getattr(exc, "attempts", 0)
                                else {}))
                fsync.register_pending(rel, kind=kind,
                                       conn_spec=conn_spec,
                                       remote_dir=rec["worker-path"],
                                       timeout_s=sync_timeout_s)
                return
            journal_sync(cid, wid, "ok", info=info, path=dest)

        todo = [(cid, rec) for cid, rec in done.items()
                if rec.get("synced") is False
                and rec.get("worker-path")]
        if not todo:
            return
        # re-syncs are independent of each other AND of dispatch;
        # serial pulls would stall startup by up to sync_timeout_s
        # per unreachable worker (journal appends are thread-safe --
        # the worker threads share it the same way)
        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(
                max_workers=min(8, len(todo)),
                thread_name_prefix="jepsen fleet resync") as pool:
            for _ in pool.map(lambda a: resync_one(*a), todo):
                pass

    if not workers:
        raise FleetError("fleet dispatch needs at least one worker")

    def _live_gauges():
        """The dispatcher's live state for GET /api/metrics: lease
        occupancy, queue depth, worker liveness — plus everything the
        fleet registry already counts."""
        with cond:
            extra = {"fleet.lease_active": len(table.active()),
                     "fleet.pending_cells": len(pending),
                     "fleet.terminal_cells": len(terminal),
                     "fleet.workers_alive": len(alive)}
        return [reg, {"gauges": extra}]

    from . import service as fservice
    metrics_source = fservice.register_metrics_source(
        f"fleet:{campaign_id}", _live_gauges)
    cap_led_before, cap_noted_before = set(), set()
    if capacity_plan is not None:
        # persist the plan, open the prediction-oracle brackets
        # (persistent-ledger keys cover worker processes, the noted
        # set covers the coordinator), and pre-register the planned
        # buckets on any live coalescer so first-window strangers
        # land in planned shapes. Contained: planning is advisory
        try:
            from ..analysis import capplan
            capplan.dump_plan(
                capacity_plan,
                store.campaign_path(campaign_id, capplan.PLAN_FILE))
            if led is not None:
                cap_led_before = set(led.refresh())
            cap_noted_before = compile_cache.noted_keys()
            coal = fservice.coalescer()
            if coal is not None:
                coal.preregister(capplan.predicted_keys(capacity_plan))
        except Exception:  # noqa: BLE001 - planning is advisory
            logger.warning("couldn't persist/pre-register the "
                           "capacity plan (contained)", exc_info=True)
            capacity_plan = None
    try:
        if resume and done:
            with obs.bind(tr, reg):
                resync_done_cells()
        watchdog = robust.LeaseWatchdog(table, on_lease_expired,
                                        poll_s=min(1.0, lease_s / 4))
        hard_abort = None
        cc_before = compile_cache.stats()
        try:
            with robust.signal_scope(latch):
                with tr.span("fleet.dispatch", cat="fleet",
                             args={"id": campaign_id,
                                   "cells": len(pending),
                                   "workers": len(workers)}):
                    if ha_ctl is not None:
                        # the claiming renewal lands before any cell
                        # lease: the journal carries the epoch first
                        ha_ctl.start()
                    watchdog.start()
                    threads = [threading.Thread(
                        target=worker_loop, args=(w,),
                        name=f"jepsen fleet {w.id}") for w in workers]
                    for t in threads:
                        t.start()
                    for t in threads:
                        while t.is_alive():
                            t.join(timeout=0.5)
        except BaseException as e:  # noqa: BLE001 - finalize, rethrow
            hard_abort = e
            if not latch.is_set():
                latch.set(repr(e))
            logger.warning("fleet campaign %s hard-aborted (%r); "
                           "journal is resumable with --resume",
                           campaign_id, e)
        finally:
            watchdog.stop()
            if ha_ctl is not None:
                ha_ctl.stop()

        if ha_ctl is not None and ha_ctl.fenced(refresh=True):
            # stand down WITHOUT touching campaign.json / report.json:
            # the winning coordinator owns them now. Our journal
            # appends are all epoch-stamped, so FL016 can audit
            # anything that slipped through the fencing race window
            raise FleetError(
                f"coordinator fenced: epoch {jr.epoch} superseded by "
                f"{ha_ctl.fenced_by}; standing down (the campaign "
                "continues under the new coordinator)")

        unfinished = set(ids) - terminal
        if unfinished and not latch.is_set():
            # every worker died with cells left: surface it as an
            # abort so the exit code and status say "incomplete", not
            # "passed"
            latch.set("workers-exhausted")
            logger.warning("fleet campaign %s: workers exhausted with "
                           "%d cell(s) unfinished", campaign_id,
                           len(unfinished))

        # compile reuse: the coordinator itself compiles nothing --
        # sum THIS run's workers' deltas from their records (cells
        # resumed from a prior process already reported theirs in that
        # process's stats event; re-folding them would double-count on
        # every --resume), then fold in the persisted ledger aggregate
        recs = jr.latest()
        fresh = [r for r in recs if str(r.get("cell")) not in done]
        cc = {"hits": 0, "misses": 0}
        for r in fresh:
            w = r.get("compile-cache") or {}
            cc["hits"] += int(w.get("hits") or 0)
            cc["misses"] += int(w.get("misses") or 0)
        local = compile_cache.delta(cc_before)
        cc["hits"] += local["hits"]
        cc["misses"] += local["misses"]
        reg.set_gauge("campaign.compile_cache.hits", cc["hits"])
        reg.set_gauge("campaign.compile_cache.misses", cc["misses"])
        if led is not None:
            # cold/warm compile wall: cells whose own delta had misses
            # paid a compile (cold); all-hit cells rode the caches
            # (warm). With the persistent jax compilation cache on, a
            # restarted campaign's "cold" cells stop paying -- this is
            # the evidence
            from .ledger import fold_walls
            cold, warm = fold_walls(fresh)
            led.note_stats(cc["hits"], cc["misses"], cold_wall_s=cold,
                           warm_wall_s=warm)
            try:
                cc = dict(cc, ledger=led.stats())
            except Exception:  # noqa: BLE001 - bookkeeping only
                logger.warning("couldn't aggregate compile-ledger "
                               "stats", exc_info=True)
        aborted = latch.is_set()
        report = creport.summarize(
            recs, meta={"id": campaign_id}, compile_cache=cc,
            aborted=aborted, abort_reason=latch.reason,
            skipped=len(done))
        report["mode"] = "fleet"
        report["workers"] = [w.id for w in workers]
        jr.write_report(report)
        try:
            tr.dump(store.campaign_path(campaign_id, "trace.jsonl"))
            tr.close_journal(remove=True)
            store._dump_json(reg.snapshot(),
                             store.campaign_path(campaign_id,
                                                 "metrics.json"))
            reg.close_journal(remove=True)
        except Exception:  # noqa: BLE001 - telemetry is a byproduct
            logger.warning("couldn't write fleet obs artifacts",
                           exc_info=True)
        if trace_merge:
            # fold every mirrored run trace + the coordinator's own
            # into ONE Perfetto timeline, worker clocks normalized
            # from the lease handshakes recorded above. Contained: a
            # merge failure costs the merged view, never the campaign
            try:
                from ..obs import merge as obs_merge
                minfo = obs_merge.merge_campaign(campaign_id)
                report["trace"] = {k: minfo[k] for k in
                                   ("path", "events", "cells",
                                    "skipped")}
                report["trace"]["workers"] = minfo["workers"]
                jr.write_report(report)
                logger.info("merged campaign trace: %d events, %d "
                            "cells (%d skipped) -> %s",
                            minfo["events"], minfo["cells"],
                            minfo["skipped"], minfo["path"])
            except Exception:  # noqa: BLE001
                logger.warning("couldn't merge the campaign trace",
                               exc_info=True)
        # fold the per-cell metrics (journal fallback included) into
        # metrics_fold.json and surface the introspection headline —
        # per-bucket padding waste + device-busy wall — on the report.
        # Contained: a fold failure costs the table, never the campaign
        try:
            from ..obs import merge as obs_merge
            fold = obs_merge.fold_campaign_metrics(campaign_id)
            report["introspection"] = obs_merge.introspection_summary(
                fold)
            report["introspection"]["metrics_fold"] = fold.get("path")
            jr.write_report(report)
        except Exception:  # noqa: BLE001
            logger.warning("couldn't fold campaign metrics",
                           exc_info=True)
        # fold the merged trace's phase spans into the idle-bubble
        # ledger (byte-deterministic bubble_ledger.json) and put the
        # attribution headline on the report next to the padding /
        # duty-cycle numbers. Needs the merged trace; contained the
        # same way
        try:
            from ..obs import bubbles as obs_bubbles
            ledger = obs_bubbles.fold_campaign(campaign_id)
            if ledger.get("episodes"):
                report.setdefault("introspection", {})
                report["introspection"]["bubbles"] = {
                    k: ledger.get(k)
                    for k in ("device_s", "idle_s", "attributed_s",
                              "attribution_frac", "residual_s",
                              "path")}
                jr.write_report(report)
        except Exception:  # noqa: BLE001
            logger.warning("couldn't fold the bubble ledger",
                           exc_info=True)
        jr.write_meta({**(jr.load_meta() or {}),
                       "status": "aborted" if aborted else "complete",
                       "updated": store.local_time()})
        if capacity_plan is not None:
            # the prediction oracle: predicted (model, bucket) shapes
            # vs what the campaign actually compiled -- worker
            # processes report through the persistent ledger, the
            # coordinator through its own noted set. CONTAINED: a
            # crashing oracle costs the report block, nothing else
            try:
                from ..analysis import capplan
                actual = compile_cache.noted_keys() - cap_noted_before
                if led is not None:
                    actual |= set(led.refresh()) - cap_led_before
                # cap_led_before = shapes on disk BEFORE the run: a
                # worker using one warm leaves no campaign-scoped
                # evidence (the ledger records misses only), so the
                # oracle reports it "warm", never "missed"
                report["capacity"] = capplan.report_section(
                    capacity_plan, actual,
                    path=store.campaign_path(campaign_id,
                                             capplan.PLAN_FILE),
                    warm_keys=cap_led_before)
                jr.write_report(report)
            except Exception:  # noqa: BLE001 - oracle is contained
                logger.warning("capacity oracle crashed (contained)",
                               exc_info=True)
        if fleetlint != "off":
            # the control-plane audit: replay everything this campaign
            # just journaled/traced against the protocol's invariants.
            # CONTAINED like searchplan: findings (and auditor
            # crashes) are reported in report.json and the FL
            # artifact, never allowed to flip a cell outcome or the
            # campaign exit code
            try:
                from ..analysis import fleetlint as flint
                from ..analysis.diagnostics import run_analyzer
                fa = None

                def _run_audit():
                    nonlocal fa
                    fa, diags_ = flint.audit(campaign_id)
                    return diags_

                run_analyzer("fleetlint", _run_audit)
                report["fleet_analysis"] = {
                    "counts": fa["counts"],
                    "checks": fa["checks"],
                    "path": fa.get("path"),
                }
                jr.write_report(report)
            except Exception:  # noqa: BLE001 - audit is contained
                logger.warning("fleetlint audit of campaign %s "
                               "crashed (contained)", campaign_id,
                               exc_info=True)
        if hard_abort is not None:
            raise hard_abort
        return report
    finally:
        # always: stop serving this campaign's live gauges and stop
        # the journal flusher threads, whatever path exits. On the
        # happy path the dumps above already closed the journals
        # (remove=True) and these are no-ops; on an exceptional exit
        # the journal FILES are kept -- they are the crash evidence.
        fservice.unregister_metrics_source(metrics_source)
        tr.close_journal()
        reg.close_journal()
