"""Coordinator HA: the coordinator role as a leased, failover-able
identity carried through the campaign journal.

The fleet already survives every fault it injects at WORKERS (dead
processes, wedged transports, torn syncs) because worker ownership is
a journaled lease. The coordinator itself was the last single point of
failure: kill it and the campaign is dead until a human runs
``--resume``. This module closes that by making the coordinator role
just another lease, recoverable from the artifacts the system already
writes:

* **The active coordinator** periodically appends a
  ``{"event": "coordinator-lease", "epoch": N}`` record to
  ``cells.jsonl`` (`CoordinatorLease`, renewing through
  ``robust.HeartbeatLoop``). The record is stamped with the journal's
  ``writer: host:pid`` identity like every other append, so the
  fleetlint single-writer oracle (FL004) and the new chain audit
  (FL016) can replay the whole handoff from the journal alone.
* **Standbys** (`Standby`; ``campaign --standby``, or a second host
  pointed at a shared/synced store) tail the journal READ-ONLY and
  detect lease expiry. Detection is *arrival*-based, not stamp-based:
  the standby times, on its own monotonic clock, how long the journal
  has gone without growing -- so a coordinator whose wall clock is
  hours behind (stale-looking stamps) is never falsely fenced while
  its renewals keep landing. The wall-clock stamps are only consulted
  as a second condition, adjusted by the observed future-skew bound
  (records stamped ahead of the standby's clock prove the
  coordinator's clock runs ahead by at least that much -- the same
  one-sided bound the PR 10 clock handshake uses for workers), so a
  dead coordinator with an AHEAD clock is still detected.
* **Fencing.** On expiry the standby appends a
  ``{"event": "coordinator-takeover", "epoch": N+1, "prev-epoch": N}``
  record naming the expired predecessor lease and writer. Appends are
  line-atomic, so when two standbys race, the journal itself
  serializes them: the FIRST takeover record claiming a given
  predecessor epoch wins (`coordinator_state`), the loser recognizes
  on re-read that the winning record's unique ``fence-id`` is not its
  own (writer identity alone cannot distinguish two standbys sharing
  one process) and goes back to tailing. The winner then
  resumes the campaign through the existing ``--resume`` path (which
  already tolerates torn tails, re-syncs artifacts from workers no
  longer in the fleet list, and skips terminal cells).
* **Zombie fencing.** Every journal append by an HA coordinator is
  stamped with its epoch (CampaignJournal.epoch), every cell spec
  carries ``coordinator-epoch``, and the dispatcher's terminal-guard
  re-checks the journal before appending an outcome: a superseded
  coordinator coming back from a pause finds the takeover record,
  refuses its own late appends, and aborts. The un-closable race --
  a stale append landing in the instant between the takeover record
  and the zombie's next check -- is exactly what FL016 proves post
  hoc from the epoch stamps.

The ``coordinator-kill`` chaos fault (fleet.chaos) SIGKILLs the
active coordinator right after a seeded lease grant, and the e2e soak
asserts the standby completes the campaign with exactly one terminal
record per cell and a clean FL004/FL007/FL016 audit.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time

from .. import robust, store
from ..campaign.journal import CampaignJournal

logger = logging.getLogger(__name__)

__all__ = ["LEASE_EVENT", "TAKEOVER_EVENT",
           "DEFAULT_COORDINATOR_LEASE_S", "DEFAULT_TAKEOVER_GRACE_S",
           "RENEW_FRACTION", "coordinator_state", "current_epoch",
           "last_lease", "fence", "CoordinatorLease", "Standby"]

LEASE_EVENT = "coordinator-lease"
TAKEOVER_EVENT = "coordinator-takeover"

#: default coordinator-lease TTL (seconds): how long the journal may
#: go quiet before standbys may fence. Deliberately much shorter than
#: the cell lease -- coordinator renewals are cheap appends, cells are
#: whole test runs
DEFAULT_COORDINATOR_LEASE_S = 15.0

#: extra quiet time a standby waits past the lease TTL before fencing
DEFAULT_TAKEOVER_GRACE_S = 5.0

#: the active coordinator renews every ``lease_s / RENEW_FRACTION``
#: seconds, so a single dropped renewal never looks like death
RENEW_FRACTION = 3.0

#: per-process fence-attempt sequence: combined with the journal's
#: ``writer`` (host:pid) it makes every takeover record's ``fence-id``
#: globally unique, so a fence can recognize its OWN record on re-read
#: even when two standbys share a process identity (threads)
_FENCE_SEQ = itertools.count()


def _as_int(v):
    return int(v) if isinstance(v, int) and not isinstance(v, bool) \
        else None


def last_lease(records):
    """The newest coordinator-lease record, or None (pre-HA journal)."""
    for rec in reversed(list(records or [])):
        if rec.get("event") == LEASE_EVENT:
            return rec
    return None


def coordinator_state(records):
    """Fold the journal's HA events into the authoritative
    ``(epoch, writer)`` pair -- ``(0, None)`` for a pre-HA journal.

    Epoch claims are monotone: a coordinator-lease only establishes a
    NEW epoch (renewals and zombie re-claims of an old epoch change
    nothing), and the FIRST takeover record claiming a given
    predecessor epoch wins -- later records for the same predecessor
    are losing fence attempts from a standby race, benign as long as
    the loser stands down (FL016 checks that it did)."""
    epoch, writer = 0, None
    taken = set()
    for rec in records or []:
        ev = rec.get("event")
        if ev == LEASE_EVENT:
            e = _as_int(rec.get("epoch"))
            if e is not None and e > epoch:
                epoch, writer = e, rec.get("writer")
        elif ev == TAKEOVER_EVENT:
            prev = _as_int(rec.get("prev-epoch"))
            if prev is not None and prev in taken:
                continue            # a losing fence attempt
            e = _as_int(rec.get("epoch"))
            if e is not None and e > epoch:
                if prev is not None:
                    taken.add(prev)
                epoch, writer = e, rec.get("writer")
    return epoch, writer


def current_epoch(records):
    """The journal's current coordinator epoch (0 = pre-HA)."""
    return coordinator_state(records)[0]


def fence(journal, reason="lease-expired", forced=False,
          skew_allowance_s=None, expect_epoch=None):
    """Fence the current coordinator: append a takeover record naming
    the expired predecessor lease, then re-read the journal to learn
    whether WE won the race. Returns the new epoch on a win, None when
    another standby's takeover landed first.

    ``expect_epoch`` is the compare-and-swap guard: the epoch the
    caller OBSERVED to be expired. If the journal has moved past it by
    the time we re-read (a rival's takeover landed in the window
    between our expiry verdict and our fence), we must NOT fence the
    new, live coordinator -- return None and go back to tailing.

    ``forced`` marks an operator-driven fence (a manual ``--resume``
    of an HA campaign): the kill is out-of-band evidence, so FL016
    skips the stamp-based expiry requirement for it."""
    jr = journal if isinstance(journal, CampaignJournal) \
        else CampaignJournal(journal)
    records = jr.records()
    prev_epoch, prev_writer = coordinator_state(records)
    if expect_epoch is not None and prev_epoch != expect_epoch:
        logger.warning(
            "coordinator takeover abandoned: observed epoch %d "
            "expired but the journal is at epoch %d (%r) now",
            expect_epoch, prev_epoch, prev_writer)
        return None
    lease = last_lease(records)
    epoch = prev_epoch + 1
    rec = {"event": TAKEOVER_EVENT, "epoch": epoch,
           "prev-epoch": prev_epoch, "prev-writer": prev_writer,
           "reason": str(reason), "t": store.local_time(),
           "fence-id": f"{jr.writer}#{next(_FENCE_SEQ)}"}
    if lease is not None:
        rec["prev-lease-t"] = lease.get("t")
        if lease.get("lease-s") is not None:
            rec["lease-s"] = lease.get("lease-s")
    if forced:
        rec["forced"] = True
    if skew_allowance_s is not None:
        rec["skew-allowance-s"] = round(float(skew_allowance_s), 3)
    jr.append_event(rec)
    # The journal's line-atomic appends serialized the race: the FIRST
    # takeover record claiming our predecessor epoch is the winner the
    # fold credits (coordinator_state's ``taken`` set). Match it by
    # fence-id, not writer -- two standbys in one process share the
    # host:pid writer identity, and the fence must still stand down.
    for got in jr.records():
        if got.get("event") == TAKEOVER_EVENT \
                and _as_int(got.get("prev-epoch")) == prev_epoch:
            if got.get("fence-id") == rec["fence-id"]:
                logger.warning("coordinator takeover: epoch %d -> %d "
                               "(fenced %r, %s)", prev_epoch, epoch,
                               prev_writer, reason)
                return epoch
            logger.warning("coordinator takeover lost: %r won epoch %s",
                           got.get("writer"), got.get("epoch"))
            return None
    return None  # append did not land (unreachable with a sane journal)


class CoordinatorLease:
    """The ACTIVE coordinator's side of the role lease: renew the
    journaled coordinator-lease on a heartbeat, and discover fencing.

    Each renewal first re-reads the journal: a takeover record with a
    higher epoch (or this epoch under a foreign writer -- a lost
    standby race) flips the fenced flag, stops renewing, and fires
    ``on_fenced`` exactly once, which the dispatcher wires to its
    abort latch. ``fenced(refresh=True)`` is the terminal-guard's
    check: re-read the journal at the last possible moment before an
    outcome append."""

    def __init__(self, journal, *, lease_s=DEFAULT_COORDINATOR_LEASE_S,
                 epoch=1, renew_s=None, on_fenced=None, registry=None,
                 tracer=None):
        self.jr = journal
        self.lease_s = float(lease_s)
        self.epoch = int(epoch)
        self.renew_s = float(renew_s) if renew_s is not None \
            else max(self.lease_s / RENEW_FRACTION, 0.2)
        self.on_fenced = on_fenced
        self.registry = registry
        self.tracer = tracer
        self._fenced = threading.Event()
        self._fenced_by = None
        self._notified = False
        self._lock = threading.Lock()
        self._loop = None

    @property
    def fenced_by(self):
        """The ``(epoch, writer)`` that superseded us, or None."""
        return self._fenced_by

    def fenced(self, refresh=False):
        """Whether this coordinator's epoch has been superseded.
        ``refresh`` re-reads the journal (the terminal-guard path);
        without it only the cached flag (updated every renewal) is
        consulted."""
        if self._fenced.is_set():
            return True
        if refresh:
            self._check(coordinator_state(self.jr.records()))
        return self._fenced.is_set()

    def _check(self, state):
        epoch, writer = state
        if epoch > self.epoch or (epoch == self.epoch
                                  and writer not in (None,
                                                     self.jr.writer)):
            with self._lock:
                first = not self._fenced.is_set()
                self._fenced.set()
                self._fenced_by = (epoch, writer)
                notify = first and not self._notified
                if notify:
                    self._notified = True
            if notify:
                logger.warning(
                    "coordinator epoch %d fenced: epoch %d held by %r "
                    "took over", self.epoch, epoch, writer)
                if self.registry is not None:
                    try:
                        self.registry.inc("fleet.coordinator_fenced")
                    except Exception:  # noqa: BLE001 - telemetry only
                        pass
                if self.on_fenced is not None:
                    try:
                        self.on_fenced((epoch, writer))
                    except Exception:  # noqa: BLE001 - contained
                        logger.warning("on_fenced callback crashed",
                                       exc_info=True)

    def renew(self):
        """One heartbeat: re-check the journal, then append the lease
        renewal. Returns False once fenced (the loop's stop signal)."""
        if self.fenced(refresh=True):
            return False
        self.jr.append_event({"event": LEASE_EVENT, "epoch": self.epoch,
                              "lease-s": self.lease_s,
                              "t": store.local_time()})
        if self.registry is not None:
            try:
                self.registry.inc("fleet.coordinator_renewals")
                self.registry.set_gauge("fleet.coordinator_epoch",
                                        self.epoch)
            except Exception:  # noqa: BLE001 - telemetry only
                pass
        return True

    def start(self):
        """Append the claiming renewal synchronously (the journal must
        carry the epoch before any cell lease does), then heartbeat."""
        self.renew()
        self._loop = robust.HeartbeatLoop(
            self.renew, self.renew_s,
            name=f"jepsen coordinator-lease {self.jr.campaign_id}")
        self._loop.start()
        return self

    def stop(self, join_s=5.0):
        if self._loop is not None:
            self._loop.stop(join_s=join_s)


class Standby:
    """The PASSIVE side: tail one campaign's journal read-only, fence
    the coordinator once its lease goes stale, report who won.

    Expiry requires BOTH conditions:

    * **arrival**: the journal has not grown for ``lease_s + grace_s``
      on the standby's own monotonic clock (skew-immune -- a live
      coordinator's renewals keep arriving whatever its wall clock
      says); and
    * **stamps**: the newest coordinator-lease stamp is older than
      ``lease_s + grace_s`` of wall clock, after crediting the
      observed future-skew bound (the largest amount by which any
      record's stamp ran ahead of this process's clock at observation
      time -- a one-sided coordinator-clock-offset estimate in the
      same spirit as the PR 10 worker handshake).

    A campaign whose journal carries no coordinator-lease records
    (HA off) is never fenced -- the standby just waits for its
    meta to finalize."""

    def __init__(self, campaign_id, *, lease_s=None, grace_s=None,
                 poll_s=0.5):
        self.campaign_id = str(campaign_id)
        self._lease_s = lease_s
        self.grace_s = float(grace_s) if grace_s is not None \
            else DEFAULT_TAKEOVER_GRACE_S
        self.poll_s = float(poll_s)
        self._seen = None           # (record_count, last_raw_tail)
        self._last_change = None    # monotonic stamp of last growth
        self._skew_bound = 0.0      # max observed stamp-minus-wall
        self._observed_epoch = 0    # epoch fold at the last poll

    # -- store reads (all read-only) ------------------------------------

    def _meta(self):
        try:
            with open(store.campaign_path(self.campaign_id,
                                          "campaign.json")) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _records(self):
        try:
            return store.load_campaign_records(self.campaign_id)
        except OSError:
            return []

    def lease_s(self, meta=None, lease=None):
        """The coordinator-lease TTL to judge expiry by: explicit
        knob, else the campaign meta's, else the newest lease
        record's own ``lease-s``, else the default."""
        if self._lease_s is not None:
            return float(self._lease_s)
        v = ((meta or {}).get("coordinator-lease-s"))
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        v = (lease or {}).get("lease-s")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        return DEFAULT_COORDINATOR_LEASE_S

    # -- the tail loop --------------------------------------------------

    def poll(self):
        """One observation: returns ``"complete"`` (campaign
        finalized; stand down), ``"expired"`` (fence now), or None
        (keep tailing)."""
        from ..analysis.fleetmodel import parse_t
        meta = self._meta()
        if meta is not None and meta.get("status") in ("complete",
                                                       "aborted"):
            return "complete"
        records = self._records()
        now = time.monotonic()
        wall = time.time()
        fingerprint = (len(records),
                       json.dumps(records[-1], sort_keys=True,
                                  default=str) if records else None)
        if self._seen != fingerprint:
            # the journal moved: the coordinator is alive. Fold the
            # newest stamps into the future-skew bound while we're
            # looking at them
            self._seen = fingerprint
            self._last_change = now
            for rec in records[-5:]:
                t = parse_t(rec.get("t"))
                if t is not None:
                    self._skew_bound = max(self._skew_bound, t - wall)
        lease = last_lease(records)
        self._observed_epoch = current_epoch(records)
        if lease is None:
            return None             # HA off (or not started yet)
        bound = self.lease_s(meta, lease) + self.grace_s
        if self._last_change is None or now - self._last_change < bound:
            return None             # arrival condition not met
        t = parse_t(lease.get("t"))
        if t is not None and (wall - t) + self._skew_bound <= bound:
            return None             # stamps say the lease may be live
        return "expired"

    def fence(self, reason="lease-expired"):
        """Append our takeover record; returns the won epoch or None
        (another standby won, or the journal moved past the epoch we
        judged expired -- go back to tailing either way)."""
        return fence(CampaignJournal(self.campaign_id), reason=reason,
                     skew_allowance_s=self._skew_bound,
                     expect_epoch=self._observed_epoch or None)

    def wait(self, timeout_s=None, sleep=time.sleep):
        """Tail until takeover or completion. Returns ``("takeover",
        epoch)``, ``("complete", None)``, or ``("timeout", None)``.
        A lost fence race resets the tail (the winner's records are
        arriving); a won one hands the campaign to the caller, who
        resumes it via the normal ``--resume`` path with
        ``ha_epoch=epoch``."""
        t0 = time.monotonic()
        while True:
            status = self.poll()
            if status == "complete":
                return ("complete", None)
            if status == "expired":
                epoch = self.fence()
                if epoch is not None:
                    return ("takeover", epoch)
            if timeout_s is not None \
                    and time.monotonic() - t0 >= timeout_s:
                return ("timeout", None)
            sleep(self.poll_s)


def takeover_marker(campaign_id):
    """Path of the chaos coordinator-kill die-once marker (shared by
    dispatch and the bench rung)."""
    return os.path.abspath(
        store.campaign_path(campaign_id, "chaos-coordinator-kill"))
