"""The disk-persistent compile ledger: cross-PROCESS compile-reuse
knowledge under ``store/compile_ledger/``.

``campaign.compile_cache`` already answers "has a shape-identical
search run in this process?" -- the in-memory face of jax's jit cache.
What it cannot see is history: a campaign re-started after a crash, or
two concurrent campaign processes on one host sharing a persistent jax
compilation cache, re-count every shape as a cold miss. This module is
the durable half: every first sighting of a compile plan appends one
JSON line to ``ledger.jsonl``, and ``refresh()`` folds lines appended
by *other* processes into the reader's view, so a shape any process
has planned counts as a hit everywhere afterwards.

Disk discipline matches the campaign journal (``cells.jsonl``):

* appends happen under an ``fcntl`` exclusive lock (concurrent
  *processes* interleave whole lines, never bytes) and are
  flushed+fsynced before the lock drops;
* a process killed mid-append leaves a torn final line; the next
  appender terminates the fragment in place and readers skip it;
* records are never rewritten -- stats land as separate ``"stats"``
  event lines (one per campaign finalize), and ``stats()`` aggregates
  the whole file.

Keys are canonicalized through a JSON round trip before comparison, so
a tuple noted live and the same tuple re-read from disk are equal.

Deliberately dependency-light (store + stdlib): compile_cache imports
this from inside ``note()`` and nothing here may drag the heavy
scheduler/checker chain back in.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from .. import store

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX
    fcntl = None

logger = logging.getLogger(__name__)

__all__ = ["LEDGER_FILE", "JAX_CACHE_DIR", "Ledger", "canon_key",
           "attach", "attached", "detach", "enable_jax_cache",
           "fold_walls"]

LEDGER_FILE = "ledger.jsonl"


def canon_key(engine, key):
    """The canonical (hashable) form of one compile-plan key: what a
    live ``note()`` computes and what a ledger line parses back to
    must be equal, so both go through one JSON round trip."""
    raw = json.loads(json.dumps(list(key), cls=store._Encoder))
    return (str(engine),
            tuple(tuple(x) if isinstance(x, list) else x for x in raw))


class Ledger:
    """One process's handle on the shared on-disk ledger."""

    def __init__(self, dir=None):  # noqa: A002 - mirrors open()
        self.dir = os.path.abspath(dir or store.compile_ledger_path())
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, LEDGER_FILE)
        self._lock = threading.Lock()
        self._offset = 0        # how far refresh() has parsed
        self._keys = set()

    # -- reading --------------------------------------------------------

    def refresh(self):
        """Fold lines other processes appended since the last refresh
        into this handle's key set; returns the full set. A torn final
        line (a writer mid-append, or one that died there) is left
        unparsed -- the offset stays before it, so a later refresh
        picks the completed line up."""
        with self._lock:
            try:
                with open(self.path, "rb") as f:
                    f.seek(self._offset)
                    chunk = f.read()
            except FileNotFoundError:
                return set(self._keys)
            consumed = 0
            for line in chunk.split(b"\n")[:-1]:   # last piece: no \n yet
                consumed += len(line) + 1
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # an interior fragment: a previous writer's torn
                    # tail that a later appender terminated in place
                    logger.warning("skipping torn compile-ledger line")
                    continue
                if isinstance(rec, dict) and "key" in rec:
                    try:
                        self._keys.add(
                            canon_key(rec.get("engine"), rec["key"]))
                    except TypeError:
                        logger.warning("unhashable compile-ledger key "
                                       "skipped: %r", rec)
            self._offset += consumed
            return set(self._keys)

    def keys(self):
        with self._lock:
            return set(self._keys)

    # -- writing --------------------------------------------------------

    def _append(self, rec):
        line = json.dumps(rec, cls=store._Encoder)
        with self._lock:
            with open(self.path, "a+b") as f:
                if fcntl is not None:
                    fcntl.flock(f.fileno(), fcntl.LOCK_EX)
                try:
                    # terminate a torn tail (a writer killed mid-append)
                    # so this record never merges into the fragment
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        if f.read(1) != b"\n":
                            f.write(b"\n")
                    f.write(line.encode() + b"\n")
                    f.flush()
                    try:
                        os.fsync(f.fileno())
                    except OSError:  # pragma: no cover - exotic fs
                        pass
                finally:
                    if fcntl is not None:
                        fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    def record(self, engine, key):
        """Persist one first-sighting (a compile miss). Failures are
        contained: the ledger is bookkeeping, never verdict-bearing."""
        k = canon_key(engine, key)
        try:
            self._append({"engine": k[0], "key": list(k[1]),
                          "pid": os.getpid(), "t": store.local_time()})
        except Exception:  # noqa: BLE001 - telemetry only
            logger.warning("compile-ledger append failed", exc_info=True)
            return
        with self._lock:
            self._keys.add(k)

    def note_stats(self, hits, misses, cold_wall_s=None,
                   warm_wall_s=None):
        """Append one process's hit/miss delta as a stats event (the
        campaign scheduler calls this at finalize), so the persisted
        ledger carries reuse evidence, not just shapes.

        ``cold_wall_s``/``warm_wall_s`` fold the campaign's compile
        wall clock in: total wall of cells that paid a compile (their
        delta had misses) vs cells that rode the caches. Paired with
        the persistent jax compilation cache (`enable_jax_cache`),
        the cold number is what a warm restart should shrink."""
        st = {"hits": int(hits), "misses": int(misses)}
        if cold_wall_s is not None:
            st["cold_wall_s"] = round(float(cold_wall_s), 3)
        if warm_wall_s is not None:
            st["warm_wall_s"] = round(float(warm_wall_s), 3)
        try:
            self._append({"stats": st, "pid": os.getpid(),
                          "t": store.local_time()})
        except Exception:  # noqa: BLE001 - telemetry only
            logger.warning("compile-ledger stats append failed",
                           exc_info=True)

    # -- aggregation ----------------------------------------------------

    def stats(self):
        """Whole-file aggregate: distinct shapes, summed hit/miss
        deltas across every process that ever reported, and the
        contributing pids."""
        shapes, hits, misses, pids = set(), 0, 0, set()
        cold_s, warm_s = 0.0, 0.0
        try:
            with open(self.path, "rb") as f:
                lines = f.read().split(b"\n")
        except FileNotFoundError:
            lines = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if "key" in rec:
                try:
                    shapes.add(canon_key(rec.get("engine"), rec["key"]))
                except TypeError:
                    pass
            st = rec.get("stats")
            if isinstance(st, dict):
                hits += int(st.get("hits") or 0)
                misses += int(st.get("misses") or 0)
                cold_s += float(st.get("cold_wall_s") or 0)
                warm_s += float(st.get("warm_wall_s") or 0)
            if rec.get("pid") is not None:
                pids.add(rec["pid"])
        return {"path": self.path, "shapes": len(shapes),
                "hits": hits, "misses": misses,
                "cold_wall_s": round(cold_s, 3),
                "warm_wall_s": round(warm_s, 3),
                "processes": len(pids)}


def fold_walls(records):
    """``(cold_wall_s, warm_wall_s)`` over campaign cell records: the
    total wall of cells whose compile-cache delta had misses (they
    paid a compile) vs all-hit cells. One definition, shared by the
    scheduler and fleet finalize paths, so the ledger's cold/warm
    evidence can't silently diverge between the two."""
    cold = sum(float(r.get("wall_s") or 0) for r in records
               if (r.get("compile-cache") or {}).get("misses"))
    warm = sum(float(r.get("wall_s") or 0) for r in records
               if r.get("compile-cache")
               and not r["compile-cache"].get("misses"))
    return cold, warm


JAX_CACHE_DIR = "jax_cache"


def enable_jax_cache(cache_dir=None):
    """Point jax's persistent compilation cache at a per-store
    directory (``store/compile_ledger/jax_cache/`` by default), so
    the COMPILES survive process restarts -- the ledger alone only
    makes the hit accounting survive; a restarted campaign still paid
    every XLA compile again. Returns the cache dir, or None when jax
    (or this jax version's knob) isn't available; never raises --
    compile caching is an optimization, not a dependency."""
    path = os.path.abspath(cache_dir
                           or store.compile_ledger_path(JAX_CACHE_DIR))
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        if getattr(jax.config, "jax_compilation_cache_dir", None) \
                != path:
            jax.config.update("jax_compilation_cache_dir", path)
            # small searches compile in well under the 60s default
            # floor; 1s keeps sweep-sized kernels cacheable without
            # persisting every trivial jit
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1)
        return path
    except Exception:  # noqa: BLE001 - optimization only
        logger.warning("couldn't enable the persistent jax "
                       "compilation cache", exc_info=True)
        return None


def attach(dir=None, jax_cache=True):  # noqa: A002 - mirrors Ledger
    """Attach a persistent ledger to ``campaign.compile_cache`` (the
    note() path consults it from then on) and seed the in-memory seen
    set from disk, so shapes compiled by earlier/concurrent processes
    count as hits immediately. Idempotent per directory: re-attaching
    the same directory reuses the live handle (nested campaign runs in
    one process must not reset each other's offsets).

    ``jax_cache=True`` also points jax's persistent compilation cache
    at a sibling directory (`enable_jax_cache`): ledger and compile
    artifacts restart together."""
    from ..campaign import compile_cache
    led = compile_cache.get_ledger()
    target = os.path.abspath(dir or store.compile_ledger_path())
    if jax_cache:
        enable_jax_cache(os.path.join(target, JAX_CACHE_DIR))
    if led is not None and led.dir == target:
        return led
    led = Ledger(target)
    led.refresh()
    compile_cache.set_ledger(led)
    return led


def attached():
    """The currently attached Ledger, or None."""
    from ..campaign import compile_cache
    return compile_cache.get_ledger()


def detach(expected=None):
    """Detach the persistent ledger (in-memory counting continues).
    With ``expected``, detaches only if that handle is still the
    attached one -- overlapping campaigns must not sever a sibling's
    ledger."""
    from ..campaign import compile_cache
    if expected is not None and compile_cache.get_ledger() is not expected:
        return
    compile_cache.set_ledger(None)
