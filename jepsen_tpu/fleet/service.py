"""Checking-as-a-service: the logic behind web.py's ``/api/`` routes.

The web server grew up from a store viewer into a submission API --
external traffic can POST work instead of running the harness locally:

* ``POST /api/check`` -- one history JSON in, one verdict out.
  Pipeline: histlint (malformed histories are a 400 with the
  diagnostics, not a garbage verdict -- the same preconditions the
  offline checker relies on), then the SAME one-engine dispatch the
  streaming monitor uses (``monitor/engine.py check_prefix``), so the
  service's verdict is by construction the offline checker's verdict
  on that history. Keyed ([k, v]-valued) histories split per key like
  ``independent`` does and merge validity the same way.
* ``POST /api/campaigns`` -- a sweep-matrix JSON in, a campaign id
  out; the campaign runs on a background thread through the ordinary
  campaign scheduler (journal, ledger, resume semantics all apply)
  and its status polls at ``GET /api/campaigns/<id>``.
* **Shutdown.** Every submitted campaign's latch chains off one
  service-wide ``robust.AbortLatch``; ``shutdown()`` flips it, so
  stopping the service gracefully aborts (and leaves resumable) every
  campaign it accepted.

* **Admission control.** The service used to trust its callers; now
  every request passes the `Admission` gate first: token authn
  (constant-time compare; planlint PL016 makes a non-loopback bind
  without a token a preflight error), per-caller budgets (concurrent
  checks, queued campaigns, ops/day) with a bounded admission queue
  that sheds load as 429 + Retry-After instead of wedging, and a
  graceful drain on shutdown. Rejected or shed requests never touch
  in-flight work -- a 429 is bookkeeping, not an abort.

* **Cross-tenant batch coalescing.** Admitted ``jax-wgl`` checks used
  to dispatch one device search each, serializing behind the device
  while strangers queued (``service.queue_wait_s`` is exactly that
  wait). P-compositionality (arxiv 1504.00204) makes merging them
  sound: independent histories check independently, so the
  `Coalescer` holds each submission's planner-produced encoded
  segments for a short window (default 25 ms) or until a size cap,
  then feeds segments from DIFFERENT callers as one
  ``keyshard.check_batch_encoded`` call. Batches group on
  ``(model, op-count bucket)`` -- the same pow-2
  ``jax_wgl._n_floor()`` buckets the campaign ledger keys on, so
  shape-identical submissions from strangers hit one compiled search
  (and the persistent jax cache) across tenants. Per-request wall
  deadlines survive the merge: a segment whose request deadline
  passes returns "unknown" to its owner without poisoning
  batchmates, and ANY batcher failure falls back to the solo path
  (verdict containment, the searchplan rule). The
  ``service.coalesce.*`` metric family on ``/api/metrics`` carries
  batches/segments/occupancy next to ``admission.shed_total``, so
  the shed-vs-coalesce crossover under load is visible live.

Transport-level hardening (size limits, JSON errors) lives in
web.Handler; this module is pure request logic so it tests without a
socket.
"""

from __future__ import annotations

import contextlib
import hmac
import logging
import re
import threading
import time

from .. import robust, store
from ..obs import phases as obs_phases

logger = logging.getLogger(__name__)

__all__ = ["MAX_BODY_BYTES", "ApiError", "Admission", "Coalescer",
           "DEFAULT_BUDGETS", "DEFAULT_COALESCE_WINDOW_MS",
           "DEFAULT_COALESCE_MAX_SEGMENTS",
           "authorize", "admission", "configure",
           "configure_coalesce", "coalescer",
           "check_history", "submit_campaign", "campaign_status",
           "latch", "drain", "shutdown", "reset",
           "register_metrics_source", "unregister_metrics_source",
           "metrics_text", "slo_registry", "note_request",
           "endpoint_of"]

#: request-body ceiling enforced by web.Handler BEFORE reading
MAX_BODY_BYTES = 16 << 20

#: device-engine wall budget for one /api/check (seconds); payloads
#: may lower it, never raise it past the cap
CHECK_TIMEOUT_S = 30.0
CHECK_TIMEOUT_CAP_S = 120.0

#: histories larger than this are refused outright: the check is
#: NP-hard and a service must bound the work it accepts
MAX_CHECK_OPS = 200_000


class ApiError(Exception):
    """An HTTP-shaped request failure. ``retry_after`` (seconds)
    becomes a ``Retry-After`` response header -- shed load tells the
    caller when to come back instead of just slamming the door."""

    def __init__(self, status, message, retry_after=None, headers=None,
                 **extra):
        self.status = int(status)
        self.payload = {"error": str(message), **extra}
        self.headers = dict(headers or {})
        if retry_after is not None:
            self.headers["Retry-After"] = str(max(1, int(retry_after)))
        super().__init__(str(message))


# ---------------------------------------------------------------------------
# admission control: authn + per-caller budgets + bounded queue

#: default per-caller budgets. Generous on purpose: a bare viewer on
#: loopback should behave exactly as before; real deployments tighten
#: them via `configure`. ``ops-per-day`` is off (None) by default.
DEFAULT_BUDGETS = {
    "concurrent-checks": 8,   # in-flight /api/check per caller
    "queue-depth": 16,        # callers allowed to WAIT for a slot
    "campaigns": 8,           # queued+running campaigns per caller
    "ops-per-day": None,      # history events accepted per caller/day
}


class Admission:
    """The front door: who may ask, and how much.

    * **Authn.** With tokens configured, every request needs
      ``Authorization: Bearer <token>``; comparison is constant-time
      (`hmac.compare_digest`) so the token can't be sniffed out a
      byte at a time. Without tokens the caller is identified by its
      client address (budgets still apply).
    * **Budgets.** Per caller: at most ``concurrent-checks`` checks in
      flight; up to ``queue-depth`` more may wait (bounded, with a
      wall deadline) and everything past that sheds immediately as
      429 + Retry-After -- the queue is how bursts smooth out, the
      shed is how overload stays an error instead of a wedge.
      ``campaigns`` bounds queued+running submissions; ``ops-per-day``
      is a daily work quota (the check is NP-hard: accepted ops ARE
      the cost).
    * **Drain.** ``drain()`` stops new admissions (503) and wakes
      waiters; in-flight requests and accepted campaigns are
      untouched -- shutdown gets to be graceful because rejection
      never reaches into running work.
    """

    def __init__(self, token=None, tokens=None, budgets=None,
                 queue_wait_s=15.0):
        self.tokens = {str(t): str(n) for t, n in (tokens or {}).items()}
        if token:
            self.tokens.setdefault(str(token), "token")
        self.budgets = dict(DEFAULT_BUDGETS)
        self.budgets.update(budgets or {})
        for k in ("concurrent-checks", "queue-depth", "campaigns",
                  "ops-per-day"):
            v = self.budgets.get(k)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                raise ValueError(f"budget {k!r} must be a "
                                 f"non-negative integer, got {v!r}")
        self.queue_wait_s = float(queue_wait_s)
        self._cond = threading.Condition()
        self._draining = False
        self._callers = {}
        self._shed = 0

    def _shed_one(self, err):
        """Count one shed/refused admission (429/503) and rethrow —
        the ``admission.shed_total`` series /api/metrics exposes."""
        with self._cond:
            self._shed += 1
        raise err

    @property
    def shed_count(self):
        with self._cond:
            return self._shed

    def gauges(self):
        """The live admission state as metric series (the
        ``admission.*`` family /api/metrics renders)."""
        with self._cond:
            return {
                "admission.active_checks": sum(
                    st["active"] for st in self._callers.values()),
                "admission.queue_depth": sum(
                    st["waiting"] for st in self._callers.values()),
                "admission.campaigns": sum(
                    st["campaigns"] for st in self._callers.values()),
                "admission.callers": len(self._callers),
                "admission.draining": int(self._draining),
            }

    def _state(self, caller):
        return self._callers.setdefault(str(caller), {
            "active": 0, "waiting": 0, "day": None, "ops": 0,
            "campaigns": 0})

    def _gc(self, caller):
        """Drop an idle caller's state (lock held). Unauthenticated
        callers are keyed by client address, so without this the
        table grows by one entry per distinct source IP forever --
        a slow leak anyone with rotating addresses could drive on
        purpose. Kept only while something is actually held: a slot
        in flight, a waiter, a live campaign, or today's op spend."""
        caller = str(caller)
        st = self._callers.get(caller)
        if st is None or st["active"] or st["waiting"] \
                or st["campaigns"]:
            return
        if self.budgets.get("ops-per-day") is not None and st["ops"] \
                and st["day"] == int(time.time() // 86400):
            return
        self._callers.pop(caller, None)

    # -- authn ----------------------------------------------------------

    def authorize(self, header=None, client="local"):
        """The caller id for one request, or 401. ``header`` is the
        raw Authorization value (``Bearer <token>`` or the bare
        token); ``client`` identifies unauthenticated callers when no
        token is required."""
        if not self.tokens:
            return str(client or "local")
        tok = str(header or "")
        if tok.lower().startswith("bearer "):
            tok = tok[len("bearer "):].strip()
        matched = None
        for t, name in self.tokens.items():
            # compare EVERY configured token: the loop's timing must
            # not reveal which (if any) prefix-matched
            if hmac.compare_digest(tok.encode(), t.encode()):
                matched = name
        if matched is None:
            raise ApiError(401, "missing or invalid API token",
                           headers={"WWW-Authenticate": "Bearer"})
        return matched

    # -- checks ---------------------------------------------------------

    @contextlib.contextmanager
    def check_slot(self, caller, ops=0):
        """Hold one concurrent-check slot for ``caller`` (queueing up
        to the budget, shedding past it); charges ``ops`` against the
        daily quota on admission."""
        self._admit(str(caller), int(ops))
        try:
            yield
        finally:
            with self._cond:
                self._state(caller)["active"] -= 1
                self._gc(caller)
                self._cond.notify_all()

    def _admit(self, caller, ops):
        deadline = time.monotonic() + self.queue_wait_s
        with self._cond:
            st = self._state(caller)
            quota = self.budgets.get("ops-per-day")

            def check_quota():
                if quota is None:
                    return
                day = int(time.time() // 86400)
                if st["day"] != day:
                    st["day"], st["ops"] = day, 0
                if st["ops"] + ops > quota:
                    nxt = (day + 1) * 86400 - time.time()
                    self._shed_one(ApiError(
                        429, f"daily op quota exhausted "
                             f"({st['ops']}/{quota} used, "
                             f"{ops} requested)",
                        retry_after=min(86400, max(1, nxt))))

            check_quota()
            # a None budget means unlimited, for every key -- the
            # validator admits None, so the checks must too
            limit = self.budgets["concurrent-checks"]
            qdepth = self.budgets["queue-depth"]
            while not self._draining and limit is not None \
                    and st["active"] >= limit:
                left = deadline - time.monotonic()
                if (qdepth is not None and st["waiting"] >= qdepth) \
                        or left <= 0:
                    self._shed_one(ApiError(
                        429, "concurrent check budget exhausted "
                             f"({st['active']} in flight, "
                             f"{st['waiting']} queued)",
                        retry_after=2))
                st["waiting"] += 1
                try:
                    self._cond.wait(timeout=left)
                finally:
                    st["waiting"] -= 1
            if self._draining:
                self._shed_one(ApiError(503, "service is draining",
                                        retry_after=30))
            # cond.wait released the lock, so sibling waiters may
            # have spent the quota meanwhile: re-check before charging
            check_quota()
            st["active"] += 1
            if quota is not None:
                st["ops"] += ops

    # -- campaigns ------------------------------------------------------

    def campaign_slot(self, caller):
        """Claim one campaign slot (released via `campaign_done` when
        the campaign thread finishes); 429 past the budget."""
        with self._cond:
            if self._draining:
                self._shed_one(ApiError(503, "service is draining",
                                        retry_after=30))
            st = self._state(caller)
            limit = self.budgets["campaigns"]
            if limit is not None and st["campaigns"] >= limit:
                self._shed_one(ApiError(
                    429, f"campaign budget exhausted ({limit} "
                         "queued or running)", retry_after=30))
            st["campaigns"] += 1

    def campaign_done(self, caller):
        with self._cond:
            st = self._state(caller)
            st["campaigns"] = max(0, st["campaigns"] - 1)
            self._gc(caller)
            self._cond.notify_all()

    # -- lifecycle ------------------------------------------------------

    def drain(self):
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    @property
    def draining(self):
        with self._cond:
            return self._draining

    def snapshot(self):
        """Per-caller counters (status pages, tests)."""
        with self._cond:
            return {c: dict(st) for c, st in self._callers.items()}


# ---------------------------------------------------------------------------
# cross-tenant batch coalescing: queued /api/check segments from
# different callers merge into one padded device batch

#: how long the first segment of a batch may wait for batchmates
#: before the batch closes anyway (milliseconds)
DEFAULT_COALESCE_WINDOW_MS = 25.0

#: segments per batch past which the batch closes early -- bounds both
#: the device program's key axis and how much one batch failure costs
DEFAULT_COALESCE_MAX_SEGMENTS = 32

#: occupancy histogram buckets: real segments / pow-2 key lanes
COALESCE_OCC_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                        0.875, 1.0)

#: the result a segment's owner reads when its request deadline
#: passes -- ONE shape for the coalesced, solo, and pre-encode budget
#: checks, so verdict folding cannot tell the paths apart (no engine
#: key: the same sentinel serves every engine's exhausted budget)
_DEADLINE_RESULT = {"valid": "unknown",
                    "error": "request timeout budget exhausted"}


class _TxnClosureSpec:
    """The coalescer's stand-in "model" for transactional cycle
    probes: txn tenants queue per (``txn-closure``, pow-2 txn-count
    bucket) exactly like WGL tenants queue per (model, op bucket), and
    one device squaring pass answers the whole batch
    (``cycle.batch_closure_probe``)."""

    name = "txn-closure"


TXN_CLOSURE_SPEC = _TxnClosureSpec()

#: monitored-stream frontier lanes: one lane per model, named
#: "streamlin:<model>" (checker/streamlin.STREAM_LANE_PREFIX --
#: duplicated as a constant so lane ROUTING never imports jax). Stream
#: tenants queue per (lane, pow-2 event bucket) exactly like WGL
#: tenants, and one vmapped fold extends the whole batch's frontiers.
STREAM_LANE_PREFIX = "streamlin:"


class _PendingSegment:
    """One encoded segment waiting in (or delivered by) the batcher.
    ``result`` is read only after ``event`` is set; ``None`` then
    means "fall back to the solo path" (batcher failure / shutdown),
    never a verdict."""

    __slots__ = ("spec", "pair", "deadline", "owner", "enqueued",
                 "event", "result")

    def __init__(self, spec, pair, deadline, owner):
        self.spec = spec
        self.pair = pair
        self.deadline = float(deadline)
        self.owner = str(owner)
        self.enqueued = time.monotonic()
        self.event = threading.Event()
        self.result = None


class Coalescer:
    """The cross-tenant batcher: a coalescing queue plus one daemon
    thread that closes batches and drives the device.

    * **Grouping.** Segments queue per ``(model, op-count bucket)`` --
      the bucket from ``campaign.compile_cache.bucket_for`` (the same
      pow-2 ``jax_wgl._n_floor()`` rule every engine pads with), so
      one giant history can't inflate every batchmate's padding, and
      shape-identical strangers land in ONE compiled search (the
      compile ledger and the persistent jax cache hit across
      tenants).
    * **Closing.** A group's batch closes ``window_s`` after its
      oldest segment enqueued, or immediately at ``max_segments``.
      Batches dispatch on the batcher thread itself, so while one
      batch runs the device, later submissions keep accumulating into
      larger batches -- backpressure turns into occupancy.
    * **Deadlines.** Each segment carries its request's wall
      deadline. A segment already expired at dispatch is answered
      "unknown" without touching the device; the batch's own device
      budget is the LONGEST remaining deadline (capped), so a
      short-deadline tenant times out alone -- `wait` returns its
      "unknown" at its own deadline while batchmates keep running.
    * **Containment.** Any dispatch failure (and shutdown) delivers
      ``None`` to every waiting owner, which re-runs that segment on
      the solo path -- a batcher bug can cost the batching win, never
      a verdict (the searchplan fallback rule).
    """

    def __init__(self, window_s=DEFAULT_COALESCE_WINDOW_MS / 1000.0,
                 max_segments=DEFAULT_COALESCE_MAX_SEGMENTS,
                 planned=None):
        window_s = float(window_s)
        max_segments = int(max_segments)
        if window_s <= 0:
            raise ValueError(f"coalesce window must be positive, "
                             f"got {window_s!r}")
        if max_segments <= 0:
            raise ValueError(f"coalesce segment cap must be positive, "
                             f"got {max_segments!r}")
        self.window_s = window_s
        self.max_segments = max_segments
        self._cond = threading.Condition()
        self._queues = {}       # (model, bucket) -> [_PendingSegment]
        self._planned = {}      # model -> sorted [planned buckets]
        self._stopped = False
        self._thread = None     # started lazily on first submit
        self._batches = 0
        self._segments = 0
        self._lanes = 0
        self._fallbacks = 0
        self._expired = 0
        if planned:
            self.preregister(planned)

    # -- the request side ----------------------------------------------

    def preregister(self, keys):
        """Seed the planned ``(model, bucket)`` shape set from a
        capacity plan (analysis.capplan): a submission whose raw
        pow-2 bucket falls BELOW a planned bucket for its model queues
        on the smallest planned bucket >= it, so first-window
        strangers land in planned (already-compiled, ledger-hitting)
        shapes instead of discovering their own. Rounding only ever
        goes UP -- padding rows are inert, so a coarser bucket is
        always sound -- and models/buckets outside the plan keep the
        raw rule."""
        with self._cond:
            for m, b in keys:
                buckets = set(self._planned.get(str(m)) or ())
                buckets.add(int(b))
                self._planned[str(m)] = sorted(buckets)

    def _bucket_key(self, spec, n_rows):
        from ..campaign import compile_cache
        raw = compile_cache.bucket_for(n_rows)
        with self._cond:
            planned = self._planned.get(spec.name) or ()
        for b in planned:       # sorted ascending: smallest >= raw
            if b >= raw:
                return (spec.name, b)
        return (spec.name, raw)

    def submit(self, spec, e, init_state, deadline, owner="local"):
        """Enqueue one encoded segment; returns the pending handle to
        `wait` on. Raises when the coalescer is stopped (the caller
        then checks solo)."""
        key = self._bucket_key(spec, len(e))
        item = _PendingSegment(spec, (e, init_state), deadline, owner)
        with self._cond:
            if self._stopped:
                raise RuntimeError("coalescer is stopped")
            self._queues.setdefault(key, []).append(item)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="jepsen coalesce batcher")
                self._thread.start()
            self._cond.notify_all()
        return item

    def submit_closure(self, adj, deadline, owner="local"):
        """Enqueue one txn adjacency matrix for a batched cycle probe
        (the txn family's coalescing unit: key
        (``txn-closure``, pow-2 txn-count bucket)). ``wait`` answers
        ``{"cyclic": bool}``, the deadline "unknown", or None = probe
        solo."""
        return self.submit(TXN_CLOSURE_SPEC, adj, None, deadline,
                           owner=owner)

    def wait(self, item):
        """Block until ``item``'s batch delivered or its request
        deadline passed. Returns the engine result dict, the
        deadline "unknown" (same dict the solo path's exhausted
        budget produces), or None = fall back to the solo path."""
        left = item.deadline - time.monotonic()
        if left <= 0 or not item.event.wait(timeout=left):
            return dict(_DEADLINE_RESULT)
        return item.result

    def stats(self):
        """Lifetime batch counters (tests, the bench rung):
        ``occupancy`` is real segments over pow-2 key lanes across
        every dispatched batch."""
        with self._cond:
            return {"batches": self._batches,
                    "segments": self._segments,
                    "lanes": self._lanes,
                    "fallbacks": self._fallbacks,
                    "expired": self._expired,
                    "queued": sum(len(q)
                                  for q in self._queues.values()),
                    "planned": sum(len(v)
                                   for v in self._planned.values()),
                    "occupancy": round(self._segments / self._lanes, 4)
                    if self._lanes else None}

    # -- lifecycle ------------------------------------------------------

    def stop(self, join_s=5.0):
        """Stop accepting and wake every queued segment with the
        solo-fallback sentinel; bounded join on the batcher thread."""
        with self._cond:
            self._stopped = True
            pending = [it for q in self._queues.values() for it in q]
            self._queues.clear()
            t = self._thread
            self._cond.notify_all()
        self._fail(pending)
        if t is not None:
            t.join(timeout=join_s)

    # -- the batcher thread --------------------------------------------

    def _ripe_key(self, now):
        """The ripe group with the OLDEST head segment (not dict
        order: one continuously-busy group must not starve the
        others on the single batcher thread)."""
        best = None
        best_age = -1.0
        for key, q in self._queues.items():
            if q and (len(q) >= self.max_segments
                      or now - q[0].enqueued >= self.window_s):
                age = now - q[0].enqueued
                if age > best_age:
                    best, best_age = key, age
        return best

    def _next_close(self, now):
        return min(q[0].enqueued + self.window_s
                   for q in self._queues.values() if q)

    def _run(self):
        while True:
            with self._cond:
                while not self._stopped \
                        and not any(self._queues.values()):
                    self._cond.wait()
                if self._stopped:
                    return
                now = time.monotonic()
                key = self._ripe_key(now)
                if key is None:
                    self._cond.wait(
                        timeout=max(0.001, self._next_close(now) - now))
                    continue
                q = self._queues[key]
                items = q[:self.max_segments]
                rest = q[self.max_segments:]
                if rest:
                    self._queues[key] = rest
                else:
                    del self._queues[key]
            try:
                self._dispatch(items, bucket=key[1])
            except Exception:  # noqa: BLE001 - thread must survive
                logger.warning("coalesced batch dispatch crashed",
                               exc_info=True)
                self._fail(items)

    def _fail(self, items):
        """Deliver the solo-fallback sentinel to every still-waiting
        member (containment: their owners re-check solo)."""
        undelivered = [it for it in items if not it.event.is_set()]
        if not undelivered:
            return
        with self._cond:
            self._fallbacks += len(undelivered)
        for it in undelivered:
            it.result = None
            it.event.set()
        try:
            slo_registry().inc("service.coalesce.fallbacks",
                               len(undelivered))
        except Exception:  # noqa: BLE001
            logger.warning("coalesce accounting failed", exc_info=True)

    def _dispatch(self, items, bucket=None):
        spec = items[0].spec
        now = time.monotonic()
        live = []
        for it in items:
            if it.deadline <= now:
                # expired while queued: its owner already read (or
                # will read) the deadline "unknown" from wait();
                # don't burn device work on it, don't let its corpse
                # widen the batch
                it.result = dict(_DEADLINE_RESULT)
                it.event.set()
            else:
                live.append(it)
        with self._cond:
            self._expired += len(items) - len(live)
        if not live:
            return
        # the batch's device budget serves its LONGEST deadline: a
        # short-deadline member times out alone in wait(), batchmates
        # keep their shot at a definite verdict
        timeout_s = min(CHECK_TIMEOUT_CAP_S,
                        max(it.deadline for it in live) - now)
        try:
            if spec.name == TXN_CLOSURE_SPEC.name:
                # txn tenants: ONE batched transitive-closure probe
                # answers cyclic-or-not for every member's adjacency
                # matrix (cycle classification stays host-side, and
                # only for members that turn out cyclic)
                from ..cycle import batch_closure_probe
                flags = batch_closure_probe(
                    [it.pair[0] for it in live],
                    n_floor=bucket or 64)
                results = [{"cyclic": bool(f)} for f in flags]
            elif spec.name.startswith(STREAM_LANE_PREFIX):
                # monitored-stream tenants: frontier-extension folds
                # from strangers' streams stack into one compiled
                # dispatch (checker/streamlin.batch_fold regroups by
                # full tensor shape, so a mid-flight frontier grow
                # never mis-stacks a batch)
                from ..checker import streamlin
                results = streamlin.batch_fold(
                    [it.pair[0] for it in live],
                    owners=[it.owner for it in live],
                    e_bucket=bucket)
            else:
                from ..parallel import keyshard
                # pad the batch to its GROUP bucket, not a re-derived
                # one: with capacity-plan pre-registration the group
                # bucket may sit ABOVE every member's raw length, and
                # the whole point is compiling at the planned
                # (ledger-hitting) shape
                results = keyshard.check_batch_encoded(
                    spec, [it.pair for it in live], timeout_s=timeout_s,
                    owners=[it.owner for it in live], n_floor=bucket)
        except Exception:  # noqa: BLE001 - contained per batch
            logger.warning("coalesced batch failed; %d segment(s) "
                           "fall back to the solo path", len(live),
                           exc_info=True)
            self._fail(live)
            return
        for it, r in zip(live, results):
            it.result = r
            it.event.set()
        lanes = 1 << (len(live) - 1).bit_length() if len(live) > 1 else 1
        with self._cond:
            self._batches += 1
            self._segments += len(live)
            self._lanes += lanes
        self._note_batch(spec, live, lanes, now)

    # -- accounting (never verdict-bearing) ----------------------------

    def _note_batch(self, spec, live, lanes, t_dispatch):
        try:
            reg = slo_registry()
            reg.inc("service.coalesce.batches", model=spec.name)
            reg.inc("service.coalesce.segments", len(live),
                    model=spec.name)
            reg.observe("service.coalesce.occupancy",
                        len(live) / lanes,
                        buckets=COALESCE_OCC_BUCKETS)
            reg.observe("service.coalesce.owners",
                        len({it.owner for it in live}),
                        buckets=(1, 2, 4, 8, 16, 32))
            for it in live:
                reg.observe("service.coalesce.wait_s",
                            t_dispatch - it.enqueued,
                            buckets=SLO_BUCKETS_S)
                # the queue wait is also a named phase in the
                # time-attribution plane (obs.phases): idle the bubble
                # ledger books against "wait", not mystery residual
                if spec.name == TXN_CLOSURE_SPEC.name:
                    lane = spec.name
                elif spec.name.startswith(STREAM_LANE_PREFIX):
                    lane = "streamlin-batch"
                else:
                    lane = "jax-wgl-batch"
                obs_phases.note_wait(lane, t_dispatch - it.enqueued,
                                     owner=it.owner)
        except Exception:  # noqa: BLE001
            logger.warning("coalesce accounting failed", exc_info=True)


_lock = threading.Lock()
_latch = None
_admission = None
_campaigns = {}     # campaign id -> {"thread", "latch", "submitted"}
_coalescer = None
_slo = None


# ---------------------------------------------------------------------------
# service SLO metrics: per-endpoint request accounting + the
# verdict-latency / queue-wait histograms the batch-coalescing work is
# gated on (p50/p99 derive from the Prometheus buckets)

#: request/verdict latency buckets, seconds: /api/check spans sub-ms
#: histlint rejections to the 120 s engine cap
SLO_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def slo_registry():
    """The service's own metrics registry (lives independent of any
    run's bound registry: a serve-only process still has SLOs).
    Rendered into ``GET /api/metrics`` alongside everything else."""
    global _slo
    with _lock:
        if _slo is None:
            from ..obs import Registry
            _slo = Registry()
        return _slo


def endpoint_of(path):
    """The SLO label for one /api path: 'check', 'campaigns',
    'campaign-status', 'metrics', or 'other'."""
    clean = str(path).rstrip("/")
    if clean == "/api/check":
        return "check"
    if clean == "/api/campaigns":
        return "campaigns"
    if clean.startswith("/api/campaigns/"):
        return "campaign-status"
    if clean == "/api/metrics":
        return "metrics"
    return "other"


def note_request(endpoint, status, wall_s):
    """One served /api request: counted per {endpoint, status} with a
    request-latency observation, plus a trace span when a tracer is
    bound (fleet coordinators bind one, so request handling lands on
    the campaign timeline). web.Handler calls this for every /api
    response, including the 4xx/5xx ones."""
    try:
        reg = slo_registry()
        reg.inc("service.requests", endpoint=str(endpoint),
                status=str(int(status)))
        reg.observe("service.request_s", float(wall_s),
                    buckets=SLO_BUCKETS_S, endpoint=str(endpoint))
        from .. import obs
        tr = obs.tracer()
        if tr is not None:
            now = tr.now_ns()
            dur = int(float(wall_s) * 1e9)
            tr.complete("service.request", now - dur, dur,
                        cat="service",
                        args={"endpoint": str(endpoint),
                              "status": int(status)})
    except Exception:  # noqa: BLE001 - accounting must not 500 requests
        logger.warning("request accounting failed", exc_info=True)


def _slo_observe(name, value, **labels):
    try:
        slo_registry().observe(name, float(value),
                               buckets=SLO_BUCKETS_S, **labels)
    except Exception:  # noqa: BLE001
        logger.warning("SLO observation failed", exc_info=True)


def configure(token=None, tokens=None, budgets=None,
              queue_wait_s=15.0):
    """(Re)build the service-wide admission gate: the --serve /
    web.serve entry points call this with the operator's token and
    budget knobs. Replacing the gate only affects NEW requests;
    in-flight slots release against the old one harmlessly (its
    counters die with it)."""
    global _admission
    gate = Admission(token=token, tokens=tokens, budgets=budgets,
                     queue_wait_s=queue_wait_s)
    with _lock:
        _admission = gate
    return gate


def admission():
    """The service-wide Admission gate (permissive defaults until
    `configure` is called: no tokens, generous budgets)."""
    global _admission
    with _lock:
        if _admission is None:
            _admission = Admission()
        return _admission


def configure_coalesce(enabled=True, window_ms=None, max_segments=None,
                       planned=None):
    """(Re)build the service-wide cross-tenant batcher. ``enabled``
    False tears it down (every check runs solo, the pre-coalescing
    behavior); ``window_ms``/``max_segments`` default to the module
    constants; ``planned`` pre-registers a capacity plan's
    ``(model, bucket)`` shapes (see `Coalescer.preregister`). Returns
    the new `Coalescer` (or None when disabled).
    Replacing an existing coalescer stops it: its queued segments are
    delivered the solo-fallback sentinel, so in-flight requests
    complete correctly against the OLD configuration's containment
    path rather than wedging."""
    global _coalescer
    new = None
    if enabled:
        w = DEFAULT_COALESCE_WINDOW_MS if window_ms is None \
            else float(window_ms)
        m = DEFAULT_COALESCE_MAX_SEGMENTS if max_segments is None \
            else int(max_segments)
        new = Coalescer(window_s=w / 1000.0, max_segments=m,
                        planned=planned)
    with _lock:
        old = _coalescer
        _coalescer = new
    if old is not None:
        old.stop()
    return new


def coalescer():
    """The service-wide Coalescer, or None while coalescing is off
    (the default for direct `check_history` callers; ``web.serve``
    turns it on unless told otherwise)."""
    with _lock:
        return _coalescer


def authorize(header=None, client="local"):
    """Module-level convenience: the caller id for one request, or
    401 (web.Handler calls this before routing)."""
    return admission().authorize(header, client=client)


def drain():
    """Stop admitting new requests (503 + Retry-After); in-flight
    requests and accepted campaigns keep running."""
    admission().drain()


def latch():
    """The service-wide abort latch (created on first use)."""
    global _latch
    with _lock:
        if _latch is None:
            _latch = robust.AbortLatch()
        return _latch


def shutdown(reason="service-shutdown", join_s=10.0):
    """Graceful stop: drain admission first (new requests shed as
    503, waiters wake), then honor the shared AbortLatch so every
    accepted campaign aborts gracefully (journals stay resumable),
    then give their threads a bounded join."""
    drain()
    latch().set(reason)
    with _lock:
        threads = [c["thread"] for c in _campaigns.values()]
        coal = _coalescer
    if coal is not None:
        # after the drain: no new submissions arrive, and queued
        # segments fall back to the solo path so in-flight requests
        # still answer correctly while the server winds down
        coal.stop()
    deadline = time.monotonic() + join_s
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))


def reset():
    """Forget service state (tests)."""
    global _latch, _admission, _slo, _coalescer
    with _lock:
        coal = _coalescer
        _latch = None
        _admission = None
        _slo = None
        _coalescer = None
        _campaigns.clear()
        _metrics_sources.clear()
    if coal is not None:
        coal.stop()


# ---------------------------------------------------------------------------
# GET /api/metrics: Prometheus text exposition

_metrics_sources = {}


def register_metrics_source(name, fn):
    """Register a live metrics provider for ``GET /api/metrics``.
    ``fn()`` returns an obs.Registry or a structured section dict
    (see obs.metrics.render_prometheus) — the fleet dispatcher
    registers its lease-table/queue gauges here for the duration of a
    campaign. Returns the name (pass to `unregister_metrics_source`)."""
    with _lock:
        _metrics_sources[str(name)] = fn
    return str(name)


def unregister_metrics_source(name):
    with _lock:
        _metrics_sources.pop(str(name), None)


def _ledger_section():
    """The compile-ledger / persistent-jax-cache family: cross-process
    hit/miss counts plus the cold/warm compile wall split the jax
    cache's warm restarts shrink."""
    from . import ledger as fledger
    led = fledger.attached()
    if led is None:
        return None
    st = led.stats()
    return {"counters": {"ledger.hits": st.get("hits", 0),
                         "ledger.misses": st.get("misses", 0)},
            "gauges": {"ledger.shapes": st.get("shapes", 0),
                       "ledger.processes": st.get("processes", 0),
                       "ledger.cold_wall_s": st.get("cold_wall_s", 0.0),
                       "ledger.warm_wall_s": st.get("warm_wall_s",
                                                    0.0)}}


def metrics_text():
    """The ``GET /api/metrics`` body: EVERY live obs Registry (each
    in-process run/campaign with an open bind scope — concurrent
    campaign cells expose distinct {campaign, cell}-labelled series,
    including the device searches' live explored/frontier progress
    gauges mid-search), every registered source (fleet dispatch
    gauges), the service's own SLO registry (per-endpoint request
    counts, verdict-latency and queue-wait histograms), the admission
    gate's live state, and the compile-ledger aggregate — rendered in
    the Prometheus text exposition format. Sources that fail are
    skipped, never 5xx'd: a metrics scrape must not depend on every
    subsystem being healthy (that is what it is for)."""
    from .. import obs

    sections = list(obs.live_registries())
    with _lock:
        sources = list(_metrics_sources.items())
    for name, fn in sources:
        try:
            section = fn()
            if isinstance(section, (list, tuple)):
                sections.extend(s for s in section if s is not None)
            elif section is not None:
                sections.append(section)
        except Exception:  # noqa: BLE001 - scrape over perfection
            logger.warning("metrics source %s failed", name,
                           exc_info=True)
    adm = admission()
    sections.append({"gauges": adm.gauges(),
                     "counters": {"admission.shed_total":
                                  adm.shed_count}})
    sections.append(slo_registry())
    try:
        led = _ledger_section()
        if led is not None:
            sections.append(led)
    except Exception:  # noqa: BLE001
        logger.warning("ledger metrics section failed", exc_info=True)
    from ..obs import render_prometheus
    return render_prometheus(sections)


# ---------------------------------------------------------------------------
# POST /api/check

def _require(payload, key, types, what):
    v = payload.get(key)
    if not isinstance(v, types):
        raise ApiError(400, f"{key!r} must be {what}")
    return v


def _split_keyed(hist):
    """Per-key subhistories of an [k, v]-valued history, mirroring
    independent.subhistory (each key checks alone; P-compositionality
    is what makes the split sound). JSON has no tuple type, so every
    2-element list value is coerced to an independent.Tuple first --
    the caller opted into keyed semantics, so that reading is the
    declared one."""
    from .. import independent
    coerced = []
    for op in hist:
        v = op.get("value")
        if isinstance(v, (list, tuple)) and len(v) == 2 \
                and not independent.is_tuple(v):
            op = dict(op)
            op["value"] = independent.tuple_(v[0], v[1])
        coerced.append(op)
    hist = coerced
    keys = independent.history_keys(hist)
    if not keys:
        raise ApiError(400, "keyed check requested but no op carries "
                            "an [key, value] tuple value")
    return {k: independent.subhistory(k, hist) for k in keys}


def check_history(payload, caller="local"):
    """The /api/check pipeline; returns the response dict or raises
    ApiError. Payload keys: ``history`` (list of op maps, required),
    ``model`` (name, default cas-register), ``engine`` (jax-wgl /
    linear / wgl, default jax-wgl), ``keyed`` (bool), ``init-ops``,
    ``timeout-s``. ``caller`` is the `authorize`-d identity the
    admission gate budgets against."""
    if not isinstance(payload, dict):
        raise ApiError(400, "request body must be a JSON object")
    hist = _require(payload, "history", list, "a list of op maps")
    if len(hist) > MAX_CHECK_OPS:
        raise ApiError(413, f"history has {len(hist)} events; this "
                            f"service accepts at most {MAX_CHECK_OPS}")
    # admission: one concurrent-check slot per caller for the whole
    # pipeline (the check is NP-hard; accepted events ARE the cost, so
    # the history length is what the daily quota charges). SLO
    # accounting brackets it: queue wait is the slot-acquisition wall
    # (the signal the batch-coalescing work needs — queued strangers
    # are the coalescing opportunity), verdict latency the whole
    # admission-to-verdict request wall
    t0 = time.monotonic()
    with admission().check_slot(caller, ops=len(hist)):
        _slo_observe("service.queue_wait_s", time.monotonic() - t0,
                     endpoint="check")
        out = _check_admitted(payload, hist, caller=caller)
    _slo_observe("service.verdict_latency_s", time.monotonic() - t0,
                 endpoint="check", valid=str(out.get("valid")))
    return out


def _certify_one(spec, slot, payload):
    """Certify one (sub)history's merged result ("_certify" stash) and
    return the response summary. Differential sampling is off on the
    service path (a replay per request would double device load); the
    witness replay + invalid cross-check are the cheap, bounded
    parts."""
    from ..analysis import certify
    stash = slot.pop("_certify", None)
    if stash is None:
        return {"certified": False}
    merged, client = stash
    cert, diags = certify.certify_with_diagnostics(
        spec, client, merged, samples=0, differential=False,
        init_ops=payload.get("init-ops"))
    return {"certified": True, "verdict": cert["verdict"],
            "counts": cert["counts"], "checks": cert["checks"],
            "diagnostics": cert["diagnostics"]}


def _certify_response(spec, out, payload):
    """The "certify": true response block, over the single submission
    or folded across a keyed submission's per-key results. Contained:
    a certifier crash reports itself instead of failing the check."""
    try:
        if "keys" in out:
            per_key = {k: _certify_one(spec, slot, payload)
                       for k, slot in sorted(out["keys"].items())}
            counts = {}
            for s in per_key.values():
                for sev, n in (s.get("counts") or {}).items():
                    counts[sev] = counts.get(sev, 0) + n
            return {"certified": True, "counts": counts,
                    "keys": per_key}
        return _certify_one(spec, out, payload)
    except Exception:  # noqa: BLE001 - contained, never verdict-bearing
        logger.warning("/api/check certification crashed",
                       exc_info=True)
        # the stashes hold ndarray-bearing results: never let one
        # leak into the JSON response
        for slot in [out] + list((out.get("keys") or {}).values()):
            if isinstance(slot, dict):
                slot.pop("_certify", None)
        return {"certified": False, "error": "certification crashed"}


def _check_txn_admitted(payload, hist, caller="local"):
    """The ``"family": "txn"`` /api/check pipeline: host-side
    dependency inference, a (coalesced) device cycle probe, and
    offline Adya classification only for histories that earn it.
    Payload keys: ``workload`` (append / wr), ``anomalies`` (requested
    class names), ``realtime`` / ``process`` (edge flags),
    ``skew-bound`` (ns; gates realtime edges), ``certify``,
    ``coalesce``."""
    from ..cycle import (DEFAULT_ANOMALIES, PROCESS_ANOMALIES,
                         transitive_closure)
    from ..monitor import engine as mengine

    workload = payload.get("workload", "append")
    if workload not in mengine.TXN_WORKLOADS:
        raise ApiError(400, f"unknown txn workload {workload!r}; "
                            f"known: {list(mengine.TXN_WORKLOADS)}")
    known = set(DEFAULT_ANOMALIES) | set(PROCESS_ANOMALIES)
    anomalies = payload.get("anomalies")
    if anomalies is not None:
        if not isinstance(anomalies, (list, tuple)) \
                or not all(isinstance(a, str) for a in anomalies):
            raise ApiError(400, "'anomalies' must be a list of "
                                "anomaly-class names")
        bad = sorted(set(anomalies) - known)
        if bad:
            raise ApiError(400, f"unknown anomaly class(es) {bad}; "
                                f"known: {sorted(known)}")
    for key in ("realtime", "process"):
        if key in payload and not isinstance(payload[key], bool):
            raise ApiError(400, f"{key!r} must be a boolean")
    skew = payload.get("skew-bound", 0)
    if not isinstance(skew, (int, float)) or isinstance(skew, bool) \
            or skew < 0:
        raise ApiError(400, "'skew-bound' must be a non-negative "
                            "number (history time units)")
    if not isinstance(payload.get("coalesce", True), bool):
        raise ApiError(400, "'coalesce' must be a boolean")
    if not isinstance(payload.get("certify", False), bool):
        raise ApiError(400, "'certify' must be a boolean")
    opts = {"anomalies": tuple(anomalies) if anomalies
            else DEFAULT_ANOMALIES,
            "realtime": payload.get("realtime", True),
            "process": payload.get("process", False),
            "skew-bound": int(skew)}
    t0 = time.monotonic()
    timeout_s = min(float(payload.get("timeout-s") or CHECK_TIMEOUT_S),
                    CHECK_TIMEOUT_CAP_S)
    deadline = t0 + timeout_s
    from .. import history as jhistory
    hist = jhistory.index([dict(o) for o in hist])
    try:
        if workload == "wr":
            from ..cycle import wr as cycle_wr
            graph, found, oks, _garbage = cycle_wr.infer(hist, opts)
        else:
            from ..cycle import append as cycle_app
            graph, found, oks = cycle_app.infer(
                hist, opts["anomalies"], realtime=opts["realtime"],
                process=opts["process"], skew_bound=opts["skew-bound"])
    except ApiError:
        raise
    except Exception as exc:  # noqa: BLE001 - bad input, not a 500
        logger.warning("/api/check txn inference failed", exc_info=True)
        raise ApiError(422, f"txn history could not be inferred: "
                            f"{exc!r}") from None
    suspicious = set(found) - {"garbage-read"}
    garbage = found.get("garbage-read") or []
    coalesced = None
    cyclic = None
    if not suspicious:
        adj = graph.adj > 0
        coal = coalescer()
        if coal is not None and payload.get("coalesce", True) \
                and len(adj):
            try:
                item = coal.submit_closure(adj, deadline, owner=caller)
            except Exception:  # noqa: BLE001 - stopped/replaced
                logger.warning("closure coalesce submit failed; "
                               "probing solo", exc_info=True)
            else:
                r = coal.wait(item)
                if isinstance(r, dict) and "cyclic" in r:
                    cyclic = bool(r["cyclic"])
                    coalesced = {"txns": len(adj)}
        if cyclic is None and len(adj):
            closure = transitive_closure(adj)
            cyclic = bool(closure.diagonal().any())
        cyclic = bool(cyclic)
    if suspicious or cyclic:
        # the offline engine owns every classified verdict: witnesses,
        # anomaly names, and requested-subset semantics come from the
        # same code the offline checker runs
        res = mengine.check_txn_prefix(hist, workload, opts)
    elif garbage:
        res = {"valid": "unknown", "anomaly_types": [],
               "anomalies": {"garbage-read": garbage}}
    else:
        res = {"valid": True, "anomaly_types": [], "anomalies": {}}
    out = {"valid": res.get("valid"),
           "family": "txn", "workload": workload,
           "model": f"txn-{workload}", "engine": f"txn-{workload}",
           "anomaly_types": list(res.get("anomaly_types") or ()),
           "anomalies": res.get("anomalies") or {},
           "txns": len(oks), "events": len(hist),
           **({"error": str(res["error"])} if res.get("error")
              else {}),
           **({"coalesced": coalesced} if coalesced else {}),
           "wall_s": round(time.monotonic() - t0, 3)}
    if payload.get("certify", False):
        try:
            from ..analysis import certify
            checks = []
            diags = certify.certify_cycle_witness(
                res, hist, workload=workload, opts=opts, checks=checks)
            sev = {"error": 0, "warning": 0, "info": 0}
            for d in diags:
                sev[d.severity] = sev.get(d.severity, 0) + 1
            out["certify"] = {
                "certified": True,
                "verdict": res.get("valid"),
                "counts": sev,
                "checks": checks,
                "diagnostics": [{"code": d.code,
                                 "severity": d.severity,
                                 "message": d.message,
                                 "location": d.location}
                                for d in diags]}
        except Exception:  # noqa: BLE001 - contained, never verdict-bearing
            logger.warning("/api/check txn certification crashed",
                           exc_info=True)
            out["certify"] = {"certified": False,
                              "error": "certification crashed"}
    from .. import obs
    obs.inc("fleet.api_checks", valid=str(out.get("valid")),
            family="txn")
    return out


def _check_admitted(payload, hist, caller="local"):
    from ..analysis import histlint, errors as diag_errors
    from ..checker.checkers import Linearizable
    from ..models import model_spec
    from ..monitor import engine as mengine

    family = payload.get("family")
    if family == "txn":
        return _check_txn_admitted(payload, hist, caller=caller)
    if family is not None and family != "wgl":
        raise ApiError(400, f"unknown check family {family!r}; "
                            "known: wgl (default), txn")

    model = payload.get("model", "cas-register")
    try:
        spec = model_spec(str(model))
    except KeyError as e:
        raise ApiError(400, str(e)) from None
    engine = payload.get("engine", "jax-wgl")
    if engine not in mengine.ENGINES:
        raise ApiError(400, f"unknown engine {engine!r}; known: "
                            f"{list(mengine.ENGINES)}")
    timeout_s = payload.get("timeout-s")
    if timeout_s is not None and (not isinstance(timeout_s, (int, float))
                                  or isinstance(timeout_s, bool)
                                  or timeout_s <= 0):
        raise ApiError(400, f"timeout-s must be a positive number, "
                            f"got {timeout_s!r}")
    timeout_s = min(float(timeout_s or CHECK_TIMEOUT_S),
                    CHECK_TIMEOUT_CAP_S)
    if not isinstance(payload.get("coalesce", True), bool):
        raise ApiError(400, f"'coalesce' must be a boolean, got "
                            f"{payload['coalesce']!r}")
    if not isinstance(payload.get("certify", False), bool):
        raise ApiError(400, f"'certify' must be a boolean, got "
                            f"{payload['certify']!r}")
    # proof-carrying verdicts on demand: "certify": true replays the
    # verdict's witness through the pure CPU model and cross-checks
    # invalid verdicts through an independent engine
    # (analysis/certify.py); the summary rides back on the response.
    # Contained: certification can never change the verdict
    certify_on = bool(payload.get("certify", False))
    # cross-tenant coalescing: only the device engine batches (the CPU
    # engines have no key axis); the payload may opt a single request
    # out ("coalesce": false), e.g. to compare against the solo path
    coal = coalescer()
    use_coal = (coal is not None and engine == "jax-wgl"
                and payload.get("coalesce", True))

    # -- histlint: refuse malformed histories with the diagnostics ----
    diags = histlint.lint_history(hist, model_fs=set(spec.f_codes))
    errs = diag_errors(diags)
    if errs:
        raise ApiError(
            400, "history failed histlint",
            diagnostics=[{"code": d.code, "message": d.message,
                          "location": d.location} for d in errs[:20]])

    from .. import history as jhistory
    hist = jhistory.index([dict(o) for o in hist])
    lin = Linearizable(spec, engine,
                       init_ops=payload.get("init-ops"))
    # ONE wall budget for the whole request, not per key: a keyed
    # history with many hard keys must not multiply the cap
    t0 = time.monotonic()
    deadline = t0 + timeout_s

    # the search planner runs on every submission (opt out with
    # "searchplan": false in the payload): sealed quiescent cuts slice
    # each (sub)history into independent segments checked through the
    # same engine dispatch, so huge sequential histories that would
    # blow the one-search budget fit as many small ones
    plan_on = payload.get("searchplan", True)
    from ..analysis import searchplan

    def solo(e, init_state):
        # the non-batched dispatch (and the containment target when
        # the batcher fails a segment): the verdict survives, only
        # the batching win is lost
        left = deadline - time.monotonic()
        if left <= 0:
            return dict(_DEADLINE_RESULT)
        engine_opts = {"timeout_s": left} \
            if engine == "jax-wgl" else None
        return mengine.check_prefix(spec, e, init_state,
                                    engine=engine,
                                    engine_opts=engine_opts)

    def start_one(sub):
        """Phase 1 of one (sub)history's check: plan, encode, and
        SUBMIT every segment before anything waits -- all of this
        request's segments (every key of a keyed submission, and
        every concurrent stranger's) land in the same coalescing
        window instead of paying one window per segment in sequence.
        Returns the phase-2 closure that waits and folds."""
        client = lin.prepare_history(jhistory.client_ops(sub))
        segments = [client]
        seg_seeds = [None]
        plan_meta = None
        n_ops = None
        if plan_on:
            segs, info = searchplan.plan_segments(spec, client)
            if len(segs) > 1:
                segments = [s.events for s in segs]
                seg_seeds = [s.seed for s in segs]
                plan_meta = {"segments": len(segs),
                             "cuts": info["cuts"],
                             "elided": info["elided"]}
                # "ops" keeps its unplanned meaning — the logical ops
                # of the submitted (sub)history, what ONE flat encode
                # would produce — independent of plan shape (seed
                # pairs re-encode per segment) or budget timing
                n_ops = info["rows"] + info["elided"]
        per_seg = []
        pending = []            # (slot, item, e, init_state)
        for seg in segments:
            left = deadline - time.monotonic()
            if left <= 0:
                per_seg.append(dict(_DEADLINE_RESULT))
                continue
            e, init_state = spec.encode(seg)
            if n_ops is None:
                n_ops = len(e)
            if use_coal:
                try:
                    item = coal.submit(spec, e, init_state, deadline,
                                       owner=caller)
                except Exception:  # noqa: BLE001 - stopped/replaced
                    logger.warning("coalescer submit failed; "
                                   "checking solo", exc_info=True)
                else:
                    per_seg.append(None)
                    pending.append((len(per_seg) - 1, item, e,
                                    init_state))
                    continue
            per_seg.append(solo(e, init_state))

        def finish():
            for slot, item, e, init_state in pending:
                r = coal.wait(item)
                per_seg[slot] = r if r is not None \
                    else solo(e, init_state)
            # stamp witness provenance exactly like the offline
            # planned path (checkers._check_planned): the certifier
            # re-certifies each segment against a replanned cut, and
            # the (index, count, seed) triple is part of the proof
            for i, r in enumerate(per_seg):
                w = r.get("witness") if isinstance(r, dict) else None
                if isinstance(w, dict):
                    w["segment"] = {"index": i, "count": len(per_seg),
                                    "seed": seg_seeds[i]}
            # demux back into one per-(sub)history verdict through
            # the same fold the planned offline paths use (worst-wins
            # validity, configs sum, failing segment's witness
            # carried)
            merged = searchplan.merge_segment_results(
                per_seg,
                info={"cuts": plan_meta["cuts"],
                      "elided": plan_meta["elided"]}
                if plan_meta else None,
                engine=engine)
            errs = [str(r["error"]) for r in per_seg
                    if r.get("error")]
            out = {"valid": merged["valid"], "ops": n_ops or 0,
                   "configs_explored": merged["configs_explored"],
                   **({"searchplan": plan_meta} if plan_meta else {}),
                   **({"error": errs[0]} if errs else {})}
            # how many distinct tenants shared this submission's
            # device batches (keyshard stamps batch_owners on
            # searched keys)
            owners = max((int(r.get("batch_owners") or 0)
                          for r in per_seg), default=0)
            if use_coal and owners:
                out["coalesced"] = {"owners": owners}
            if certify_on:
                # raw material for the post-verdict certification
                # below (popped before the response is shaped)
                out["_certify"] = (merged, client)
            return out

        return finish

    def check_one(sub):
        return start_one(sub)()

    try:
        if payload.get("keyed"):
            from ..checker.core import merge_valid
            # start EVERY key before finishing any: all keys'
            # segments share one coalescing window (and one device
            # batch) instead of each key paying its own window
            started = [(str(k), start_one(sub))
                       for k, sub in sorted(_split_keyed(hist).items(),
                                            key=lambda kv: str(kv[0]))]
            per_key = {k: finish() for k, finish in started}
            out = {"valid": merge_valid([r["valid"]
                                         for r in per_key.values()]),
                   "keys": per_key}
        else:
            out = check_one(hist)
    except ApiError:
        raise
    except Exception as exc:  # noqa: BLE001 - bad input, not a 500
        logger.warning("/api/check failed", exc_info=True)
        raise ApiError(422, f"history could not be checked: "
                            f"{exc!r}") from None
    if certify_on:
        out["certify"] = _certify_response(spec, out, payload)
    out.update({"model": spec.name, "engine": engine,
                "events": len(hist),
                "wall_s": round(time.monotonic() - t0, 3),
                "histlint": {"warnings": len(diags) - len(errs)}})
    from .. import obs
    obs.inc("fleet.api_checks", valid=str(out.get("valid")))
    return out


# ---------------------------------------------------------------------------
# POST /api/campaigns + GET /api/campaigns/<id>

#: default base options submitted campaigns build cells from (the demo
#: suite's no-ssh shape); a payload's "options" overlay these
DEFAULT_OPTIONS = {
    "nodes": ["n1"], "concurrency": 1, "ssh": {"dummy?": True},
    "time-limit": 5, "workload": "register",
}

#: option keys a remote payload may NOT override: anything that would
#: point the server's control plane at real hosts or local files.
#: Submitted campaigns ALWAYS run on the dummy remote -- a caller who
#: can POST here must not be able to make this process open SSH
#: connections (or read key files) of its choosing.
PROTECTED_OPTIONS = ("nodes-file", "nodes", "node", "ssh",
                     "ssh-private-key", "leave-db-running?")

_SAFE_ID = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._+=,-]*$")


def _safe_campaign_id(cid):
    """Campaign ids from the wire become filesystem path components
    (store/campaigns/<id>/...): refuse anything that isn't a plain
    token, or a crafted id escapes the store on both read and write."""
    cid = str(cid)
    if not _SAFE_ID.fullmatch(cid) or len(cid) > 200:
        raise ApiError(400, f"invalid campaign id {cid!r}: use "
                            "letters, digits, and ._+=,- only")
    return cid


def submit_campaign(payload, builder=None, caller="local"):
    """Accept a sweep matrix; returns (campaign_id, meta dict). The
    campaign runs on a daemon thread via the ordinary scheduler with a
    latch chained off the service latch. ``caller`` is the
    `authorize`-d identity whose campaign budget the submission
    claims (released when the campaign thread finishes)."""
    from ..campaign import plan as cplan
    from ..campaign import run_cells, CampaignError

    if not isinstance(payload, dict):
        raise ApiError(400, "request body must be a JSON object")
    axes = _require(payload, "axes", dict, "an {axis: [values]} object")
    matrix = {"axes": axes, "base": payload.get("base") or {}}
    try:
        cells_plan, diags = cplan.validate(matrix)
    except cplan.CampaignPlanError as e:
        raise ApiError(400, f"campaign matrix invalid: {e}") from None
    options = dict(DEFAULT_OPTIONS)
    overlay = payload.get("options") or {}
    if not isinstance(overlay, dict):
        raise ApiError(400, "'options' must be an object")
    overlay = {k: v for k, v in overlay.items()
               if k not in PROTECTED_OPTIONS}
    options.update(overlay)
    # belt and braces on top of PROTECTED_OPTIONS: whatever the
    # payload said, a submitted campaign runs on the dummy remote
    options["ssh"] = {"dummy?": True}

    def _pos_int(key):
        v = payload.get(key)
        if v is None:
            return 1
        if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
            raise ApiError(400, f"{key!r} must be a positive integer, "
                                f"got {v!r}")
        return v

    parallel = _pos_int("parallel")
    device_slots = _pos_int("device-slots")
    campaign_id = _safe_campaign_id(payload.get("id") or
                                    "api-" + store.local_time())
    if campaign_id in _campaigns:
        raise ApiError(409, f"campaign {campaign_id!r} already "
                            "submitted")

    from ..fleet.worker import resolve_builder
    build_fn = resolve_builder(builder or "jepsen_tpu.demo:demo_test")
    build_lock = threading.Lock()

    def build(params):
        import random
        o = dict(options)
        o.update(params)
        with build_lock:
            if "seed" in params:
                random.seed(params["seed"])
            return build_fn(o)

    cells = [{"id": c["id"], "group": c["group"],
              "params": c["params"], "build": build}
             for c in cells_plan]
    child = robust.ChainedLatch(parent=latch())
    # claim the caller's campaign-budget slot LAST, after every 4xx
    # has had its chance: a rejected payload must not burn budget
    adm = admission()
    adm.campaign_slot(caller)

    def run():
        try:
            run_cells(cells, campaign_id=campaign_id,
                      parallel=parallel, device_slots=device_slots,
                      latch=child)
        except CampaignError as e:
            logger.warning("submitted campaign %s refused: %s",
                           campaign_id, e)
        except Exception:  # noqa: BLE001 - background thread
            logger.warning("submitted campaign %s crashed",
                           campaign_id, exc_info=True)
        finally:
            adm.campaign_done(caller)

    try:
        t = threading.Thread(target=run, daemon=True,
                             name=f"jepsen api campaign {campaign_id}")
        with _lock:
            _campaigns[campaign_id] = {"thread": t, "latch": child,
                                       "submitted": store.local_time()}
        t.start()
    except BaseException:   # thread never ran: give the slot back
        adm.campaign_done(caller)
        raise
    from .. import obs
    obs.inc("fleet.api_campaigns")
    return campaign_id, {"campaign": campaign_id,
                         "cells": [c["id"] for c in cells_plan],
                         "status-url": f"/api/campaigns/{campaign_id}",
                         "warnings": len(diags)}


def campaign_status(campaign_id):
    """The pollable status body for one campaign (submitted via the
    API or any other way -- the store is the truth)."""
    campaign_id = _safe_campaign_id(campaign_id)
    data = store.load_campaign(campaign_id)
    with _lock:
        sub = _campaigns.get(campaign_id)
    if data is None and sub is None:
        raise ApiError(404, f"unknown campaign {campaign_id!r}")
    meta = (data or {}).get("meta") or {}
    records = store.latest_campaign_records(campaign_id) if data else []
    out = {"campaign": campaign_id,
           "status": meta.get("status") or "submitted",
           "cells-planned": len(meta.get("cells") or []),
           "cells-done": len(records),
           "outcomes": {},
           "records": records}
    for r in records:
        k = str(r.get("outcome"))
        out["outcomes"][k] = out["outcomes"].get(k, 0) + 1
    if data and data.get("report"):
        out["report"] = {k: v for k, v in data["report"].items()
                         if k not in ("cells", "results")}
    return out
