"""Seeded chaos profiles for the fleet's own control plane.

The fleet layer (dispatch/worker/sync/service) claims to survive the
faults it injects into systems under test: dead workers, flaky
transports, torn files, wedged connections. Until this module, those
claims were exercised only by hand-built test fixtures over clean
loopback transports. A `ChaosProfile` turns the "real multi-host soak"
into a reproducible single-machine test: a seeded, deterministic
schedule of

* **exec faults** -- injected ssh-style ``exit-255``s, subprocess
  timeouts, and bounded hangs on the dispatcher's cell execs (the
  lease/steal/strike machinery's diet);
* **sync faults** -- failed and *partial* downloads (a torn copy that
  reports success; the manifest verification in `fleet.sync` must
  catch it) and failed uploads;
* **worker kills** -- scheduled ``kill -9``s riding the worker's
  die-once-marker mechanism, so a chosen cell's first lease dies
  mid-run and the cell is stolen;
* **a torn ledger tail** -- a partial line appended to the persistent
  compile ledger before the campaign starts, exercising the
  torn-tail tolerance for real;
* **a coordinator kill** -- SIGKILL the dispatcher itself right after
  a seeded cell's lease-grant append, leaving a half-run campaign with
  a live lease and a dead coordinator: the `fleet.ha` standby's whole
  reason to exist.

Faults are injected through `control.remotes.FaultyRemote`; this
module only decides *when*. Per-worker schedules derive from
``random.Random(f"{seed}|{worker_id}")`` with per-fault caps, so a
given ``(profile, seed)`` replays the same pattern per worker (caps
are per worker: totals scale with fleet width, and no worker can be
struck past the dispatcher's consecutive-failure retirement bound by
injection alone -- the soak must exercise recovery, not amputation).

CLI: ``--chaos-profile NAME[:SEED]`` (e.g. ``soak:42``); see
``PROFILES`` for the named shapes and doc/fleet.md for the lifecycle.
"""

from __future__ import annotations

import dataclasses
import logging
import random

logger = logging.getLogger(__name__)

__all__ = ["ChaosProfile", "PROFILES", "parse"]


@dataclasses.dataclass(frozen=True)
class ChaosProfile:
    """One seeded fault schedule. Probabilities are per transport op;
    ``*_max`` caps bound how many of each fault ONE worker's transport
    may see (keep the sum of exec-fault caps under the dispatcher's
    ``WORKER_STRIKES`` so injection alone can't retire a worker)."""

    name: str = "custom"
    seed: int = 0
    #: injected exec exit-255s (probability / per-worker cap)
    exec_exit255_p: float = 0.0
    exec_exit255_max: int = 0
    #: injected exec subprocess timeouts
    exec_timeout_p: float = 0.0
    exec_timeout_max: int = 0
    #: injected exec hangs (sleep, then timeout result)
    hang_p: float = 0.0
    hang_max: int = 0
    hang_s: float = 3.0
    #: failed downloads (exit-255 before any byte moves)
    download_fail_p: float = 0.0
    download_fail_max: int = 0
    #: partial downloads (largest file truncated, success reported)
    download_partial_p: float = 0.0
    download_partial_max: int = 0
    #: failed uploads
    upload_fail_p: float = 0.0
    upload_fail_max: int = 0
    #: how many cells get a die-once kill -9 marker
    kills: int = 0
    #: append a torn fragment to the compile ledger at campaign start
    torn_ledger_tail: bool = False
    #: SIGKILL the ACTIVE COORDINATOR right after a seeded cell's
    #: lease grant lands in the journal (once per campaign, die-once
    #: marker): the fleet.ha standby must detect the dead lease, fence
    #: the coordinator, and finish the campaign
    coordinator_kill: int = 0
    #: per-worker wall-clock skew: each struck worker's clock stamps
    #: (the PR-10 handshake legs ``worker-received-epoch`` /
    #: ``worker-result-epoch``) shift by a seeded offset drawn from
    #: [-clock_skew_max_s, +clock_skew_max_s]; ``obs.merge``'s
    #: worker_offsets recovers it, and the txn family's realtime-edge
    #: inference must stay sound under it (skew-bound gating)
    clock_skew_p: float = 0.0
    clock_skew_max_s: float = 0.0

    def with_seed(self, seed):
        return dataclasses.replace(self, seed=int(seed))

    def describe(self):
        """The JSON-able shape journaled into campaign.json so a soak
        is reproducible from its artifacts alone."""
        return dataclasses.asdict(self)

    def faults_for(self, worker_id):
        """The ``faults(kind)`` callable `remotes.FaultyRemote` wants,
        seeded per worker. Candidates draw in a fixed order per kind
        so the schedule depends only on (seed, worker, op index)."""
        rng = random.Random(f"{self.seed}|{worker_id}")
        left = {
            "hang": self.hang_max,
            "exit-255": self.exec_exit255_max,
            "timeout": self.exec_timeout_max,
            "download-fail": self.download_fail_max,
            "download-partial": self.download_partial_max,
            "upload-fail": self.upload_fail_max,
        }

        def draw(key, p):
            # one rng draw per candidate per op, cap or no cap: the
            # schedule must not shift when an earlier cap runs out
            wants = rng.random() < p
            if wants and left[key] > 0:
                left[key] -= 1
                return True
            return False

        def faults(kind):
            if kind == "execute":
                if draw("hang", self.hang_p):
                    return ("hang", self.hang_s)
                if draw("exit-255", self.exec_exit255_p):
                    return "exit-255"
                if draw("timeout", self.exec_timeout_p):
                    return "timeout"
            elif kind == "download":
                if draw("download-fail", self.download_fail_p):
                    return "exit-255"
                if draw("download-partial", self.download_partial_p):
                    return "partial"
            elif kind == "upload":
                if draw("upload-fail", self.upload_fail_p):
                    return "exit-255"
            return None

        return faults

    def skew_for(self, worker_id):
        """This worker's injected wall-clock offset in seconds (0.0
        when unstruck): deterministic in (seed, worker), independent of
        the transport-fault draws."""
        if not self.clock_skew_p or not self.clock_skew_max_s:
            return 0.0
        rng = random.Random(f"{self.seed}|clock-skew|{worker_id}")
        if rng.random() >= self.clock_skew_p:
            return 0.0
        return round(rng.uniform(-self.clock_skew_max_s,
                                 self.clock_skew_max_s), 3)

    def skew_bound_s(self):
        """A sound bound on the pairwise clock disagreement this
        profile can inject: the width of the offset envelope (both
        tails) -- what a txn suite should pass as its skew bound."""
        return 2.0 * float(self.clock_skew_max_s) \
            if self.clock_skew_p and self.clock_skew_max_s else 0.0

    def plan_kills(self, cell_ids):
        """The deterministic set of cells whose FIRST lease kill -9s
        its worker (die-once markers make the second lease run)."""
        ids = sorted(str(c) for c in cell_ids)
        n = min(max(0, int(self.kills)), len(ids))
        if not n:
            return set()
        rng = random.Random(f"{self.seed}|kills")
        return set(rng.sample(ids, n))

    def plan_coordinator_kill(self, cell_ids):
        """The deterministic cell whose lease-grant append is the
        coordinator's last act (dispatch SIGKILLs itself right after
        journaling that grant), or None when this profile doesn't kill
        the coordinator. The first cell (sorted order) is skipped when
        there is any other choice so the kill lands MID-campaign --
        after some cells already ran -- which is the interesting
        takeover case."""
        ids = sorted(str(c) for c in cell_ids)
        if not self.coordinator_kill or not ids:
            return None
        rng = random.Random(f"{self.seed}|coordinator-kill")
        if len(ids) > 1:
            return ids[rng.randrange(1, len(ids))]
        return ids[0]


#: the named shapes ``--chaos-profile`` accepts. "soak" is the CI /
#: bench shape: a couple of exec exit-255s, one hang per worker, one
#: worker kill -9, one partial download, and a torn ledger tail --
#: every recovery path lit, no path pushed past its budget.
PROFILES = {
    "none": ChaosProfile(name="none"),
    "flaky-exec": ChaosProfile(
        name="flaky-exec",
        exec_exit255_p=0.4, exec_exit255_max=2,
        exec_timeout_p=0.2, exec_timeout_max=1),
    "lossy-sync": ChaosProfile(
        name="lossy-sync",
        download_fail_p=0.4, download_fail_max=2,
        download_partial_p=0.4, download_partial_max=2,
        upload_fail_p=0.2, upload_fail_max=1),
    "soak": ChaosProfile(
        name="soak",
        exec_exit255_p=0.5, exec_exit255_max=1,
        hang_p=0.4, hang_max=1, hang_s=2.0,
        download_partial_p=0.5, download_partial_max=1,
        kills=1, torn_ledger_tail=True),
    "coordinator-kill": ChaosProfile(
        name="coordinator-kill", coordinator_kill=1),
    # the txn family's clock soak: every worker's clock skews by up to
    # +/-45s (plus mild exec flakiness so skew composes with retries);
    # RT-edge inference must not fabricate anomalies from it
    "txn-skew": ChaosProfile(
        name="txn-skew",
        clock_skew_p=1.0, clock_skew_max_s=45.0,
        exec_exit255_p=0.2, exec_exit255_max=1),
}


def parse(spec):
    """``"soak"`` / ``"soak:42"`` -> a seeded ChaosProfile (also
    accepts a ready profile and passes it through)."""
    if isinstance(spec, ChaosProfile):
        return spec
    if spec is None:
        return None
    name, sep, seed = str(spec).partition(":")
    if name not in PROFILES:
        raise ValueError(f"unknown chaos profile {name!r}; known: "
                         f"{sorted(PROFILES)}")
    prof = PROFILES[name]
    if sep:
        try:
            prof = prof.with_seed(int(seed))
        except ValueError:
            raise ValueError(f"chaos profile seed {seed!r} should be "
                             "an integer") from None
    return prof


def tear_ledger_tail(ledger):
    """Append a torn (newline-less, unparseable) fragment to the
    persistent compile ledger: the on-disk state a writer killed
    mid-append leaves behind. The ledger's readers/appenders must
    tolerate it; this plants it on purpose."""
    try:
        with open(ledger.path, "ab") as f:
            f.write(b'{"key": ["chaos-torn')
            f.flush()
        logger.warning("chaos: tore the compile-ledger tail (%s)",
                       ledger.path)
    except OSError:  # pragma: no cover - ledger dir missing
        logger.warning("chaos: couldn't tear ledger tail",
                       exc_info=True)
