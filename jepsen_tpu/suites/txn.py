"""Transactional workload suite: the list-append and rw-register
families as first-class, CLI-runnable tests (reference
jepsen/src/jepsen/tests/cycle/append.clj wired the way a consumer
database suite would: a workload registry + clients + nemesis axes).

Histories are transactions over ``jepsen_tpu.txn`` micro-ops::

    {"f": "txn", "value": [["append", 3, 2], ["r", 3, None]]}   # append
    {"f": "txn", "value": [["w", 1, 7], ["r", 1, None]]}        # wr

checked by the ``jepsen_tpu.cycle`` Adya engine and streamed through
the ``family="txn"`` monitor (monitor/txn.py): the first committed
cycle aborts the run while it is still going.

The backing store is an in-process shared map behind one lock
(serializable by construction), with injectable bugs so every anomaly
path is demonstrable end to end:

* ``--bug future-read``  -- every 5th read *predicts* the next append
  (G1c-realtime: the predicted value's eventual writer precedes the
  read in the dependency graph, realtime orders them the other way);
* ``--bug dirty-read``   -- reversed list reads (incompatible-order) /
  stale register reads.

Nemesis axes (``--nemesis none|faketime|charybdefs``) reuse the real
cluster tooling -- libfaketime clock skew via ``nemesis.time`` and
CharybdeFS EIO injection -- contained into info completions when the
control plane can't reach a real cluster, so the same campaign matrix
runs against the dummy rig and a docker/SSH fleet alike.

Clock-skew soaks make naive realtime-edge inference unsound: a worker
whose clock runs 30s behind "completes" ops long before other workers
invoke theirs. The suite's checker recovers the per-node offset bound
from the clock nemesis' ``check-offsets`` completions in the history
(``skew_bound_from_history``) and feeds it to the cycle engine, which
only infers an RT edge when the realtime gap exceeds the bound.

Run it yourself::

    python -m jepsen_tpu.suites.txn test --node n1 --time-limit 8
    python -m jepsen_tpu.suites.txn test --workload wr --monitor
    python -m jepsen_tpu.suites.txn test --bug future-read --monitor \\
        --monitor-chunk 8    # must FAIL, mid-run
"""

from __future__ import annotations

import threading

from .. import checker as cc
from .. import cli
from .. import client as jclient
from .. import db as jdb
from .. import generator as gen
from .. import os as jos
from ..checker import checkers as cks
from ..cycle import skew_bound_from_offsets
from ..demo import nemesis_axis
from ..tests.cycle import append as append_workload
from ..tests.cycle import wr as wr_workload


class TxnStore:
    """Shared serializable store: per-key lists (append family) and
    per-key (current, previous) registers (wr family)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.lists = {}
        self.kv = {}

    def clear(self):
        with self.lock:
            self.lists.clear()
            self.kv.clear()


class TxnDB(jdb.DB):
    def __init__(self, store):
        self.store = store

    def setup(self, test, node):
        self.store.clear()

    def teardown(self, test, node):
        pass


class ListAppendClient(jclient.Client):
    """Executes append/r micro-ops against the shared store; see the
    module docstring for the injectable bugs."""

    def __init__(self, store, bug=None):
        self.store = store
        self.bug = bug
        self._n = 0

    def open(self, test, node):
        return ListAppendClient(self.store, self.bug)

    def invoke(self, test, op):
        out = dict(op)
        txn = []
        # keys this txn itself appends to: the future-read prediction
        # must stay CROSS-txn (predicting a value this same txn then
        # appends degrades the clean G1c-realtime signal into a
        # within-txn incompatible-order)
        own_appends = {k for f, k, _ in op["value"] if f == "append"}
        with self.store.lock:
            self._n += 1
            for f, k, v in op["value"]:
                if f == "append":
                    lst = self.store.lists.setdefault(k, [])
                    # store-assigned per-key values: generated values
                    # apply out of order under concurrency, so lists
                    # would carry gaps and the future-read prediction
                    # below would name a value whose append lands far
                    # from where the read put it (incompatible-order
                    # noise instead of the clean G1c signal)
                    v = lst[-1] + 1 if lst else 1
                    lst.append(v)
                    txn.append([f, k, v])
                else:
                    got = list(self.store.lists.get(k, []))
                    if self.bug == "dirty-read" and self._n % 7 == 0 \
                            and len(got) >= 2:
                        got = got[::-1]
                    elif self.bug == "future-read" \
                            and self._n % 5 == 0 and got \
                            and k not in own_appends:
                        got = got + [max(got) + 1]
                    txn.append([f, k, got])
        out.update(type="ok", value=txn)
        return out


class RwRegisterClient(jclient.Client):
    """Executes w/r micro-ops; dirty-read serves every 7th read from
    the key's previous version."""

    def __init__(self, store, bug=None):
        self.store = store
        self.bug = bug
        self._n = 0

    def open(self, test, node):
        return RwRegisterClient(self.store, self.bug)

    def invoke(self, test, op):
        out = dict(op)
        txn = []
        with self.store.lock:
            self._n += 1
            for f, k, v in op["value"]:
                if f == "w":
                    prev = self.store.kv.get(k, (None, None))[0]
                    self.store.kv[k] = (v, prev)
                    txn.append([f, k, v])
                else:
                    cur, prev = self.store.kv.get(k, (None, None))
                    got = cur
                    if self.bug in ("dirty-read", "stale-read") \
                            and self._n % 7 == 0 and prev is not None:
                        got = prev
                    txn.append([f, k, got])
        out.update(type="ok", value=txn)
        return out


def skew_bound_from_history(history, scale=1e9):
    """Recover a realtime-skew bound (history time units; ns by
    default) from clock-nemesis completions: every ``clock_offsets``
    map in the history contributes its per-node offsets (seconds) to
    one max-min envelope."""
    offsets = []
    for op in history or ():
        co = op.get("clock_offsets") if isinstance(op, dict) else None
        if isinstance(co, dict):
            offsets.extend(float(v) for v in co.values()
                           if isinstance(v, (int, float)))
    if not offsets:
        return 0
    return int(skew_bound_from_offsets(offsets, scale))


def _checker(workload_mod, opts):
    """The workload's cycle checker, made skew-aware: the realtime
    bound is recovered from the history THIS run produced (an explicit
    --skew-bound-s wins)."""
    fixed = opts.get("skew-bound")
    base = workload_mod.checker(dict(opts.get("checker-opts") or {}))

    from ..checker.core import FnChecker

    def run(test, hist, copts):
        bound = fixed if fixed is not None \
            else skew_bound_from_history(hist)
        inner = dict(opts.get("checker-opts") or {})
        if bound:
            inner["skew-bound"] = int(bound)
        return workload_mod.checker(inner).check(test, hist, copts)

    return FnChecker(run, name=f"txn-{getattr(base, 'name', 'cycle')}")


def append_family(opts):
    store = opts["_store"]
    w = append_workload.test(opts.get("checker-opts"))
    return {**w,
            "checker": _checker(append_workload, opts),
            "client": ListAppendClient(store, opts.get("bug")),
            "generator": gen.stagger(1.0 / opts.get("rate", 100),
                                     w["generator"])}


def wr_family(opts):
    store = opts["_store"]
    w = wr_workload.test(opts.get("checker-opts"))
    return {**w,
            "checker": _checker(wr_workload, opts),
            "client": RwRegisterClient(store, opts.get("bug")),
            "generator": gen.stagger(1.0 / opts.get("rate", 100),
                                     w["generator"])}


WORKLOADS = {
    "append": append_family,
    "wr": wr_family,
}


def txn_test(opts):
    """Build the suite's test map from parsed CLI options (the
    campaign/worker builder: ``jepsen_tpu.suites.txn:txn_test``)."""
    opts = dict(opts)
    store = TxnStore()
    opts["_store"] = store
    wname = opts.get("workload", "append")
    opts.setdefault("checker-opts", {
        "key-count": int(opts.get("key-count", 3)),
        "max-txn-length": int(opts.get("max-txn-length", 3)),
    })
    if opts.get("skew-bound-s") is not None:
        opts["skew-bound"] = int(float(opts["skew-bound-s"]) * 1e9)
    workload = WORKLOADS[wname](opts)
    nem, nem_gen = nemesis_axis(opts.get("nemesis"))
    body = gen.clients(workload["generator"])
    if nem_gen is not None:
        body = gen.nemesis(nem_gen, body)
    generator = gen.time_limit(opts.get("time-limit", 8), body)
    checker = cc.compose({
        "workload": workload["checker"],
        "stats": cks.stats(),
        "exceptions": cks.unhandled_exceptions(),
    })
    test = {
        "name": f"txn-{wname}"
                + (f"-{opts['bug']}" if opts.get("bug") else "")
                + (f"-{opts['nemesis']}"
                   if opts.get("nemesis") not in (None, "none") else ""),
        "nodes": opts.get("nodes") or ["n1"],
        "concurrency": opts.get("concurrency")
        or len(opts.get("nodes") or ["n1"]) * 3,
        "ssh": opts.get("ssh", {"dummy?": True}),
        "os": jos.noop,
        "db": TxnDB(store),
        "nemesis": nem,
        "client": workload["client"],
        "generator": generator,
        "checker": checker,
    }
    for k in ("op-timeout-ms", "time-limit-s", "abort-grace-s",
              "monitor", "monitor-chunk", "progress-interval-s",
              "telemetry-flush-ms"):
        if opts.get(k) is not None:
            test[k] = opts[k]
    if test.get("monitor"):
        mcfg = test["monitor"]
        if mcfg is True:
            mcfg = {}
        elif isinstance(mcfg, int):
            mcfg = {"chunk": mcfg}
        else:
            mcfg = dict(mcfg)
        mcfg.setdefault("family", "txn")
        mcfg.setdefault("workload", wname)
        if opts.get("skew-bound"):
            mcfg.setdefault("skew-bound", int(opts["skew-bound"]))
        test["monitor"] = mcfg
    return test


def _opt_spec(parser):
    parser.add_argument("--workload", default="append",
                        choices=sorted(WORKLOADS))
    parser.add_argument("--bug", default=None,
                        choices=["future-read", "dirty-read",
                                 "stale-read"],
                        help="inject a consistency bug the cycle "
                             "checker (and live monitor) must catch")
    parser.add_argument("--nemesis", default="none",
                        choices=["none", "faketime", "charybdefs"],
                        help="fault axis: libfaketime clock skew or "
                             "CharybdeFS EIO injection (no-ops under "
                             "the dummy rig)")
    parser.add_argument("--rate", type=float, default=100,
                        help="approximate txns per second per thread")
    parser.add_argument("--key-count", type=int, default=3)
    parser.add_argument("--max-txn-length", type=int, default=3)
    parser.add_argument("--skew-bound-s", type=float, default=None,
                        help="explicit realtime-skew bound in seconds "
                             "(default: recovered from clock-nemesis "
                             "check-offsets completions)")


def main(argv=None):
    cmds = {}
    cmds.update(cli.single_test_cmd({"test-fn": txn_test,
                                     "opt-spec": _opt_spec}))
    cmds.update(cli.serve_cmd())
    cli.run(cmds, argv)


if __name__ == "__main__":
    cli.hard_main(main)
