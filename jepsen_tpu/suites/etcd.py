"""etcd test suite: the exemplar consumer (reference consumers in
SURVEY.md §2.8; structure follows zookeeper.clj:1-137 with the modern
workload-registry pattern of tidb/src/tidb/core.clj:32-70).

Run against a real cluster::

    python -m jepsen_tpu.suites.etcd test --node n1 --node n2 --node n3 \\
        --workload register --time-limit 60 --nemesis partition

or smoke-test the whole pipeline with no cluster at all::

    python -m jepsen_tpu.suites.etcd test --stub --node n1 --node n2

(--stub swaps the network client for a shared in-memory store and uses
the dummy remote, like the reference's integration tests.)"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

from .. import checker as cc
from .. import cli
from .. import client as jclient
from .. import control as c
from .. import db as jdb
from .. import generator as gen
from ..checker import checkers as cks
from ..checker import timeline
from ..control import util as cu
from ..nemesis import combined as nc
from ..os import debian
from ..tests import linearizable_register

VERSION = "3.4.27"
DIR = "/opt/etcd"
DATA_DIR = "/opt/etcd/data"
LOGFILE = "/opt/etcd/etcd.log"
PIDFILE = "/opt/etcd/etcd.pid"
CLIENT_PORT = 2379
PEER_PORT = 2380


def node_url(node, port):
    return f"http://{node}:{port}"


def initial_cluster(test):
    """--initial-cluster flag value: name=peer-url pairs
    (zookeeper.clj:32-38 is the analogous config fragment)."""
    return ",".join(f"{n}={node_url(n, PEER_PORT)}"
                    for n in test["nodes"])


class EtcdDB(jdb.DB, jdb.Process, jdb.Pause, jdb.Primary, jdb.LogFiles):
    """Installs and runs an etcd node from the release tarball."""

    def __init__(self, version=VERSION):
        self.version = version

    def setup(self, test, node):
        with c.su():
            cu.install_archive(
                f"https://github.com/etcd-io/etcd/releases/download/"
                f"v{self.version}/etcd-v{self.version}-linux-amd64.tar.gz",
                DIR)
        self.start(test, node)
        cu.await_tcp_port(CLIENT_PORT, host=node, timeout_s=30)

    def teardown(self, test, node):
        self.kill(test, node)
        with c.su():
            c.exec_("rm", "-rf", DATA_DIR, LOGFILE)

    def start(self, test, node):
        with c.su():
            cu.start_daemon(
                f"{DIR}/etcd",
                "--name", node,
                "--data-dir", DATA_DIR,
                "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
                "--advertise-client-urls", node_url(node, CLIENT_PORT),
                "--listen-peer-urls", f"http://0.0.0.0:{PEER_PORT}",
                "--initial-advertise-peer-urls",
                node_url(node, PEER_PORT),
                "--initial-cluster", initial_cluster(test),
                logfile=LOGFILE, pidfile=PIDFILE)
        return "started"

    def kill(self, test, node):
        with c.su():
            cu.stop_daemon(pidfile=PIDFILE, process_name="etcd")
        return "killed"

    def pause(self, test, node):
        with c.su():
            cu.grepkill("etcd", signal="STOP")
        return "paused"

    def resume(self, test, node):
        with c.su():
            cu.grepkill("etcd", signal="CONT")
        return "resumed"

    def primaries(self, test):
        """Nodes that believe they're the leader, via the v3
        maintenance status endpoint (leader id == own member id)."""
        out = []
        for node in test["nodes"]:
            try:
                req = urllib.request.Request(
                    f"{node_url(node, CLIENT_PORT)}"
                    f"/v3/maintenance/status",
                    data=b"{}",
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=2) as resp:
                    got = json.load(resp)
                leader = str(got.get("leader", ""))
                me = str((got.get("header") or {}).get("member_id", "?"))
                if leader and leader == me:
                    out.append(node)
            except Exception:  # noqa: BLE001 - dead node: not a primary
                pass
        return out

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [LOGFILE]


# -- clients -----------------------------------------------------------------

def _b64(s) -> str:
    import base64
    return base64.b64encode(str(s).encode()).decode()


def _unb64(s) -> str:
    import base64
    return base64.b64decode(s).decode()


class EtcdRegisterClient(jclient.Client):
    """Keyed cas-register over etcd's v3 gRPC-gateway JSON API
    (``/v3/kv/range|put|txn``; keys and values travel base64-coded).
    Round 2 used the v2 keys API, which is legacy and OFF by default
    since etcd 3.4 -- any stock deployment without --enable-v2 broke
    (VERDICT r2 weak #4). v3 notes: range reads are linearizable by
    default; the gateway omits false/zero/empty protobuf fields in
    responses, so ``succeeded``/``kvs`` must be read with .get().
    Ops carry independent-style [k, v] values
    (linearizable_register.py)."""

    def __init__(self, node=None, timeout_s=5.0):
        self.node = node
        self.timeout_s = timeout_s

    def open(self, test, node):
        return type(self)(node, self.timeout_s)

    def _post(self, path, body):
        req = urllib.request.Request(
            f"{node_url(self.node, CLIENT_PORT)}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.load(resp)

    def _key(self, k):
        return _b64(f"r{k}")

    def _cas_txn(self, k, new, compare):
        """One compare -> put txn; returns the gateway's ``succeeded``."""
        got = self._post("/v3/kv/txn", {
            "compare": [compare],
            "success": [{"requestPut":
                         {"key": self._key(k), "value": _b64(new)}}],
        })
        return bool(got.get("succeeded"))

    def invoke(self, test, op):
        k, v = op["value"]
        out = dict(op)
        try:
            if op["f"] == "read":
                got = self._post("/v3/kv/range", {"key": self._key(k)})
                kvs = got.get("kvs") or []
                val = int(_unb64(kvs[0]["value"])) if kvs else None
                out.update(type="ok", value=type(op["value"])(k, val))
            elif op["f"] == "write":
                self._post("/v3/kv/put",
                           {"key": self._key(k), "value": _b64(v)})
                out["type"] = "ok"
            elif op["f"] == "create":
                # atomic create-if-absent: two racing first-writers must
                # not both ack. Compare VERSION == 0 means "key absent".
                ok = self._cas_txn(k, v, {
                    "key": self._key(k), "target": "VERSION",
                    "version": "0"})
                out["type"] = "ok" if ok else "fail"
            elif op["f"] == "cas":
                old, new = v
                ok = self._cas_txn(k, new, {
                    "key": self._key(k), "target": "VALUE",
                    "value": _b64(old)})
                out["type"] = "ok" if ok else "fail"
            else:
                raise ValueError(f"unknown f {op['f']!r}")
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # indeterminate: the request may have been applied
            out.update(type=("fail" if op["f"] == "read" else "info"),
                       error=repr(e))
        return out


class StubRegisterClient(jclient.Client):
    """In-memory keyed cas-register sharing one dict: lets the whole
    suite run end-to-end with the dummy remote (reference test level 3,
    core_test.clj:62-120)."""

    def __init__(self, kv=None, lock=None):
        self.kv = kv if kv is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return StubRegisterClient(self.kv, self.lock)

    def invoke(self, test, op):
        k, v = op["value"]
        out = dict(op)
        with self.lock:
            if op["f"] == "read":
                out.update(type="ok",
                           value=type(op["value"])(k, self.kv.get(k)))
            elif op["f"] == "write":
                self.kv[k] = v
                out["type"] = "ok"
            elif op["f"] == "create":
                if k in self.kv:
                    out["type"] = "fail"
                else:
                    self.kv[k] = v
                    out["type"] = "ok"
            else:
                old, new = v
                if self.kv.get(k) == old:
                    self.kv[k] = new
                    out["type"] = "ok"
                else:
                    out["type"] = "fail"
        return out


# -- workloads (tidb/core.clj:32-44-style registry) --------------------------

def register_workload(opts):
    """Keyed linearizable cas-registers, checked on device in one batch
    (linearizable_register.clj:39-53)."""
    wl = linearizable_register.test(opts)
    wl["client"] = (StubRegisterClient() if opts.get("stub")
                    else EtcdRegisterClient())
    return wl


def set_workload(opts):
    """Unique adds to one key via cas read-modify-write; final read
    (checker.clj set semantics)."""
    counter = {"n": 0}

    def add(test, ctx):
        counter["n"] += 1
        return {"type": "invoke", "f": "add", "value": counter["n"]}

    class SetClient(jclient.Client):
        def __init__(self, inner):
            self.inner = inner

        def open(self, test, node):
            return SetClient(self.inner.open(test, node))

        def invoke(self, test, op):
            from ..independent import tuple_ as T
            if op["f"] == "add":
                for _ in range(16):
                    r = self.inner.invoke(
                        test, {**op, "f": "read", "value": T(0, None)})
                    if r["type"] != "ok":
                        return dict(op, type="info", error="read")
                    cur = r["value"][1]
                    items = [] if cur in (None, "") else \
                        [int(x) for x in str(cur).split(":")]
                    new = ":".join(str(x) for x in items + [op["value"]])
                    if cur is None:
                        # atomic create: racing first-adds must not
                        # silently overwrite each other
                        w = self.inner.invoke(
                            test,
                            {**op, "f": "create", "value": T(0, new)})
                        if w["type"] == "ok":
                            return dict(op, type="ok")
                        if w["type"] == "info":
                            return dict(op, type="info", error="create")
                        continue
                    r2 = self.inner.invoke(
                        test, {**op, "f": "cas", "value": T(0, (cur, new))})
                    if r2["type"] == "ok":
                        return dict(op, type="ok")
                return dict(op, type="fail", error="cas-contention")
            # final read
            r = self.inner.invoke(
                test, {**op, "f": "read", "value": T(0, None)})
            if r["type"] != "ok":
                return dict(op, type=r["type"])
            cur = r["value"][1]
            items = [] if cur in (None, "") else \
                [int(x) for x in str(cur).split(":")]
            return dict(op, type="ok", value=items)

    inner = (StubRegisterClient() if opts.get("stub")
             else EtcdRegisterClient())
    return {
        "client": SetClient(inner),
        "checker": cks.set_checker(),
        "generator": gen.phases(
            gen.limit(opts.get("op-count", 100), add),
            gen.synchronize(gen.each_thread(gen.once(
                {"type": "invoke", "f": "read", "value": None})))),
    }


WORKLOADS = {
    "register": register_workload,
    "set": set_workload,
}

NEMESES = ["partition", "kill", "pause", "clock"]


def etcd_test(opts):
    """Build a test map from CLI options (zookeeper.clj:106-129)."""
    workload_name = opts.get("workload", "register")
    if workload_name == "register":
        # the register workload groups 2n threads per key
        # (linearizable_register.clj:49); round the worker count up so
        # the default "1n" concurrency doesn't crash the generator
        group = 2 * len(opts.get("nodes") or [1])
        conc = opts.get("concurrency") or group
        opts = {**opts,
                "concurrency": max(group,
                                   (conc + group - 1) // group * group)}
    workload = WORKLOADS[workload_name](opts)
    db = jdb.noop if opts.get("stub") else EtcdDB(opts.get("version",
                                                           VERSION))
    faults = opts.get("nemesis") or []
    pkg = nc.nemesis_package({
        "db": db, "faults": faults,
        "interval": opts.get("nemesis-interval", 10)})

    generator = gen.clients(workload["generator"], pkg["generator"])
    generator = gen.time_limit(opts.get("time-limit", 60), generator)
    final = pkg["final_generator"]
    if final is not None:
        generator = gen.phases(generator, gen.nemesis(final))

    checker = cc.compose({
        "workload": workload["checker"],
        "stats": cks.stats(),
        "exceptions": cks.unhandled_exceptions(),
        "timeline": timeline.html(),
    })
    from .. import os as jos
    test = {
        "name": f"etcd-{workload_name}",
        "os": jos.noop if opts.get("stub") else debian.os,
        "db": db,
        "client": workload["client"],
        "nemesis": pkg["nemesis"],
        "generator": generator,
        "checker": checker,
        "plot": {"nemeses": pkg["perf"]},
    }
    out = {**opts, **test}
    if opts.get("stub"):
        out["ssh"] = {"dummy?": True}
    return out


def _opt_spec(parser):
    parser.add_argument("--workload", default="register",
                        choices=sorted(WORKLOADS))
    parser.add_argument("--nemesis", action="append", default=[],
                        choices=NEMESES,
                        help="fault types to inject (repeatable)")
    parser.add_argument("--nemesis-interval", type=float, default=10.0)
    parser.add_argument("--version", default=VERSION)
    parser.add_argument("--op-count", type=int, default=100)
    parser.add_argument("--stub", action="store_true",
                        help="in-memory client + dummy remote (no "
                             "cluster needed)")


def all_tests(opts):
    """test-all matrix: every workload x every single nemesis
    (cli.clj:487-515, tidb/core.clj:46-70). --nemesis flags restrict the
    fault axis; default sweeps them all."""
    chosen = opts.get("nemesis") or NEMESES
    out = []
    for wname in sorted(WORKLOADS):
        for nem in [[]] + [[n] for n in chosen]:
            o = {**opts, "workload": wname, "nemesis": nem}
            out.append(etcd_test(o))
    return out


def main(argv=None):
    cmds = {}
    cmds.update(cli.single_test_cmd({"test-fn": etcd_test,
                                     "opt-spec": _opt_spec}))
    cmds.update(cli.test_all_cmd({"tests-fn": all_tests,
                                  "opt-spec": _opt_spec}))
    cmds.update(cli.serve_cmd())
    cli.run(cmds, argv)


if __name__ == "__main__":
    cli.hard_main(main)
