"""Exemplar consumer suites: complete, runnable tests for real systems,
built on the framework the way the reference's per-database projects are
(SURVEY.md §2.8 — e.g. zookeeper.clj as the minimal single-file example,
tidb/core.clj for the workload-registry pattern)."""
