"""Minimal ZooKeeper wire protocol (jute) client for the data path.

The round-2 zookeeper suite screen-scraped zkCli.sh output, with a
load-bearing comment about which zkCli version's grammar it assumed
(ADVICE/VERDICT r2). This module replaces the data path with the actual
client protocol: length-prefixed jute frames over TCP -- connect
handshake, then getData/setData/create with real error codes, so CAS
maps to SetData-with-expected-version and a BadVersion (-103) reply
instead of parsing shell output.

Format (big-endian), reconstructed from the public jute definitions
(zookeeper.jute) and protocol documentation:

* frame: 4-byte length prefix (excluding itself)
* primitives: int (4), long (8), bool (1), buffer (len + bytes, -1 =
  null), string (utf-8 buffer), vector (count + items)
* session: ConnectRequest{proto=0, lastZxid=0, timeout, session=0,
  passwd[16], readOnly} -> ConnectResponse
* requests: RequestHeader{xid, type} + record; replies:
  ReplyHeader{xid, zxid, err} + record. Watch events arrive with
  xid == -1 and are skipped; pings are xid == -2.

``FakeZkServer`` implements the same protocol server-side over a plain
dict -- enough for the integration rig to drive the client through real
sockets (tests/test_suite_zookeeper.py). The byte layout is pinned by
hand-assembled golden frames derived from the public jute definitions
(tests/test_wire_golden.py), so encode and decode are validated against
fixtures this module did not produce -- not merely against each other;
against a real ensemble any residual mismatch fails loudly at the
connect handshake rather than silently corrupting values.
"""

from __future__ import annotations

import socket
import struct
import threading

# request types (zookeeper protocol)
OP_CREATE, OP_DELETE, OP_EXISTS, OP_GETDATA, OP_SETDATA = 1, 2, 3, 4, 5
OP_PING, OP_CLOSE = 11, -11

# error codes
OK = 0
NO_NODE = -101
BAD_VERSION = -103
NODE_EXISTS = -110

#: world:anyone ACL with all permissions
OPEN_ACL = [(31, "world", "anyone")]


class ZkError(Exception):
    def __init__(self, code):
        self.code = code
        super().__init__(f"zookeeper error {code}")


class _Enc:
    def __init__(self):
        self.b = bytearray()

    def int(self, v):
        self.b += struct.pack(">i", v)
        return self

    def long(self, v):
        self.b += struct.pack(">q", v)
        return self

    def bool(self, v):
        self.b += b"\x01" if v else b"\x00"
        return self

    def buffer(self, v):
        if v is None:
            return self.int(-1)
        self.int(len(v))
        self.b += v
        return self

    def string(self, v):
        return self.buffer(v.encode())


class _Dec:
    def __init__(self, b):
        self.b = b
        self.i = 0

    def int(self):
        v = struct.unpack_from(">i", self.b, self.i)[0]
        self.i += 4
        return v

    def long(self):
        v = struct.unpack_from(">q", self.b, self.i)[0]
        self.i += 8
        return v

    def bool(self):
        v = self.b[self.i] != 0
        self.i += 1
        return v

    def buffer(self):
        n = self.int()
        if n < 0:
            return None
        v = bytes(self.b[self.i:self.i + n])
        self.i += n
        return v

    def string(self):
        v = self.buffer()
        return None if v is None else v.decode()

    def stat(self):
        names = ("czxid", "mzxid", "ctime", "mtime")
        out = {k: self.long() for k in names}
        out["version"] = self.int()
        out["cversion"] = self.int()
        out["aversion"] = self.int()
        out["ephemeralOwner"] = self.long()
        out["dataLength"] = self.int()
        out["numChildren"] = self.int()
        out["pzxid"] = self.long()
        return out


def _stat_bytes(version=0, data_len=0, zxid=0):
    e = _Enc()
    for _ in range(4):
        e.long(zxid)
    e.int(version).int(0).int(0).long(0).int(data_len).int(0).long(zxid)
    return bytes(e.b)


def _send_frame(sock, payload):
    sock.sendall(struct.pack(">i", len(payload)) + payload)


def _recv_exact(sock, n):
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("zookeeper connection closed")
        out += chunk
    return out


def _recv_frame(sock):
    (n,) = struct.unpack(">i", _recv_exact(sock, 4))
    return _recv_exact(sock, n)


class ZkWireClient:
    """One session: connect handshake then sequential request/reply."""

    def __init__(self, host, port, timeout_s=5.0,
                 session_timeout_ms=10_000):
        self.sock = socket.create_connection((host, port), timeout_s)
        self.sock.settimeout(timeout_s)
        self.xid = 0
        e = _Enc()
        e.int(0).long(0).int(session_timeout_ms).long(0)
        e.buffer(b"\x00" * 16)
        e.bool(False)                       # readOnly (3.4+)
        _send_frame(self.sock, bytes(e.b))
        d = _Dec(_recv_frame(self.sock))
        d.int()                             # protocol version
        self.negotiated_timeout = d.int()
        self.session_id = d.long()

    def close(self):
        try:
            e = _Enc()
            e.int(1).int(OP_CLOSE)
            _send_frame(self.sock, bytes(e.b))
        except OSError:
            pass
        finally:
            self.sock.close()

    def _call(self, op, body):
        self.xid += 1
        xid = self.xid
        e = _Enc()
        e.int(xid).int(op)
        e.b += body
        _send_frame(self.sock, bytes(e.b))
        while True:
            d = _Dec(_recv_frame(self.sock))
            rxid = d.int()
            d.long()                        # zxid
            err = d.int()
            if rxid in (-1, -2):            # watch event / ping: skip
                continue
            if rxid != xid:
                raise ConnectionError(
                    f"xid mismatch: sent {xid}, got {rxid}")
            if err != OK:
                raise ZkError(err)
            return d

    def create(self, path, data, flags=0):
        e = _Enc()
        e.string(path).buffer(data)
        e.int(len(OPEN_ACL))
        for perms, scheme, ident in OPEN_ACL:
            e.int(perms).string(scheme).string(ident)
        e.int(flags)
        return self._call(OP_CREATE, bytes(e.b)).string()

    def get_data(self, path):
        """-> (data bytes, stat dict)."""
        e = _Enc()
        e.string(path).bool(False)
        d = self._call(OP_GETDATA, bytes(e.b))
        data = d.buffer()
        return data, d.stat()

    def set_data(self, path, data, version=-1):
        """version >= 0 = compare-and-set; -1 = unconditional."""
        e = _Enc()
        e.string(path).buffer(data).int(version)
        return self._call(OP_SETDATA, bytes(e.b)).stat()


class FakeZkServer:
    """Protocol-emulating single-node server over a dict, for the rig:
    znodes with versioned CAS semantics, served on real sockets."""

    def __init__(self, host="127.0.0.1", port=0):
        self.store = {}                 # path -> [data bytes, version]
        self.lock = threading.Lock()
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def close(self):
        self._stop.set()
        self.sock.close()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._session, args=(conn,),
                             daemon=True).start()

    def _session(self, conn):
        try:
            d = _Dec(_recv_frame(conn))     # ConnectRequest
            d.int(), d.long()
            timeout = d.int()
            e = _Enc()
            e.int(0).int(timeout).long(0x1234).buffer(b"\x00" * 16)
            e.bool(False)
            _send_frame(conn, bytes(e.b))
            while True:
                d = _Dec(_recv_frame(conn))
                xid, op = d.int(), d.int()
                if op == OP_CLOSE:
                    self._reply(conn, xid, OK, b"")
                    return
                try:
                    body = self._handle(op, d)
                    self._reply(conn, xid, OK, body)
                except ZkError as z:
                    self._reply(conn, xid, z.code, b"")
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            conn.close()

    def _handle(self, op, d):
        if op == OP_PING:
            return b""
        path = d.string()
        with self.lock:
            if op == OP_GETDATA:
                d.bool()
                if path not in self.store:
                    raise ZkError(NO_NODE)
                data, version = self.store[path]
                e = _Enc()
                e.buffer(data)
                e.b += _stat_bytes(version, len(data or b""))
                return bytes(e.b)
            if op == OP_CREATE:
                data = d.buffer()
                if path in self.store:
                    raise ZkError(NODE_EXISTS)
                self.store[path] = [data, 0]
                return bytes(_Enc().string(path).b)
            if op == OP_SETDATA:
                data = d.buffer()
                version = d.int()
                if path not in self.store:
                    raise ZkError(NO_NODE)
                cur = self.store[path]
                if version >= 0 and cur[1] != version:
                    raise ZkError(BAD_VERSION)
                cur[0], cur[1] = data, cur[1] + 1
                return _stat_bytes(cur[1], len(data or b""))
        raise ZkError(-2)                   # unimplemented

    @staticmethod
    def _reply(conn, xid, err, body):
        e = _Enc()
        e.int(xid).long(1).int(err)
        e.b += body
        _send_frame(conn, bytes(e.b))
