"""ZooKeeper test suite: the minimal single-file consumer (reference
zookeeper/src/jepsen/zookeeper.clj, 137 LoC — the tutorial's target).

A single compare-and-set register held in a znode, driven over the
actual client wire protocol (suites/zk_proto.py -- no Python client
dependency and no shell scraping), a random-halves partitioner, and the
device linearizability checker::

    python -m jepsen_tpu.suites.zookeeper test \\
        --node n1 --node n2 --node n3 --time-limit 15

``--stub`` runs the whole pipeline against an in-memory register."""

from __future__ import annotations

import random
import threading

from .. import checker as cc
from .. import cli
from .. import client as jclient
from .. import control as c
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import os as jos
from .. import tests as tst
from ..checker import checkers as cks
from ..checker import perf as cperf
from ..checker import timeline
from ..os import debian
from . import zk_proto

VERSION = "3.6.3"


def zk_node_ids(test) -> dict:
    """node name -> myid (zookeeper.clj:19-30)."""
    return {node: i for i, node in enumerate(test["nodes"])}


def zoo_cfg_servers(test) -> str:
    """server.N lines for zoo.cfg (zookeeper.clj:32-38)."""
    return "\n".join(f"server.{i}={node}:2888:3888"
                     for node, i in zk_node_ids(test).items())


ZOO_CFG = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
"""


DIR = "/opt/zookeeper"


class ZkDB(jdb.DB, jdb.LogFiles):
    """Installs ZooKeeper from the release tarball and (re)configures
    the ensemble (zookeeper.clj:40-72 uses the 3.4 distro package)."""

    def __init__(self, version=VERSION):
        self.version = version

    def setup(self, test, node):
        from ..control import util as cu
        with c.su():
            debian.install(["default-jre-headless"])
            cu.install_archive(
                f"https://archive.apache.org/dist/zookeeper/"
                f"zookeeper-{self.version}/"
                f"apache-zookeeper-{self.version}-bin.tar.gz", DIR)
            c.exec_("mkdir", "-p", "/var/lib/zookeeper")
            c.upload_string(str(zk_node_ids(test)[node]),
                            "/var/lib/zookeeper/myid")
            c.upload_string(ZOO_CFG + "\n" + zoo_cfg_servers(test),
                            f"{DIR}/conf/zoo.cfg")
            c.exec_(f"{DIR}/bin/zkServer.sh", "restart")

    def teardown(self, test, node):
        with c.su():
            c.exec_star(f"{DIR}/bin/zkServer.sh", "stop")
            c.exec_star("rm", "-rf", "/var/lib/zookeeper/version-2",
                        f"{DIR}/logs")

    def log_files(self, test, node):
        return [f"{DIR}/logs/zookeeper.log"]


# generators (zookeeper.clj:74-76)

def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [random.randint(0, 4), random.randint(0, 4)]}


class ZkClient(jclient.Client):
    """CAS register in the /jepsen znode over the actual client wire
    protocol (suites/zk_proto.py): getData/setData with real version
    numbers, CAS = SetData-with-expected-version answered by BadVersion
    (-103). Replaces round 2's zkCli.sh screen-scraping, which depended
    on one zkCli version's output grammar (zookeeper.clj:78-104 uses
    avout; the wire client keeps this suite dependency-free without
    parsing shell output)."""

    PATH = "/jepsen"

    def __init__(self, node=None, port=2181):
        self.node = node
        self.port = port
        self.conn = None

    def open(self, test, node):
        return ZkClient(node, test.get("zk-port", 2181))

    def _session(self):
        if self.conn is None:
            self.conn = zk_proto.ZkWireClient(self.node, self.port,
                                              timeout_s=5.0)
        return self.conn

    def close(self, test):
        if self.conn is not None:
            self.conn.close()
            self.conn = None

    def setup(self, test):
        try:
            self._session().create(self.PATH, b"0")
        except zk_proto.ZkError as e:
            if e.code != zk_proto.NODE_EXISTS:
                raise

    def _get(self):
        data, stat = self._session().get_data(self.PATH)
        return int(data.decode()), stat["version"]

    def invoke(self, test, op):
        out_op = dict(op)
        try:
            if op["f"] == "read":
                value, _ = self._get()
                out_op.update(type="ok", value=value)
            elif op["f"] == "write":
                self._session().set_data(self.PATH,
                                         str(op["value"]).encode())
                out_op["type"] = "ok"
            else:
                old, new = op["value"]
                value, version = self._get()
                if value != old:
                    out_op["type"] = "fail"
                else:
                    try:
                        self._session().set_data(
                            self.PATH, str(new).encode(),
                            version=version)
                        out_op["type"] = "ok"
                    except zk_proto.ZkError as e:
                        if e.code != zk_proto.BAD_VERSION:
                            raise
                        # another writer interleaved: a clean loss
                        out_op["type"] = "fail"
        except (zk_proto.ZkError, OSError) as e:
            # drop the session: reconnect on the next op
            self.close(test)
            out_op.update(
                type=("fail" if op["f"] == "read" else "info"),
                error=repr(e))
        return out_op


class StubClient(jclient.Client):
    """Shared in-memory register for --stub runs."""

    def __init__(self, box=None, lock=None):
        self.box = box if box is not None else {"v": 0}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return StubClient(self.box, self.lock)

    def invoke(self, test, op):
        out = dict(op)
        with self.lock:
            if op["f"] == "read":
                out.update(type="ok", value=self.box["v"])
            elif op["f"] == "write":
                self.box["v"] = op["value"]
                out["type"] = "ok"
            else:
                old, new = op["value"]
                if self.box["v"] == old:
                    self.box["v"] = new
                    out["type"] = "ok"
                else:
                    out["type"] = "fail"
        return out


def zk_test(opts):
    """Options map -> test map (zookeeper.clj:106-129)."""
    stub = opts.get("stub")
    test = dict(tst.noop_test())
    test.update(opts)
    test.update({
        "name": "zookeeper",
        "os": jos.noop if stub else debian.os,
        "db": jdb.noop if stub else ZkDB(opts.get("version", VERSION)),
        "client": StubClient() if stub else ZkClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "generator": gen.time_limit(
            opts.get("time-limit", 15),
            gen.nemesis(
                gen.cycle(gen.sleep(5),
                          {"type": "info", "f": "start"},
                          gen.sleep(5),
                          {"type": "info", "f": "stop"}),
                gen.stagger(1, gen.mix([r, w, cas])))),
        # perf + linearizable, like the reference (zookeeper.clj:127-129;
        # no stats: sparse histories legitimately have zero ok cas ops)
        "checker": cc.compose({
            # the register starts at 0 (the znode is created with "0"):
            # the reference's (model/cas-register 0)
            "linear": cks.linearizable(
                {"model": "cas-register",
                 "algorithm": opts.get("algorithm", "competition"),
                 "init-ops": [{"f": "write", "value": 0}]}),
            "perf": cperf.perf(),
            "timeline": timeline.html(),
        }),
    })
    if stub:
        test["ssh"] = {"dummy?": True}
    return test


def _opt_spec(parser):
    parser.add_argument("--version", default=VERSION)
    parser.add_argument("--algorithm", default="competition")
    parser.add_argument("--stub", action="store_true",
                        help="in-memory register + dummy remote")


def main(argv=None):
    cmds = {}
    cmds.update(cli.single_test_cmd({"test-fn": zk_test,
                                     "opt-spec": _opt_spec}))
    cmds.update(cli.serve_cmd())
    cli.run(cmds, argv)


if __name__ == "__main__":
    cli.hard_main(main)
