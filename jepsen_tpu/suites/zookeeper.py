"""ZooKeeper test suite: the minimal single-file consumer (reference
zookeeper/src/jepsen/zookeeper.clj, 137 LoC — the tutorial's target).

A single compare-and-set register held in a znode, driven through the
zkCli shell (no Python client dependency), a random-halves partitioner,
and the device linearizability checker::

    python -m jepsen_tpu.suites.zookeeper test \\
        --node n1 --node n2 --node n3 --time-limit 15

``--stub`` runs the whole pipeline against an in-memory register."""

from __future__ import annotations

import random
import threading

from .. import checker as cc
from .. import cli
from .. import client as jclient
from .. import control as c
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from .. import os as jos
from .. import tests as tst
from ..checker import checkers as cks
from ..checker import perf as cperf
from ..checker import timeline
from ..os import debian

#: needs >= 3.6: `get -s` / `set -v` grammar, and zkCli exiting nonzero
#: on command errors (ZOOKEEPER-3482) -- both load-bearing for the client
VERSION = "3.6.3"


def zk_node_ids(test) -> dict:
    """node name -> myid (zookeeper.clj:19-30)."""
    return {node: i for i, node in enumerate(test["nodes"])}


def zoo_cfg_servers(test) -> str:
    """server.N lines for zoo.cfg (zookeeper.clj:32-38)."""
    return "\n".join(f"server.{i}={node}:2888:3888"
                     for node, i in zk_node_ids(test).items())


ZOO_CFG = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
"""


DIR = "/opt/zookeeper"


class ZkDB(jdb.DB, jdb.LogFiles):
    """Installs ZooKeeper from the release tarball and (re)configures the
    ensemble (zookeeper.clj:40-72 uses the 3.4 distro package; the zkCli
    grammar this suite's client needs ships with >= 3.6)."""

    def __init__(self, version=VERSION):
        self.version = version

    def setup(self, test, node):
        from ..control import util as cu
        with c.su():
            debian.install(["default-jre-headless"])
            cu.install_archive(
                f"https://archive.apache.org/dist/zookeeper/"
                f"zookeeper-{self.version}/"
                f"apache-zookeeper-{self.version}-bin.tar.gz", DIR)
            c.exec_("mkdir", "-p", "/var/lib/zookeeper")
            c.upload_string(str(zk_node_ids(test)[node]),
                            "/var/lib/zookeeper/myid")
            c.upload_string(ZOO_CFG + "\n" + zoo_cfg_servers(test),
                            f"{DIR}/conf/zoo.cfg")
            c.exec_(f"{DIR}/bin/zkServer.sh", "restart")

    def teardown(self, test, node):
        with c.su():
            c.exec_star(f"{DIR}/bin/zkServer.sh", "stop")
            c.exec_star("rm", "-rf", "/var/lib/zookeeper/version-2",
                        f"{DIR}/logs")

    def log_files(self, test, node):
        return [f"{DIR}/logs/zookeeper.log"]


# generators (zookeeper.clj:74-76)

def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [random.randint(0, 4), random.randint(0, 4)]}


class ZkClient(jclient.Client):
    """CAS register in the /jepsen znode via zkCli.sh on the node
    (zookeeper.clj:78-104 uses avout; the shell round-trip keeps this
    suite dependency-free). CAS uses the znode version for atomicity."""

    ZKCLI = "/opt/zookeeper/bin/zkCli.sh"

    def __init__(self, node=None):
        self.node = node

    def open(self, test, node):
        cl = ZkClient(node)
        return cl

    def setup(self, test):
        with c.on(self.node):
            c.exec_star(self.ZKCLI, "create", "/jepsen", "0")

    def _get(self):
        out = c.exec_(self.ZKCLI, "get", "-s", "/jepsen")
        lines = [ln.strip() for ln in str(out).splitlines()
                 if ln.strip()]
        # zkCli intersperses WATCHER::/WatchedEvent/log noise; with
        # `get -s` the value is everything before the first stat field
        # (cZxid = ...). This suite only ever writes small integers, so
        # the last pre-stat line must parse as one -- anything else is a
        # parse failure we surface explicitly rather than mis-read.
        stat_at = next(i for i, ln in enumerate(lines)
                       if ln.startswith("cZxid"))
        raw = lines[stat_at - 1] if stat_at > 0 else ""
        try:
            value = int(raw)
        except ValueError:
            raise ValueError(
                f"unparseable znode value {raw!r} before stat block "
                f"(suite writes only integers; zkCli noise?)") from None
        version = next(int(ln.split("=")[-1].strip())
                       for ln in lines if ln.startswith("dataVersion"))
        return value, version

    def invoke(self, test, op):
        out_op = dict(op)
        try:
            with c.on(self.node):
                if op["f"] == "read":
                    value, _ = self._get()
                    out_op.update(type="ok", value=value)
                elif op["f"] == "write":
                    c.exec_(self.ZKCLI, "set", "/jepsen",
                            str(op["value"]))
                    out_op["type"] = "ok"
                else:
                    old, new = op["value"]
                    value, version = self._get()
                    if value != old:
                        out_op["type"] = "fail"
                    else:
                        # version-guarded set: loses cleanly when another
                        # writer interleaved. zkCli >= 3.6 exits nonzero
                        # on BadVersion (ZOOKEEPER-3482); the output
                        # check is belt and braces.
                        res = c.exec_star(self.ZKCLI, "set", "-v",
                                          str(version), "/jepsen",
                                          str(new))
                        txt = str(res.get("out", "")) + \
                            str(res.get("err", ""))
                        if res.get("exit") != 0 or "BadVersion" in txt \
                                or "version No is not valid" in txt:
                            out_op["type"] = "fail"
                        else:
                            out_op["type"] = "ok"
        except Exception as e:  # noqa: BLE001 - indeterminate
            out_op.update(
                type=("fail" if op["f"] == "read" else "info"),
                error=repr(e))
        return out_op


class StubClient(jclient.Client):
    """Shared in-memory register for --stub runs."""

    def __init__(self, box=None, lock=None):
        self.box = box if box is not None else {"v": 0}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return StubClient(self.box, self.lock)

    def invoke(self, test, op):
        out = dict(op)
        with self.lock:
            if op["f"] == "read":
                out.update(type="ok", value=self.box["v"])
            elif op["f"] == "write":
                self.box["v"] = op["value"]
                out["type"] = "ok"
            else:
                old, new = op["value"]
                if self.box["v"] == old:
                    self.box["v"] = new
                    out["type"] = "ok"
                else:
                    out["type"] = "fail"
        return out


def zk_test(opts):
    """Options map -> test map (zookeeper.clj:106-129)."""
    stub = opts.get("stub")
    test = dict(tst.noop_test())
    test.update(opts)
    test.update({
        "name": "zookeeper",
        "os": jos.noop if stub else debian.os,
        "db": jdb.noop if stub else ZkDB(opts.get("version", VERSION)),
        "client": StubClient() if stub else ZkClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "generator": gen.time_limit(
            opts.get("time-limit", 15),
            gen.nemesis(
                gen.cycle(gen.sleep(5),
                          {"type": "info", "f": "start"},
                          gen.sleep(5),
                          {"type": "info", "f": "stop"}),
                gen.stagger(1, gen.mix([r, w, cas])))),
        # perf + linearizable, like the reference (zookeeper.clj:127-129;
        # no stats: sparse histories legitimately have zero ok cas ops)
        "checker": cc.compose({
            # the register starts at 0 (the znode is created with "0"):
            # the reference's (model/cas-register 0)
            "linear": cks.linearizable(
                {"model": "cas-register",
                 "algorithm": opts.get("algorithm", "competition"),
                 "init-ops": [{"f": "write", "value": 0}]}),
            "perf": cperf.perf(),
            "timeline": timeline.html(),
        }),
    })
    if stub:
        test["ssh"] = {"dummy?": True}
    return test


def _opt_spec(parser):
    parser.add_argument("--version", default=VERSION)
    parser.add_argument("--algorithm", default="competition")
    parser.add_argument("--stub", action="store_true",
                        help="in-memory register + dummy remote")


def main(argv=None):
    cmds = {}
    cmds.update(cli.single_test_cmd({"test-fn": zk_test,
                                     "opt-spec": _opt_spec}))
    cmds.update(cli.serve_cmd())
    cli.run(cmds, argv)


if __name__ == "__main__":
    cli.hard_main(main)
