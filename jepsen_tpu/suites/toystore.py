"""Toystore: a real, live distributed register the integration rig can
kill.

The reference proves its control plane against a 5-node docker cluster
(reference docker/README.md:1-27, core_test.clj:122-177 ssh-test). This
environment has no containers and no SSH stack, so the rig runs the
control==node topology instead ({"ssh": {"local?": True}} -> commands
execute on the control host): N "nodes" are N live server processes with
per-node ports/data dirs, deployed, daemonized, killed, paused, and
log-snarfed through the REAL control path (upload, start-stop-daemon,
grepkill, SIGSTOP/SIGCONT) -- the same code an SSH cluster would use,
minus only the wire.

The server (written to ``SERVER_SRC`` and deployed by the DB) is a
primary/follower replicated key-value register over TCP:

* all writes/cas forward to the primary (lowest node id), which
  serializes them under a lock and appends to a recovery log;
* reads forward to the primary too -- linearizable by construction --
  UNLESS the server runs with ``--stale``, where reads return the local
  asynchronously-replicated copy: a REAL consistency bug the checker
  must catch end to end.

Run it yourself::

    python -m jepsen_tpu.suites.toystore test --node n1 --node n2 \\
        --node n3 --time-limit 8
    python -m jepsen_tpu.suites.toystore test --stale ... # must FAIL
"""

from __future__ import annotations

import random
import socket

import itertools

from .. import checker as cc
from .. import cli
from .. import client as jclient
from .. import control as c
from .. import db as jdb
from .. import generator as gen
from .. import independent
from .. import nemesis as jnemesis
from .. import os as jos
from .. import tests as tst
from ..checker import checkers as cks
from ..checker import timeline

BASE_PORT = 36950

#: stdlib-only server source, deployed to each node by the DB
SERVER_SRC = r'''
import argparse, os, socket, socketserver, threading, time

ap = argparse.ArgumentParser()
ap.add_argument("--port", type=int, required=True)
ap.add_argument("--node-id", type=int, required=True)
ap.add_argument("--peers", default="")   # host:port,... (all nodes, id order)
ap.add_argument("--data-dir", required=True)
ap.add_argument("--stale", action="store_true")
ap.add_argument("--repl-delay", type=float, default=0.0)
args = ap.parse_args()

peers = [p for p in args.peers.split(",") if p]
store, lock = {}, threading.Lock()
log_path = os.path.join(args.data_dir, "toystore.log")
wal_path = os.path.join(args.data_dir, "wal.txt")
is_primary = args.node_id == 0
primary = peers[0] if peers else None

def log(msg):
    with open(log_path, "a") as f:
        f.write(msg + "\n")

# recover from the write-ahead log
if os.path.exists(wal_path):
    with open(wal_path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 2:
                store[parts[0]] = parts[1]
log("boot node=%d primary=%s stale=%s recovered=%d"
    % (args.node_id, is_primary, args.stale, len(store)))

def wal(k, v):
    with open(wal_path, "a") as f:
        f.write("%s %s\n" % (k, v))
        f.flush()
        os.fsync(f.fileno())

def replicate(k, v):
    for i, hp in enumerate(peers):
        if i == args.node_id:
            continue
        def push(hp=hp):
            try:
                if args.repl_delay:
                    time.sleep(args.repl_delay)
                h, p = hp.rsplit(":", 1)
                with socket.create_connection((h, int(p)), 1) as s:
                    s.sendall(("REPL %s %s\n" % (k, v)).encode())
                    s.recv(16)
            except OSError:
                pass
        threading.Thread(target=push, daemon=True).start()

def forward(line):
    h, p = primary.rsplit(":", 1)
    with socket.create_connection((h, int(p)), 2) as s:
        s.sendall((line + "\n").encode())
        return s.makefile().readline().strip()

def apply_op(parts):
    op = parts[0]
    with lock:
        if op == "W":
            store[parts[1]] = parts[2]
            wal(parts[1], parts[2])
            replicate(parts[1], parts[2])
            return "OK"
        if op == "R":
            return "VAL %s" % store.get(parts[1], "nil")
        if op == "CAS":
            cur = store.get(parts[1], "nil")
            if cur != parts[2]:
                return "FAIL %s" % cur
            store[parts[1]] = parts[3]
            wal(parts[1], parts[3])
            replicate(parts[1], parts[3])
            return "OK"
    return "ERR bad-op"

class H(socketserver.StreamRequestHandler):
    def handle(self):
        line = self.rfile.readline().decode().strip()
        if not line:
            return
        parts = line.split()
        try:
            if parts[0] == "REPL":
                with lock:
                    store[parts[1]] = parts[2]
                out = "OK"
            elif parts[0] == "R" and args.stale and not is_primary:
                # the consistency bug: serve the async local copy
                with lock:
                    out = "VAL %s" % store.get(parts[1], "nil")
            elif is_primary:
                out = apply_op(parts)
            else:
                out = forward(line)
        except OSError as e:
            out = "ERR %s" % e
        self.wfile.write((out + "\n").encode())

class Srv(socketserver.ThreadingTCPServer):
    allow_reuse_address = True

Srv(("127.0.0.1", args.port), H).serve_forever()
'''


def node_id(test, node):
    return test["nodes"].index(node)


def node_port(test, node):
    return test.get("base-port", BASE_PORT) + node_id(test, node)


def node_dir(test, node):
    return f"{test.get('scratch-dir', '/tmp/jepsen-toystore')}/{node}"


def peers(test):
    return ",".join(f"127.0.0.1:{node_port(test, n)}"
                    for n in test["nodes"])


class ToystoreDB(jdb.DB, jdb.Process, jdb.Pause, jdb.Primary,
                 jdb.LogFiles):
    """Deploys the server source and manages it with the real daemon
    helpers (start-stop-daemon, grepkill, SIGSTOP/SIGCONT) -- every
    protocol the combined nemesis packages drive (db.clj:11-41)."""

    def _marker(self, test, node):
        # unique argv marker (grepkill takes a quoted extended regex):
        # the deployed script's full path appears in this node's argv
        # and nobody else's
        return f"{node_dir(test, node)}/toystore.py"

    def setup(self, test, node):
        from ..control import util as cu
        # A predecessor run that died without teardown (crashed test
        # worker, kill -9) can leak a daemon still bound to this node's
        # port, serving stale state -- every later run's daemon then
        # fails to bind and reads hit the zombie, failing
        # linearizability with phantom values. The teardown marker is
        # path-specific on purpose (scratch dirs differ per run), so
        # clear the PORT's owner here regardless of path.
        cu.grepkill(
            f"toystore[.]py --port {node_port(test, node)}([^0-9]|$)")
        d = node_dir(test, node)
        c.exec_("mkdir", "-p", d)
        c.upload_string(SERVER_SRC, f"{d}/toystore.py")
        self.start(test, node)
        cu.await_tcp_port(node_port(test, node), timeout_s=10,
                          host="127.0.0.1")

    def teardown(self, test, node):
        self.kill(test, node)
        c.exec_star("rm", "-rf", node_dir(test, node))

    def start(self, test, node):
        from ..control import util as cu
        d = node_dir(test, node)
        argv = ["--port", str(node_port(test, node)),
                "--node-id", str(node_id(test, node)),
                "--peers", peers(test), "--data-dir", d]
        if test.get("stale"):
            # lag replication so follower reads observably trail the
            # primary (localhost replication is otherwise sub-ms and
            # the staleness rarely lands inside an op window)
            argv += ["--stale", "--repl-delay",
                     str(test.get("repl-delay", 0.3))]
        cu.start_daemon("/usr/bin/env", "python3", f"{d}/toystore.py",
                        *argv, logfile=f"{d}/daemon.out",
                        pidfile=f"{d}/toystore.pid")

    def kill(self, test, node):
        from ..control import util as cu
        cu.stop_daemon(pidfile=f"{node_dir(test, node)}/toystore.pid")
        cu.grepkill(self._marker(test, node))

    def pause(self, test, node):
        from ..control import util as cu
        cu.grepkill(self._marker(test, node), signal="STOP")

    def resume(self, test, node):
        from ..control import util as cu
        cu.grepkill(self._marker(test, node), signal="CONT")

    def primaries(self, test):
        return [test["nodes"][0]]

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        d = node_dir(test, node)
        return [f"{d}/toystore.log", f"{d}/daemon.out"]


class ToystoreClient(jclient.Client):
    """Line-protocol TCP client against this process's node."""

    def __init__(self, node=None):
        self.node = node

    def open(self, test, node):
        return ToystoreClient(node)

    def _call(self, test, line, timeout=2.0):
        with socket.create_connection(
                ("127.0.0.1", node_port(test, self.node)),
                timeout) as s:
            s.sendall((line + "\n").encode())
            s.settimeout(timeout)
            return s.makefile().readline().strip()

    def invoke(self, test, op):
        out = dict(op)
        f = op["f"]
        # independent-keys support (tutorial ch 6): a [k v] tuple value
        # addresses key k; plain values use the classic single key "x"
        v = op.get("value")
        if independent.is_tuple(v):
            key, payload = v.key, v.value
        else:
            key, payload = "x", v
        try:
            if f == "read":
                resp = self._call(test, f"R {key}")
                if resp.startswith("VAL"):
                    rv = resp.split()[1]
                    rv = None if rv == "nil" else int(rv)
                    out.update(type="ok",
                               value=independent.tuple_(key, rv)
                               if independent.is_tuple(v) else rv)
                else:
                    out.update(type="fail", error=resp)
            elif f == "write":
                resp = self._call(test, f"W {key} {payload}")
                out["type"] = "ok" if resp == "OK" else "info"
                if resp != "OK":
                    out["error"] = resp
            else:
                old, new = payload
                resp = self._call(
                    test,
                    f"CAS {key} {'nil' if old is None else old} {new}")
                if resp == "OK":
                    out["type"] = "ok"
                elif resp.startswith("FAIL"):
                    out["type"] = "fail"
                else:
                    out.update(type="info", error=resp)
        except OSError as e:
            # connection refused/timeout: reads definitely didn't
            # happen (idempotent -> safe to FAIL, keeping checker
            # concurrency down -- tutorial ch 6); writes are
            # indeterminate and must crash as info
            out.update(type="fail" if f == "read" else "info",
                       error=repr(e))
        return out


class ToystoreSetClient(ToystoreClient):
    """A grow-only set stored as a comma-joined string under one key,
    added to with a read/CAS read-modify-write loop (the reference
    tutorial's ``swap!`` pattern, doc/tutorial/08-set.md:209-228)."""

    KEY = "s"

    def open(self, test, node):
        return ToystoreSetClient(node)

    def invoke(self, test, op):
        out = dict(op)
        try:
            if op["f"] == "read":
                resp = self._call(test, f"R {self.KEY}")
                if not resp.startswith("VAL"):
                    out.update(type="fail", error=resp)
                    return out
                tok = resp.split()[1]
                out.update(type="ok",
                           value=[] if tok == "nil"
                           else [int(x) for x in tok.split(",")])
                return out
            # add: read-modify-CAS until this writer wins the race; a
            # spent contention budget is a clean FAIL (nothing acked)
            v = op["value"]
            for _ in range(16):
                resp = self._call(test, f"R {self.KEY}")
                if not resp.startswith("VAL"):
                    out.update(type="fail", error=resp)
                    return out
                cur = resp.split()[1]
                new = str(v) if cur == "nil" else f"{cur},{v}"
                resp = self._call(test, f"CAS {self.KEY} {cur} {new}")
                if resp == "OK":
                    out["type"] = "ok"
                    return out
                if not resp.startswith("FAIL"):
                    out.update(type="info", error=resp)
                    return out
            out.update(type="fail", error="cas-contention")
        except OSError as e:
            out.update(type="fail" if op["f"] == "read" else "info",
                       error=repr(e))
        return out


def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [random.randint(0, 4), random.randint(0, 4)]}


# -- workloads (tutorial chapters 6-8; reference doc/tutorial/08-set.md
# workload maps + etcdemo's register rewrite) --------------------------------

def _register_checker(opts):
    """The composed per-register checker both register workloads
    share (linearizable gate + timeline)."""
    return cc.compose({
        "linear": cks.linearizable(
            {"model": "cas-register",
             "algorithm": opts.get("algorithm", "competition")}),
        "timeline": timeline.html(),
    })


def register_workload(opts):
    """Single linearizable register on key "x": the tutorial's chapters
    1-5 workload, as a {client, checker, generator, final_generator}
    map."""
    rate = float(opts.get("rate", 20))
    return {
        "client": ToystoreClient(),
        "checker": _register_checker(opts),
        "generator": gen.stagger(1.0 / rate, gen.mix([r, w, cas])),
        "final_generator": None,
    }


#: threads per key for the independent-keys register workload; the
#: test's concurrency must be a multiple of this
INDEP_GROUP = 2


def register_indep_workload(opts):
    """The chapter-6 lift: the same register test over MANY independent
    keys via concurrent_generator; per-key subhistories are decided as
    one batched device call when the algorithm is jax-wgl."""
    rate = float(opts.get("rate", 20))
    per_key = int(opts.get("ops-per-key", 30))
    return {
        "client": ToystoreClient(),
        "checker": independent.checker(_register_checker(opts)),
        "generator": independent.concurrent_generator(
            INDEP_GROUP, itertools.count(),
            lambda k: gen.limit(per_key, gen.stagger(
                1.0 / rate, gen.mix([r, w, cas])))),
        "final_generator": None,
    }


def set_workload(opts):
    """Grow-only set: unique adds during faults, then heal and read
    everything back once per thread (reference doc/tutorial/08-set.md;
    checker.clj:240-291)."""
    rate = float(opts.get("rate", 20))
    counter = itertools.count(1)

    def add(test, ctx):
        return {"type": "invoke", "f": "add", "value": next(counter)}

    return {
        "client": ToystoreSetClient(),
        "checker": cks.set_checker(),
        "generator": gen.stagger(1.0 / rate, add),
        "final_generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {
    "register": register_workload,
    "register-indep": register_indep_workload,
    "set": set_workload,
}


def toystore_test(opts):
    test = dict(tst.noop_test())
    test.update(opts)
    nemesis_mode = opts.get("nemesis-mode", "kill")
    if nemesis_mode == "kill":
        nem = jnemesis.node_start_stopper(
            lambda test_, nodes: [random.choice(nodes)],
            lambda test_, node: ToystoreDB().kill(test_, node),
            lambda test_, node: ToystoreDB().start(test_, node))
    elif nemesis_mode == "pause":
        nem = jnemesis.node_start_stopper(
            lambda test_, nodes: [random.choice(nodes)],
            lambda test_, node: ToystoreDB().pause(test_, node),
            lambda test_, node: ToystoreDB().resume(test_, node))
    else:
        nem = jnemesis.noop
    wname = opts.get("workload", "register")
    if wname == "register-indep":
        # concurrent_generator groups INDEP_GROUP threads per key and
        # asserts the thread count divides evenly; the generic "1n"
        # default (3 nodes -> 3 threads) would crash it out of the
        # box, so round up to the next multiple
        conc = int(opts.get("concurrency") or 2 * INDEP_GROUP)
        conc += -conc % INDEP_GROUP
        test["concurrency"] = max(conc, INDEP_GROUP)
    workload = WORKLOADS[wname](opts)
    nem_gen = (None if nemesis_mode == "none" else
               gen.cycle(gen.sleep(2),
                         {"type": "info", "f": "start"},
                         gen.sleep(2),
                         {"type": "info", "f": "stop"}))
    main = gen.time_limit(
        opts.get("time-limit", 8),
        gen.nemesis(nem_gen, workload["generator"]))
    if workload.get("final_generator") is not None:
        # the chapter-8 shape: run the workload under faults, heal,
        # wait for recovery, THEN run the final reads -- a final read
        # racing the last adds (or a dead node) would misclassify
        # in-flight elements as lost
        generator = gen.phases(
            main,
            gen.log("healing cluster"),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.log("waiting for recovery"),
            gen.sleep(float(opts.get("recovery-time", 1))),
            gen.clients(workload["final_generator"]))
    else:
        generator = main
    test.update({
        # the parameters that change the test's MEANING go in its name
        # (reference doc/tutorial/07-parameters.md: "etcd q=true set")
        "name": ("toystore" if wname == "register"
                 else f"toystore-{wname}")
                + (" stale" if opts.get("stale") else ""),
        "ssh": {"local?": True},
        "os": jos.noop,
        "db": ToystoreDB(),
        "client": workload["client"],
        "nemesis": nem,
        "generator": generator,
        "checker": workload["checker"],
    })
    return test


def _opt_spec(parser):
    parser.add_argument("--workload", default="register",
                        choices=sorted(WORKLOADS))
    parser.add_argument("--algorithm", default="competition")
    parser.add_argument("--stale", action="store_true",
                        help="serve follower reads from the async local "
                             "copy (a real linearizability bug)")
    parser.add_argument("--nemesis-mode", default="kill",
                        choices=["kill", "pause", "none"])
    parser.add_argument("--rate", type=float, default=20,
                        help="approximate requests per second per "
                             "thread")
    parser.add_argument("--ops-per-key", type=int, default=30,
                        help="per-key op budget for register-indep")
    parser.add_argument("--recovery-time", type=float, default=1)
    parser.add_argument("--base-port", type=int, default=BASE_PORT)


def main(argv=None):
    cmds = {}
    cmds.update(cli.single_test_cmd({"test-fn": toystore_test,
                                     "opt-spec": _opt_spec}))
    cmds.update(cli.serve_cmd())
    cli.run(cmds, argv)


if __name__ == "__main__":
    cli.hard_main(main)
