/* strobe-time: oscillate the wall clock by +/- <delta> ms every <period> ms
 * for <duration> seconds, then restore it.
 *
 * TPU-framework analogue of the reference's clock strobe shim
 * (jepsen/resources/strobe-time.c).  Re-designed with flat int64
 * nanosecond arithmetic: we snapshot the offset between CLOCK_REALTIME
 * and CLOCK_MONOTONIC once at startup, then repeatedly set the wall
 * clock to monotonic + (offset or offset+delta), flipping each period.
 * Anchoring every write to the monotonic clock means the strobe itself
 * never accumulates drift, and the final restore is exact.
 *
 * Usage:  strobe-time <delta-ms> <period-ms> <duration-s>
 * Prints the number of clock writes performed.
 * Exit codes: 0 ok, 1 bad usage / read failure, 2 set failure,
 *             3 sleep failure.
 */
#define _POSIX_C_SOURCE 199309L
#include <stdio.h>
#include <stdlib.h>
#include <stdint.h>
#include <time.h>

static const int64_t NS = 1000000000LL;

static int64_t ts_to_ns(struct timespec t) {
  return (int64_t)t.tv_sec * NS + t.tv_nsec;
}

static struct timespec ns_to_ts(int64_t n) {
  struct timespec t;
  int64_t s = n / NS;
  int64_t r = n % NS;
  if (r < 0) { s -= 1; r += NS; }
  t.tv_sec = (time_t)s;
  t.tv_nsec = (long)r;
  return t;
}

static int64_t read_ns(clockid_t clk) {
  struct timespec t;
  if (clock_gettime(clk, &t) != 0) {
    perror("clock_gettime");
    exit(1);
  }
  return ts_to_ns(t);
}

static void write_wall_ns(int64_t n) {
  struct timespec t = ns_to_ts(n);
  if (clock_settime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_settime");
    exit(2);
  }
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr,
            "usage: %s <delta-ms> <period-ms> <duration-s>\n"
            "Every period ms, toggles the wall clock between its true "
            "value and true+delta ms, for duration seconds; then "
            "restores the clock and prints the number of writes.\n",
            argv[0]);
    return 1;
  }
  int64_t delta_ns  = (int64_t)(atof(argv[1]) * 1e6);
  int64_t period_ns = (int64_t)(atof(argv[2]) * 1e6);
  int64_t dur_ns    = (int64_t)(atof(argv[3]) * 1e9);

  /* wall = monotonic + base, sampled before we start meddling */
  int64_t base = read_ns(CLOCK_REALTIME) - read_ns(CLOCK_MONOTONIC);
  int64_t stop = read_ns(CLOCK_MONOTONIC) + dur_ns;

  struct timespec nap = ns_to_ts(period_ns);
  int64_t writes = 0;
  int skewed = 1;  /* first write applies the skew */

  while (read_ns(CLOCK_MONOTONIC) < stop) {
    int64_t off = skewed ? base + delta_ns : base;
    write_wall_ns(read_ns(CLOCK_MONOTONIC) + off);
    skewed = !skewed;
    writes++;
    struct timespec rem;
    if (nanosleep(&nap, &rem) != 0) {
      perror("nanosleep");
      exit(3);
    }
  }

  write_wall_ns(read_ns(CLOCK_MONOTONIC) + base);
  printf("%lld\n", (long long)writes);
  return 0;
}
