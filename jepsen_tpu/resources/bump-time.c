/* bump-time: jump the wall clock by <delta> milliseconds, once.
 *
 * TPU-framework analogue of the reference's one-shot clock bump shim
 * (jepsen/resources/bump-time.c).  Re-designed around clock_gettime /
 * clock_settime(CLOCK_REALTIME) with flat int64 nanosecond arithmetic
 * instead of timeval carry loops: one read, one add, one write.
 *
 * Usage:  bump-time <delta-ms>      (delta may be negative / fractional)
 * Prints the resulting wall-clock time as "<sec>.<nsec>" on success.
 * Exit codes: 0 ok, 1 bad usage / read failure, 2 set failure.
 */
#define _POSIX_C_SOURCE 199309L
#include <stdio.h>
#include <stdlib.h>
#include <stdint.h>
#include <time.h>

static const int64_t NS = 1000000000LL;

static int64_t ts_to_ns(struct timespec t) {
  return (int64_t)t.tv_sec * NS + t.tv_nsec;
}

static struct timespec ns_to_ts(int64_t n) {
  struct timespec t;
  /* floor-divide so negative totals still yield tv_nsec in [0, NS) */
  int64_t s = n / NS;
  int64_t r = n % NS;
  if (r < 0) { s -= 1; r += NS; }
  t.tv_sec = (time_t)s;
  t.tv_nsec = (long)r;
  return t;
}

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 1;
  }
  int64_t delta_ns = (int64_t)(atof(argv[1]) * 1e6);

  struct timespec now;
  if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
    perror("clock_gettime");
    return 1;
  }
  struct timespec bumped = ns_to_ts(ts_to_ns(now) + delta_ns);
  if (clock_settime(CLOCK_REALTIME, &bumped) != 0) {
    perror("clock_settime");
    return 2;
  }
  if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
    perror("clock_gettime");
    return 1;
  }
  printf("%lld.%09ld\n", (long long)now.tv_sec, now.tv_nsec);
  return 0;
}
