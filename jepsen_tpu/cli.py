"""Command line interface: a default main and utilities for suites to
build their own test runners (reference jepsen/src/jepsen/cli.clj).

Exit codes (cli.clj:129-139):

  0     all tests passed
  1     some test failed
  2     some test had unknown validity
  254   invalid arguments
  255   internal error
"""

from __future__ import annotations

import argparse
import logging
import re
import sys
import time
import traceback

from . import core, store

logger = logging.getLogger(__name__)

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


class CliError(Exception):
    pass


def add_test_opts(parser):
    """The shared test option spec (cli.clj:64-111 test-opt-spec)."""
    parser.add_argument("-n", "--node", action="append", default=None,
                        metavar="HOSTNAME",
                        help="Node(s) to run test on; repeatable.")
    parser.add_argument("--nodes", default=None, metavar="NODE_LIST",
                        help="Comma-separated list of node hostnames.")
    parser.add_argument("--nodes-file", default=None, metavar="FILENAME",
                        help="File of node hostnames, one per line.")
    parser.add_argument("--username", default="root",
                        help="Username for logins")
    parser.add_argument("--password", default="root",
                        help="Password for sudo access")
    parser.add_argument("--strict-host-key-checking", action="store_true",
                        help="Whether to check host keys")
    parser.add_argument("--no-ssh", action="store_true",
                        help="Don't establish SSH connections (dummy "
                             "remote).")
    parser.add_argument("--ssh-private-key", default=None, metavar="FILE",
                        help="Path to an SSH identity file")
    parser.add_argument("--concurrency", default="1n", metavar="NUMBER",
                        help="Worker count: an integer, optionally followed"
                             " by n (e.g. 3n) to multiply by node count.")
    parser.add_argument("--leave-db-running", action="store_true",
                        help="Leave the database running for inspection.")
    parser.add_argument("--logging-json", action="store_true",
                        help="JSON structured jepsen.log output.")
    parser.add_argument("--test-count", type=int, default=1,
                        help="How many times to repeat the test.")
    parser.add_argument("--time-limit", type=int, default=60,
                        metavar="SECONDS",
                        help="How long the test runs, excluding setup and "
                             "teardown.")
    parser.add_argument("--op-timeout-ms", type=float, default=None,
                        metavar="MS",
                        help="Wedged-worker watchdog: ops blocking past "
                             "this deadline complete as :info "
                             "harness-timeout and their worker is "
                             "replaced (default: off).")
    parser.add_argument("--hard-time-limit", type=float, default=None,
                        metavar="SECONDS",
                        help="Hard harness deadline: abort gracefully, "
                             "salvage and check the partial history "
                             "(default: off).")
    parser.add_argument("--abort-grace", type=float, default=None,
                        metavar="SECONDS",
                        help="How long outstanding ops may drain after "
                             "an abort (SIGINT/SIGTERM/hard deadline) "
                             "before being written off as :info.")
    parser.add_argument("--monitor", action="store_true",
                        help="Run the streaming linearizability monitor "
                             "concurrently with the test: a proven "
                             "violation aborts the run the moment it is "
                             "detected instead of after the full offline "
                             "check (default: off).")
    parser.add_argument("--monitor-chunk", type=int, default=None,
                        metavar="N",
                        help="How many completed ops the monitor batches "
                             "per incremental check (default: 64; "
                             "requires --monitor).")
    parser.add_argument("--no-searchplan", action="store_true",
                        help="Disable the search planner "
                             "(analysis/searchplan.py): check every "
                             "history as one flat device search instead "
                             "of partitioning it at keys and sealed "
                             "quiescent cuts (default: planning on).")
    parser.add_argument("--searchplan-partitions", default=None,
                        metavar="NAMES",
                        help="Comma-separated partition predicates the "
                             "planner applies (default: "
                             "per-key,crash-segments; planlint PL015 "
                             "rejects unknown names).")
    parser.add_argument("--profile", action="store_true",
                        help="Capture an XLA profiler trace around the "
                             "run's device searches, persisted next to "
                             "trace.jsonl (bounded by --profile-max-s; "
                             "contained: a run whose profiler is "
                             "unavailable proceeds unprofiled).")
    parser.add_argument("--profile-dir", default=None, metavar="DIR",
                        help="Where the XLA capture lands (default: "
                             "<run dir>/profile; PL019 rejects "
                             "unwritable locations).")
    parser.add_argument("--profile-max-s", type=float, default=None,
                        metavar="SECONDS",
                        help="Capture wall bound: the profiler stops "
                             "after this even if the search is still "
                             "running (default 120).")
    parser.add_argument("--progress-interval-s", type=float,
                        default=None, metavar="SECONDS",
                        help="Minimum interval between search-progress "
                             "trace emissions / journal flushes "
                             "(default: every host->device dispatch; "
                             "PL019 warns below the ~1 s heartbeat "
                             "cadence).")
    parser.add_argument("--lint", action="store_true",
                        help="Dry run: statically validate the test plan "
                             "(planlint) and exit without contacting any "
                             "node.")
    return parser


def parse_concurrency(value, nodes):
    """\"3n\" -> 3 * node count; plain integers parse as-is
    (cli.clj:150-165)."""
    m = re.fullmatch(r"(\d+)(n?)", str(value))
    if not m:
        raise CliError(f"--concurrency {value} should be an integer "
                       "optionally followed by n")
    unit = len(nodes) if m.group(2) == "n" else 1
    return int(m.group(1)) * unit


def parse_nodes(opts):
    """Merge --node/--nodes/--nodes-file into one list
    (cli.clj:167-202)."""
    out = []
    if opts.get("nodes-file"):
        with open(opts["nodes-file"]) as f:
            out += [ln.strip() for ln in f if ln.strip()]
    if opts.get("nodes"):
        out += [n.strip() for n in str(opts["nodes"]).split(",")]
    if opts.get("node"):
        out += list(opts["node"])
    return out or list(DEFAULT_NODES)


def test_opt_fn(opts):
    """Standard option pipeline: merge node specs, build the :ssh map,
    parse concurrency (cli.clj:242-253 test-opt-fn)."""
    opts = dict(opts)
    nodes = parse_nodes(opts)
    opts["nodes"] = nodes
    opts["concurrency"] = parse_concurrency(
        opts.get("concurrency", "1n"), nodes)
    opts["ssh"] = {
        "dummy?": bool(opts.pop("no-ssh", False)),
        "username": opts.pop("username", "root"),
        "password": opts.pop("password", "root"),
        "strict-host-key-checking":
            opts.pop("strict-host-key-checking", False),
        "private-key-path": opts.pop("ssh-private-key", None),
    }
    opts["leave-db-running?"] = opts.pop("leave-db-running", False)
    opts["logging-json?"] = opts.pop("logging-json", False)
    opts["lint?"] = opts.pop("lint", False)
    # robustness knobs (jepsen_tpu.robust): map CLI names onto the test
    # keys core.run/interpreter watch; absent flags leave the keys out
    # entirely so the features stay off
    for flag, key in (("op-timeout-ms", "op-timeout-ms"),
                      ("hard-time-limit", "time-limit-s"),
                      ("abort-grace", "abort-grace-s")):
        v = opts.pop(flag, None)
        if v is not None:
            opts[key] = v
    # streaming monitor (jepsen_tpu.monitor): --monitor turns it on,
    # --monitor-chunk sets the batch size. A bare --monitor-chunk is
    # deliberately KEPT on the map so planlint PL013 can flag the
    # ignored knob instead of it vanishing silently.
    monitor = opts.pop("monitor", False)
    chunk = opts.pop("monitor-chunk", None)
    if monitor:
        opts["monitor"] = {"chunk": chunk} if chunk is not None else True
    elif chunk is not None:
        opts["monitor-chunk"] = chunk
    # device introspection (jepsen_tpu.obs.profile / obs.search):
    # --profile maps onto the profile? key core.analyze watches;
    # the dir/bound/cadence knobs pass through under their test names
    if opts.pop("profile", False):
        opts["profile?"] = True
    for flag, key in (("profile-dir", "profile-dir"),
                      ("profile-max-s", "profile-max-s"),
                      ("progress-interval-s", "progress-interval-s")):
        v = opts.pop(flag, None)
        if v is not None:
            opts[key] = v
    # search planner (jepsen_tpu.analysis.searchplan): planning is on
    # by default, so only an explicit opt-out / predicate list lands
    # on the map (PL015 warns on explicit-enable without a plannable
    # checker, so we avoid stamping every test map "explicitly on")
    if opts.pop("no-searchplan", False):
        opts["searchplan?"] = False
    preds = opts.pop("searchplan-partitions", None)
    if preds is not None:
        opts["searchplan-partitions"] = [p.strip()
                                        for p in str(preds).split(",")
                                        if p.strip()]
    opts.pop("node", None)
    opts.pop("nodes-file", None)
    return opts


def _ns_to_opts(ns):
    return {k.replace("_", "-"): v for k, v in vars(ns).items()}


def _exit_for_valid(valid):
    if valid is False:
        return 1
    if valid != True:  # noqa: E712 - "unknown" and None both count
        return 2
    return 0


def single_test_cmd(opts):
    """Subcommands ``test`` (run + analyze) and ``analyze`` (re-check the
    latest stored history with a freshly-built test map)
    (cli.clj:352-427). opts: {"test-fn": options -> test map,
    "opt-spec": fn(parser), "opt-fn": fn(options)}."""
    test_fn = opts["test-fn"]

    def lint_test(options):
        """--lint dry run: planlint the built test map, print the
        report, exit 0 (clean) / 1 (error diagnostics). No node is
        contacted, no store directory is written."""
        from . import analysis
        test = core.prepare_test(test_fn(options))
        diags = analysis.lint_plan(test)
        print(analysis.render_text(
            diags, title=f"plan lint: {test.get('name')}"))
        sys.exit(1 if analysis.errors(diags) else 0)

    def run_test(options):
        if options.get("lint?"):
            return lint_test(options)
        for _i in range(options.get("test-count", 1)):
            test = core.run(test_fn(options))
            code = _exit_for_valid(
                (test.get("results") or {}).get("valid"))
            if code:
                sys.exit(code)

    def run_analyze(options):
        if options.get("lint?"):
            # --lint means "never touch nodes or stored state" on
            # either subcommand; without this, analyze would silently
            # ignore the flag and kick off a full re-check
            return lint_test(options)
        cli_test = test_fn(options)
        stored = store.latest()
        if stored is None:
            raise CliError("Not sure what the last test was")
        if stored.get("name") != cli_test.get("name"):
            raise CliError(
                f"Stored test ({stored.get('name')}) and CLI test "
                f"({cli_test.get('name')}) have different names; aborting")
        test = {**cli_test,
                **{k: v for k, v in stored.items() if k != "results"}}
        test = core.analyze(test)
        sys.exit(_exit_for_valid(
            (test.get("results") or {}).get("valid")))

    return {
        "test": {"opt-spec": opts.get("opt-spec"),
                 "opt-fn": opts.get("opt-fn"),
                 "run": run_test,
                 "help": "Run a test and analyze it."},
        "analyze": {"opt-spec": opts.get("opt-spec"),
                    "opt-fn": opts.get("opt-fn"),
                    "run": run_analyze,
                    "help": "Re-analyze the latest stored history."},
    }


def test_all_run_tests(tests):
    """Run tests; map of outcome (True/False/'unknown'/'crashed') to
    entries (cli.clj:429-445). Entries are store paths, or
    {"cell": ..., "path": ...} dicts for campaign cells so sweep output
    stays attributable. prepare_test runs INSIDE the try: one malformed
    test plan records as "crashed" instead of taking the suite down."""
    results = {}
    for test in tests:
        cell = None
        try:
            test = core.prepare_test(test)
            cell = (test.get("campaign") or {}).get("cell") \
                if isinstance(test.get("campaign"), dict) else None
            done = core.run(test)
            outcome = (done.get("results") or {}).get("valid")
            if outcome is not True and outcome is not False:
                outcome = "unknown"
        except Exception:  # noqa: BLE001
            logger.warning("Test crashed\n%s", traceback.format_exc())
            outcome = "crashed"
        try:
            path = store.path(test)
        except (AssertionError, AttributeError, KeyError, TypeError):
            path = "<unnamed>"
        entry = {"cell": cell, "path": path} if cell else path
        results.setdefault(outcome, []).append(entry)
    return results


def _entry_str(entry):
    """Render one outcome-group entry: plain path, or cell-id-tagged
    path for campaign cells."""
    if isinstance(entry, dict):
        cell = entry.get("cell")
        path = entry.get("path") or "<unnamed>"
        return f"[{cell}] {path}" if cell else str(path)
    return str(entry)


def _result_group(results, key):
    """Entries for one outcome group. Accepts both key spellings:
    test_all_run_tests builds bool-keyed maps (reference shape), while
    campaign report.results_map uses str() keys so the map survives a
    report.json round trip."""
    return results.get(key) or results.get(str(key)) or []


def test_all_print_summary(results):
    """Print outcome groups + counts (cli.clj:447-476). Campaign cells
    print with their cell ids so sweep output is attributable."""
    for title, key in (("Successful tests", True),
                       ("Indeterminate tests", "unknown"),
                       ("Aborted tests", "aborted"),
                       ("Crashed tests", "crashed"),
                       ("Failed tests", False)):
        group = _result_group(results, key)
        if group:
            print(f"\n# {title}\n")
            for p in group:
                print(_entry_str(p))
    print()
    print(len(_result_group(results, True)), "successes")
    print(len(_result_group(results, "unknown"))
          + len(_result_group(results, "aborted")), "unknown")
    print(len(_result_group(results, "crashed")), "crashed")
    print(len(_result_group(results, False)), "failures")
    return results


def test_all_exit_code(results):
    """255 crashed > 2 unknown > 1 failed > 0 (cli.clj:478-485).
    Aborted campaign cells have no verdict, so they rank with
    unknown."""
    if _result_group(results, "crashed"):
        return 255
    if _result_group(results, "unknown") \
            or _result_group(results, "aborted"):
        return 2
    if _result_group(results, False):
        return 1
    return 0


def campaign_exit_code(report):
    """Exit code for a whole campaign. An aborted campaign ranks as
    indeterminate (2) even when every *recorded* cell passed -- a
    SIGINT landing between cells leaves the unrun cells with no
    journal record at all, so the results map alone under-reports.
    Crashed cells still dominate (255)."""
    code = test_all_exit_code(report.get("results") or {})
    if report.get("status") == "aborted" and code in (0, 1):
        code = 2
    return code


def _parse_device_slots(value):
    """--device-slots: a positive integer, or the literal "auto" (the
    campaign subcommand derives the count from the capacity plan's
    HBM footprints vs --device-mem-budget)."""
    v = str(value).strip()
    if v == "auto":
        return "auto"
    try:
        return int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--device-slots {value!r} should be an integer or "
            "'auto'") from None


def parse_bytes(value):
    """A byte count with optional K/M/G/T suffix ("16G" -> 2**34)."""
    s = str(value).strip()
    mult = 1
    suffixes = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}
    if s and s[-1].upper() in suffixes:
        mult = suffixes[s[-1].upper()]
        s = s[:-1]
    try:
        return int(float(s) * mult)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"byte count {value!r} should be a number with an "
            "optional K/M/G/T suffix") from None


def _add_campaign_opts(parser, axes=False):
    parser.add_argument("--parallel", type=int, default=1, metavar="N",
                        help="Worker-pool width: how many test cells "
                             "run concurrently (campaign scheduler).")
    parser.add_argument("--device-slots", type=_parse_device_slots,
                        default=1, metavar="N",
                        help="How many device checker searches may run "
                             "at once (one per accelerator), or "
                             "'auto' to derive the count from the "
                             "capacity plan (requires "
                             "--device-mem-budget; campaign "
                             "subcommand only).")
    parser.add_argument("--campaign-id", default=None, metavar="ID",
                        help="Campaign id (store/campaigns/<id>/); "
                             "default: derived from the start time.")
    parser.add_argument("--resume", action="store_true",
                        help="Resume a campaign: skip cells whose "
                             "outcome is already journaled; without "
                             "--campaign-id, the most recent campaign "
                             "is resumed.")
    parser.add_argument("--no-ledger", action="store_true",
                        help="Don't persist the compile-reuse ledger "
                             "to store/compile_ledger/ (it is "
                             "persisted, and shared across campaign "
                             "processes, by default).")
    parser.add_argument("--backends", default=None,
                        metavar="TIER1,TIER2",
                        help="Backend failover ladder consulted per "
                             "cell (tiers: tpu, gpu, cpu; e.g. "
                             "tpu,gpu,cpu). A down accelerator "
                             "degrades cells to the next tier instead "
                             "of crashing them.")
    if axes:
        parser.add_argument("--workers", default=None,
                            metavar="HOST1,HOST2",
                            help="Fleet mode: lease cells to these "
                                 "worker hosts over the SSH control "
                                 "plane ('local' = loopback worker "
                                 "processes; name=host gives explicit "
                                 "worker ids).")
        parser.add_argument("--lease", type=float, default=None,
                            metavar="SECONDS",
                            help="Fleet lease TTL: a cell exec running "
                                 "past this is presumed dead and its "
                                 "cell is stolen by another worker "
                                 "(default 600).")
        parser.add_argument("--max-leases", type=int, default=None,
                            metavar="N",
                            help="How many leases a cell may burn "
                                 "before it journals as crashed "
                                 "(default 3; raise it for chaos "
                                 "soaks, where injected faults and "
                                 "real recoveries share the budget).")
        parser.add_argument("--serve", action="store_true",
                            help="Serve the web UI + submission API "
                                 "(POST /api/check, /api/campaigns) "
                                 "alongside the campaign, so its "
                                 "status is pollable while it runs.")
        parser.add_argument("--serve-port", type=int, default=8080,
                            metavar="PORT",
                            help="Port for --serve (default 8080).")
        parser.add_argument("--serve-ip", default="0.0.0.0",
                            metavar="IP",
                            help="Bind address for --serve (default "
                                 "0.0.0.0; a non-loopback bind "
                                 "requires --auth-token, PL016).")
        parser.add_argument("--auth-token", default=None,
                            metavar="TOKEN",
                            help="Bearer token /api requests must "
                                 "present (401 otherwise) when "
                                 "--serve is on.")
        parser.add_argument("--no-coalesce", action="store_true",
                            help="Disable cross-tenant batch "
                                 "coalescing for --serve: every "
                                 "accepted /api/check runs its own "
                                 "device search instead of merging "
                                 "with queued strangers (default: "
                                 "coalescing on).")
        parser.add_argument("--coalesce-window-ms", type=float,
                            default=None, metavar="MS",
                            help="How long a submitted check may wait "
                                 "for batchmates before its device "
                                 "batch closes anyway (default 25; "
                                 "PL020 rejects non-positive "
                                 "values).")
        parser.add_argument("--coalesce-max-segments", type=int,
                            default=None, metavar="N",
                            help="Segments per coalesced device batch "
                                 "past which the batch closes early "
                                 "(default 32; PL020 rejects "
                                 "non-positive values).")
        parser.add_argument("--worker-store", default=None,
                            metavar="DIR",
                            help="Store directory the fleet WORKERS "
                                 "write runs into (default: the "
                                 "coordinator's own store). Pointing "
                                 "it elsewhere gives workers isolated "
                                 "stores and turns artifact sync on "
                                 "for loopback workers too.")
        parser.add_argument("--sync-timeout", type=float, default=None,
                            metavar="SECONDS",
                            help="Wall bound for mirroring one remote "
                                 "cell's run directory into the "
                                 "coordinator store (default 120).")
        parser.add_argument("--telemetry-flush-ms", type=float,
                            default=None, metavar="MS",
                            help="Crash-safe telemetry journal flush "
                                 "interval for every cell run "
                                 "(default 500; PL017 rejects "
                                 "non-positive values).")
        parser.add_argument("--no-trace-merge", action="store_true",
                            help="Skip folding the per-run traces "
                                 "into campaign_trace.jsonl at fleet "
                                 "finalize (the merged Perfetto "
                                 "timeline with one lane per worker, "
                                 "clocks skew-normalized).")
        parser.add_argument("--fleetlint", default="on",
                            metavar="MODE",
                            help="Control-plane audit mode: 'on' "
                                 "(default) replays the finished "
                                 "campaign's artifacts against the "
                                 "fleet protocol (analysis.fleetlint "
                                 "-> fleet_analysis.json) and "
                                 "preflights --resume; 'off' skips "
                                 "both. Unknown values are a PL018 "
                                 "error.")
        parser.add_argument("--chaos-profile", default=None,
                            metavar="NAME[:SEED]",
                            help="Fleet chaos soak: inject a seeded, "
                                 "deterministic fault schedule "
                                 "(exit-255s, hangs, partial "
                                 "downloads, worker kill -9s) into "
                                 "the dispatch control plane; "
                                 "profiles: none, flaky-exec, "
                                 "lossy-sync, soak, coordinator-kill, "
                                 "txn-skew (per-worker clock skew for "
                                 "the transactional family) "
                                 "(e.g. soak:42).")
        parser.add_argument("--coordinator-lease-s", type=float,
                            default=None, metavar="SECONDS",
                            help="Coordinator HA (fleet.ha): renew a "
                                 "journaled coordinator-role lease "
                                 "with this TTL so a standby can "
                                 "detect coordinator death and take "
                                 "the campaign over (default: HA "
                                 "off; PL024 rejects non-positive "
                                 "values).")
        parser.add_argument("--takeover-grace-s", type=float,
                            default=None, metavar="SECONDS",
                            help="Extra quiet time a standby waits "
                                 "past the coordinator lease TTL "
                                 "before fencing (default 5; PL024 "
                                 "rejects non-positive values).")
        parser.add_argument("--standby", action="store_true",
                            help="Run as a standby coordinator: tail "
                                 "the campaign journal read-only; on "
                                 "coordinator-lease expiry, fence the "
                                 "dead coordinator (journaled "
                                 "takeover record) and resume the "
                                 "campaign. Without --campaign-id "
                                 "the most recent campaign is "
                                 "tailed.")
        parser.add_argument("--axis", action="append", default=[],
                            metavar="NAME=V1,V2,...",
                            help="A sweep axis: option NAME takes each "
                                 "listed value (repeatable; numeric "
                                 "values are coerced).")
        parser.add_argument("--seeds", type=int, default=None,
                            metavar="N",
                            help="Shorthand for --axis "
                                 "seed=0,1,...,N-1.")
        parser.add_argument("--capacity", default=None, metavar="MODE",
                            help="Static capacity preflight "
                                 "(analysis.capplan): 'plan' persists "
                                 "capacity_plan.json (predicted "
                                 "compile shapes, HBM footprints, "
                                 "int32-wall proximity) and runs the "
                                 "prediction oracle at finalize; "
                                 "'warn' also prints the table + "
                                 "CP diagnostics; 'enforce' refuses "
                                 "the campaign on CP/PL021 errors. "
                                 "plan/warn can never change an "
                                 "outcome or exit code.")
        parser.add_argument("--device-mem-budget", type=parse_bytes,
                            default=None, metavar="BYTES",
                            help="Usable device HBM in bytes "
                                 "(suffixes K/M/G/T accepted, e.g. "
                                 "16G): capplan checks per-cell "
                                 "footprints against it (CP004/"
                                 "CP005) and --device-slots auto "
                                 "derives from it.")


def test_all_cmd(opts):
    """Subcommand ``test-all``: run a suite of tests
    (cli.clj:487-515). opts: {"tests-fn": options -> [test maps], ...}.

    ``--parallel N`` / ``--resume`` route the suite through the
    campaign scheduler (jepsen_tpu.campaign): each test becomes a cell,
    outcomes journal to store/campaigns/<id>/, and a rerun with
    --resume skips completed cells."""
    tests_fn = opts["tests-fn"]

    def add_opts(parser):
        _add_campaign_opts(parser)
        if opts.get("opt-spec"):
            opts["opt-spec"](parser)

    def run_all(options):
        if options.get("device-slots") == "auto":
            raise CliError("--device-slots auto derives from a "
                           "capacity plan over a sweep matrix; use "
                           "the campaign subcommand")
        # ANY campaign flag routes through the scheduler -- a
        # --campaign-id or --device-slots on the legacy sequential path
        # would be silently ignored (no journal, nothing to resume)
        if options.get("parallel", 1) > 1 or options.get("resume") \
                or options.get("campaign-id") \
                or (options.get("device-slots") or 1) > 1:
            from . import campaign
            cells, seen = [], {}
            for i, t in enumerate(tests_fn(options)):
                cid = str(t.get("name") or f"test-{i}")
                seen[cid] = seen.get(cid, 0) + 1
                if seen[cid] > 1:
                    cid = f"{cid}#{seen[cid]}"
                cells.append({"id": cid, "test": t})
            try:
                report = campaign.run_cells(
                    cells, parallel=options.get("parallel", 1),
                    device_slots=options.get("device-slots", 1),
                    campaign_id=options.get("campaign-id"),
                    resume=bool(options.get("resume")),
                    ledger=not options.get("no-ledger"),
                    backends=options.get("backends") or None)
            except campaign.CampaignError as e:
                raise CliError(str(e)) from e
            print(campaign.report.render_text(report))
            test_all_print_summary(report["results"])
            sys.exit(campaign_exit_code(report))
        results = test_all_run_tests(tests_fn(options))
        test_all_print_summary(results)
        sys.exit(test_all_exit_code(results))

    return {"test-all": {"opt-spec": add_opts,
                         "opt-fn": opts.get("opt-fn"),
                         "run": run_all,
                         "help": "Run a whole suite of tests."}}


def _coerce_axis_value(v):
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


def parse_axes(specs, seeds=None):
    """--axis NAME=V1,V2 specs -> {name: [values]}; ``seeds`` adds the
    seed axis."""
    axes = {}
    for spec in specs or []:
        name, eq, values = str(spec).partition("=")
        if not eq or not name:
            raise CliError(f"--axis {spec!r} should be NAME=V1,V2,...")
        axes[name] = [_coerce_axis_value(v)
                      for v in values.split(",") if v != ""]
    if seeds:
        axes.setdefault("seed", list(range(int(seeds))))
    return axes


#: option keys that are coordinator-local wiring, never shipped to a
#: fleet worker's cell spec
_FLEET_LOCAL_OPTS = {
    "argv", "workers", "lease", "max-leases", "serve", "serve-port",
    "serve-ip",
    "auth-token", "worker-store", "sync-timeout", "chaos-profile",
    "fleetlint", "no-ledger", "backends", "axis", "seeds", "parallel",
    "device-slots", "campaign-id", "resume", "lint?",
    "no-coalesce", "coalesce-window-ms", "coalesce-max-segments",
    "capacity", "device-mem-budget",
    "standby", "coordinator-lease-s", "takeover-grace-s",
}


def _jsonable_options(options):
    """The JSON-serializable subset of the parsed options: what a
    fleet worker's cell spec carries so the remote build sees the same
    base options the coordinator would have used locally."""
    import json as _json
    out = {}
    for k, v in options.items():
        if k in _FLEET_LOCAL_OPTS:
            continue
        try:
            _json.dumps(v)
        except (TypeError, ValueError):
            continue
        out[k] = v
    return out


def campaign_cmd(opts):
    """Subcommand ``campaign``: expand a sweep matrix over the suite's
    test-fn and run it as a parallel, resumable campaign. opts:
    {"test-fn": options -> test map, "opt-spec": fn(parser),
    "opt-fn": fn(options)}.

        python -m jepsen_tpu campaign --no-ssh \\
            --axis workload=register,bank --seeds 3 --parallel 4

    Axis names are option keys: each cell rebuilds the test map from
    the base options with that cell's axis values overlaid (a ``seed``
    axis also seeds the global RNG before the build). ``--lint`` dry
    runs the PL012 matrix validation and prints the cell ids."""
    test_fn = opts["test-fn"]

    def add_opts(parser):
        _add_campaign_opts(parser, axes=True)
        if opts.get("opt-spec"):
            opts["opt-spec"](parser)

    def run_campaign(options):
        import random
        import threading

        from . import campaign
        from . import analysis

        # --lint with an EXISTING --campaign-id audits that campaign
        # from disk (fleetlint over its journal/traces) instead of dry
        # running a matrix: `campaign --lint --campaign-id soak` is
        # the post-hoc "did the control plane behave?" question
        import os
        cid = options.get("campaign-id")
        if options.get("lint?") and cid \
                and os.path.exists(store.campaign_path(cid,
                                                       "campaign.json")):
            from .analysis import fleetlint
            _report, diags = fleetlint.audit(cid)
            print(analysis.render_text(
                diags, title=f"fleetlint audit: {cid}"))
            sys.exit(1 if analysis.errors(diags) else 0)

        axes = parse_axes(options.get("axis"), options.get("seeds"))
        matrix = {"axes": axes}
        cells_plan = campaign.plan.expand(matrix)
        diags = campaign.plan.lint(matrix)
        # fleet-config preflight (PL014) rides along whenever any
        # fleet-facing knob is set; run_fleet re-checks, but --lint
        # must surface the findings without contacting a host
        workers = None
        if options.get("workers"):
            from . import fleet
            workers = fleet.parse_workers(options["workers"],
                                          ssh=options.get("ssh"))
        fleet_cfg = {
            "lease-s": options.get("lease"),
            "serve?": bool(options.get("serve")),
            # "auto" resolves AFTER the capacity preflight below;
            # PL021 owns its validation, so PL014's integer rule
            # must not see the placeholder
            "device-slots": None
            if options.get("device-slots") == "auto"
            else options.get("device-slots"),
            "backends": [t.strip() for t in
                         str(options["backends"]).split(",")
                         if t.strip()]
            if options.get("backends") else None,
            "time-limit": options.get("time-limit"),
        }
        if workers is not None:
            fleet_cfg["workers"] = [w.id for w in workers]
        if workers is not None or options.get("serve") \
                or options.get("backends"):
            diags += analysis.planlint.lint_fleet(fleet_cfg)
        # service/sync robustness preflight (PL016) rides along the
        # same way whenever serving or fleet sync knobs are in play
        if workers is not None or options.get("serve"):
            diags += analysis.planlint.lint_service({
                "serve?": bool(options.get("serve")),
                "serve-ip": options.get("serve-ip"),
                "auth-token?": bool(options.get("auth-token")),
                "sync-timeout-s": options.get("sync-timeout"),
                "lease-s": options.get("lease"),
            })
        # telemetry-plane preflight (PL017) rides along the same way:
        # flush-knob sanity always, the exposed-metrics and
        # merge-without-sync rules whenever serving / fleet-dispatching
        diags += analysis.planlint.lint_telemetry({
            "telemetry-flush-ms": options.get("telemetry-flush-ms"),
            "metrics?": bool(options.get("serve")),
            "serve-ip": options.get("serve-ip"),
            "auth-token?": bool(options.get("auth-token")),
            "trace-merge?": workers is not None
            and not options.get("no-trace-merge"),
        })
        chaos_prof = None
        if options.get("chaos-profile"):
            from .fleet import chaos as fchaos
            try:
                chaos_prof = fchaos.parse(options["chaos-profile"])
            except ValueError as e:
                raise CliError(str(e)) from None
        # searchplan knob preflight (PL015) rides along over the base
        # options every cell is built from, mirroring run_fleet
        diags += analysis.planlint.searchplan_diags(options)
        # device-introspection knob preflight (PL019) rides the same
        # way: profile / progress-cadence mistakes surface at --lint
        diags += analysis.planlint.lint_introspection(options)
        # verdict-certification knob preflight (PL023) rides the same
        # way: bad sample counts / cross-check budgets surface at
        # --lint, and the skip-offline? backstop note lands here too
        diags += analysis.planlint.lint_certify(options)
        # fleetlint knob preflight (PL018, knob half) rides the same
        # way; the journal half runs inside run_fleet's resume path
        diags += analysis.planlint.lint_fleetlint(
            {"fleetlint": options.get("fleetlint")})
        # cross-tenant coalescing preflight (PL020) rides the same
        # way whenever the service would be co-launched
        diags += analysis.planlint.lint_coalesce({
            "coalesce?": bool(options.get("serve"))
            and not options.get("no-coalesce"),
            "coalesce-window-ms": options.get("coalesce-window-ms"),
            "coalesce-max-segments":
                options.get("coalesce-max-segments"),
            "device-slots": options.get("device-slots"),
            "engine": options.get("engine"),
        })
        # coordinator-HA preflight (PL024) rides the same way: broken
        # failover math (a coordinator-kill with HA off, a standby
        # with no journal to tail) surfaces at --lint, before any
        # role lease is claimed or standby started
        standby = bool(options.get("standby"))
        standby_cid = (options.get("campaign-id")
                       or store.latest_campaign()) if standby else None
        diags += analysis.planlint.lint_ha({
            "ha?": options.get("coordinator-lease-s") is not None
            or standby,
            "coordinator-lease-s": options.get("coordinator-lease-s"),
            "takeover-grace-s": options.get("takeover-grace-s"),
            "standby?": standby,
            "store-reachable?": bool(
                standby_cid and os.path.exists(store.campaign_path(
                    standby_cid, "campaign.json"))) if standby
            else None,
            "chaos-coordinator-kill?": bool(
                getattr(chaos_prof, "coordinator_kill", 0)),
            "lease-s": options.get("lease"),
        })
        # capacity preflight (PL021 + CP001-CP008, analysis.capplan):
        # the whole-campaign static plan -- every compile shape, HBM
        # footprint, and int32-wall crossing predicted from the
        # matrix x ModelSpecs before anything runs. plan/warn are
        # contained (their findings never gate the run); only enforce
        # may refuse, and only on error diagnostics
        capacity = options.get("capacity")
        budget = options.get("device-mem-budget")
        slots = options.get("device-slots")
        cap_plan, cap_diags = None, []
        if capacity is not None or budget is not None \
                or slots == "auto":
            from .analysis import capplan
            try:
                cap_plan, cap_diags = capplan.preflight(
                    cells_plan, base=options, mode=capacity,
                    device_mem_budget=budget, device_slots=slots)
            except capplan.CapacityError as e:
                if not options.get("lint?"):
                    raise CliError(str(e)) from None
                # --lint reports the refusal instead of raising past
                # the lint output
                cap_diags = e.diagnostics
        if options.get("lint?"):
            print(analysis.render_text(diags + cap_diags,
                                       title="campaign lint:"))
            if cap_plan is not None:
                from .analysis import capplan
                print(capplan.render_table(cap_plan))
            for c in cells_plan:
                print(c["id"])
            sys.exit(1 if analysis.errors(diags + cap_diags) else 0)
        if analysis.errors(diags):
            # capacity diagnostics deliberately stay out of this gate:
            # CP/PL021 findings refuse a run only via --capacity
            # enforce (capplan.preflight raised above) -- containment
            raise CliError(analysis.render_text(
                analysis.errors(diags),
                title="campaign matrix invalid:"))
        if capacity == "warn" and (cap_diags or cap_plan is not None):
            print(analysis.render_text(cap_diags,
                                       title="capacity preflight:"))
            if cap_plan is not None:
                from .analysis import capplan
                print(capplan.render_table(cap_plan))
        elif cap_diags:
            logger.warning("%s", analysis.render_text(
                cap_diags, title="capacity preflight:"))
        if slots == "auto":
            from .analysis import capplan
            resolved = capplan.auto_slots(cap_plan)
            if resolved is None:
                raise CliError(
                    "--device-slots auto: the capacity plan has no "
                    "computable slot count (pass --device-mem-budget "
                    "and make sure the matrix has known-shape cells)")
            logger.info("--device-slots auto -> %d", resolved)
            options["device-slots"] = resolved
        # coordinator HA (fleet.ha): the standby tails the journal
        # read-only until the active coordinator's lease expires,
        # fences it with a journaled takeover record, and falls
        # through to the normal fleet --resume path as the new
        # coordinator (epoch = the won fencing token)
        ha_epoch = None
        if standby:
            if workers is None:
                raise CliError(
                    "--standby is fleet-mode only: pass --workers so "
                    "a takeover can dispatch the remaining cells")
            if not standby_cid:
                raise CliError(
                    "--standby: no campaign to stand by for (pass "
                    "--campaign-id, or start the active coordinator "
                    "first)")
            from .fleet import ha as fha
            sb = fha.Standby(
                standby_cid,
                lease_s=options.get("coordinator-lease-s"),
                grace_s=options.get("takeover-grace-s"))
            print(f"standby: tailing campaign {standby_cid}",
                  flush=True)
            status, epoch = sb.wait()
            if status == "complete":
                print(f"standby: campaign {standby_cid} completed "
                      "under its own coordinator; standing down")
                sys.exit(0)
            print(f"standby: coordinator lease expired; took over "
                  f"campaign {standby_cid} at epoch {epoch}",
                  flush=True)
            ha_epoch = epoch
            options["campaign-id"] = standby_cid
            options["resume"] = True
        elif options.get("coordinator-lease-s") is not None \
                and workers is None:
            raise CliError(
                "--coordinator-lease-s is fleet-mode only: the "
                "coordinator role lease lives in the fleet journal "
                "(pass --workers, e.g. --workers local,local)")
        if options.get("serve"):
            from . import web
            web.serve({"ip": options.get("serve-ip", "0.0.0.0"),
                       "port": options.get("serve-port", 8080),
                       "token": options.get("auth-token"),
                       "coalesce?": not options.get("no-coalesce"),
                       "coalesce-window-ms":
                           options.get("coalesce-window-ms"),
                       "coalesce-max-segments":
                           options.get("coalesce-max-segments"),
                       "capacity-plan": cap_plan})
        if workers is not None:
            from . import fleet
            try:
                report = fleet.run_fleet(
                    cells_plan, workers,
                    campaign_id=options.get("campaign-id"),
                    resume=bool(options.get("resume")),
                    lease_s=options.get("lease")
                    or fleet.dispatch.DEFAULT_LEASE_S,
                    max_leases=options.get("max-leases")
                    or fleet.dispatch.MAX_LEASES,
                    builder=opts.get("builder"),
                    base_options=_jsonable_options(options),
                    ledger=not options.get("no-ledger"),
                    backends=options.get("backends") or None,
                    serve=bool(options.get("serve")),
                    device_slots=options.get("device-slots", 1),
                    worker_store_dir=options.get("worker-store"),
                    sync_timeout_s=options.get("sync-timeout"),
                    chaos=options.get("chaos-profile"),
                    serve_ip=options.get("serve-ip"),
                    auth_token=options.get("auth-token"),
                    trace_merge=not options.get("no-trace-merge"),
                    fleetlint=options.get("fleetlint") or "on",
                    coalesce=bool(options.get("serve"))
                    and not options.get("no-coalesce"),
                    coalesce_window_ms=options.get(
                        "coalesce-window-ms"),
                    coalesce_max_segments=options.get(
                        "coalesce-max-segments"),
                    capacity=capacity,
                    device_mem_budget=budget,
                    capacity_plan=cap_plan,
                    coordinator_lease_s=options.get(
                        "coordinator-lease-s"),
                    takeover_grace_s=options.get("takeover-grace-s"),
                    ha_epoch=ha_epoch)
            except fleet.FleetError as e:
                raise CliError(str(e)) from e
            print(campaign.report.render_text(report))
            sys.exit(campaign_exit_code(report))

        # seed + build are one atomic step: scheduler pool threads
        # build cells concurrently, and the global RNG must not be
        # re-seeded by a sibling cell mid-build. (Draws during the RUN
        # still interleave between parallel cells; seeds reproduce
        # fully only at --parallel 1 -- see doc/campaign.md.)
        build_lock = threading.Lock()

        def build(params):
            o = dict(options)
            o.update(params)
            # axis values land AFTER test_opt_fn already ran, so
            # option syntaxes that need parsing get it here: a
            # concurrency axis may use the documented "3n" form
            if isinstance(o.get("concurrency"), str):
                o["concurrency"] = parse_concurrency(
                    o["concurrency"], o.get("nodes") or [])
            with build_lock:
                if "seed" in params:
                    random.seed(params["seed"])
                return test_fn(o)

        cells = [{"id": c["id"], "group": c["group"],
                  "params": c["params"], "build": build}
                 for c in cells_plan]
        try:
            report = campaign.run_cells(
                cells, parallel=options.get("parallel", 1),
                device_slots=options.get("device-slots", 1),
                campaign_id=options.get("campaign-id"),
                resume=bool(options.get("resume")),
                ledger=not options.get("no-ledger"),
                backends=options.get("backends") or None,
                fleetlint=options.get("fleetlint") != "off",
                capacity_plan=cap_plan)
        except campaign.CampaignError as e:
            raise CliError(str(e)) from e
        print(campaign.report.render_text(report))
        sys.exit(campaign_exit_code(report))

    return {"campaign": {"opt-spec": add_opts,
                         "opt-fn": opts.get("opt-fn"),
                         "run": run_campaign,
                         "help": "Run a sweep matrix as a parallel, "
                                 "resumable campaign."}}


def serve_cmd():
    """Subcommand ``serve``: the web interface (cli.clj:333-350)."""

    def add_opts(parser):
        parser.add_argument("-b", "--host", default="0.0.0.0",
                            help="Hostname to bind to")
        parser.add_argument("-p", "--port", type=int, default=8080,
                            help="Port number to bind to")
        parser.add_argument("--token", default=None, metavar="TOKEN",
                            help="Bearer token /api requests must "
                                 "present (401 otherwise); PL016 "
                                 "demands one for non-loopback binds.")
        parser.add_argument("--no-coalesce", action="store_true",
                            help="Disable cross-tenant batch "
                                 "coalescing: every accepted "
                                 "/api/check runs its own device "
                                 "search instead of merging with "
                                 "queued strangers (default: "
                                 "coalescing on).")
        parser.add_argument("--coalesce-window-ms", type=float,
                            default=None, metavar="MS",
                            help="How long a submitted check may wait "
                                 "for batchmates before its device "
                                 "batch closes anyway (default 25; "
                                 "PL020 rejects non-positive "
                                 "values).")
        parser.add_argument("--coalesce-max-segments", type=int,
                            default=None, metavar="N",
                            help="Segments per coalesced device batch "
                                 "past which the batch closes early "
                                 "(default 32; PL020 rejects "
                                 "non-positive values).")
        parser.add_argument("--capacity-plan", default=None,
                            metavar="FILE",
                            help="A capacity_plan.json (from "
                                 "`campaign --capacity plan` or "
                                 "`tools/lint.py --matrix`) whose "
                                 "predicted (model, bucket) shapes "
                                 "pre-register on the coalescer, so "
                                 "first-window strangers land in "
                                 "planned compile shapes (PL021 "
                                 "rejects unreadable files).")

    def run_serve(options):
        from . import web
        from .analysis import planlint, render_text, errors
        diags = planlint.lint_service({
            "serve?": True, "serve-ip": options.get("host"),
            "auth-token?": bool(options.get("token"))})
        diags += planlint.lint_coalesce({
            "coalesce?": not options.get("no-coalesce"),
            "coalesce-window-ms": options.get("coalesce-window-ms"),
            "coalesce-max-segments":
                options.get("coalesce-max-segments")})
        diags += planlint.lint_capacity({
            "capacity-plan-file": options.get("capacity-plan")})
        if diags:
            print(render_text(diags, title="serve preflight:"))
        if errors(diags):
            raise CliError("refusing to serve: fix the preflight "
                           "errors above (bind 127.0.0.1 / pass "
                           "--token / fix the coalesce or "
                           "capacity-plan knobs)")
        web.serve({"ip": options.get("host", "0.0.0.0"),
                   "port": options.get("port", 8080),
                   "token": options.get("token"),
                   "coalesce?": not options.get("no-coalesce"),
                   "coalesce-window-ms":
                       options.get("coalesce-window-ms"),
                   "coalesce-max-segments":
                       options.get("coalesce-max-segments"),
                   "capacity-plan": options.get("capacity-plan")})
        print(f"Listening on http://{options.get('host')}:"
              f"{options.get('port')}/")
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            # honor the service's shared AbortLatch: campaigns
            # submitted over POST /api/campaigns abort gracefully and
            # stay resumable instead of dying with the server
            from .fleet import service
            print("shutting down: aborting submitted campaigns...")
            service.shutdown()

    return {"serve": {"opt-spec": add_opts, "opt-fn": lambda o: o,
                      "standalone": True, "run": run_serve,
                      "help": "Serve the web interface."}}


def run(subcommands, argv=None):
    """Parse arguments and dispatch to a subcommand (cli.clj:255-331).
    Exits 254 on bad usage, 255 on internal errors; subcommand run fns
    control success exit codes."""
    argv = list(sys.argv[1:] if argv is None else argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s\t%(levelname)s\t%(name)s: %(message)s")
    command = argv[0] if argv else None
    if command not in subcommands:
        print("Usage: python -m jepsen_tpu COMMAND [OPTIONS ...]")
        print("Commands:", ", ".join(sorted(subcommands)))
        sys.exit(254)
    spec = subcommands[command]
    parser = argparse.ArgumentParser(prog=f"jepsen_tpu {command}")
    if not spec.get("standalone"):
        add_test_opts(parser)
    if spec.get("opt-spec"):
        spec["opt-spec"](parser)
    try:
        ns = parser.parse_args(argv[1:])
    except SystemExit as e:
        sys.exit(0 if e.code in (0, None) else 254)
    try:
        options = _ns_to_opts(ns)
        options["argv"] = argv
        opt_fn = spec.get("opt-fn") or test_opt_fn
        options = opt_fn(options)
        spec["run"](options)
        sys.exit(0)
    except SystemExit:
        raise
    except CliError as e:
        print(str(e), file=sys.stderr)
        sys.exit(254)
    except Exception:  # noqa: BLE001
        logger.critical("Oh jeez, I'm sorry, Jepsen broke. Here's why:\n%s",
                        traceback.format_exc())
        sys.exit(255)


def hard_main(main_fn):
    """Run a CLI ``main`` at the REAL process boundary (``__main__``
    blocks only) and exit via os._exit after flushing.

    A plain sys.exit runs interpreter teardown, and a still-compiling
    device engine (e.g. the competition's losing jax thread) can abort
    the C++ runtime there ("terminate called ..."), stomping the exit
    code the reference's CLI contract promises (0/1/2/254/255,
    cli.clj:129-139). All test artifacts are already on disk by then,
    so skipping teardown loses nothing. Tests call ``main`` directly
    and keep normal SystemExit semantics."""
    import os
    try:
        main_fn()
        code = 0
    except SystemExit as e:
        if e.code is None:           # bare sys.exit() = success
            code = 0
        elif isinstance(e.code, int):
            code = e.code
        else:                        # sys.exit("message")
            print(e.code, file=sys.stderr)
            code = 254
    except KeyboardInterrupt:
        code = 130
    except BaseException:  # noqa: BLE001 - teardown must not run
        traceback.print_exc()
        code = 255
    logging.shutdown()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)
