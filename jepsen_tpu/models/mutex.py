"""Mutex model (knossos.model/mutex): acquire valid iff unlocked, release
valid iff locked. BASELINE.json config 3 (high-contention lock histories)."""

from __future__ import annotations

import numpy as np

from .base import Model, ModelSpec, inconsistent, register_model

F_ACQUIRE, F_RELEASE = 0, 1


class Mutex(Model):
    def __init__(self, locked=False):
        self.locked = locked

    def step(self, op):
        f = op["f"]
        if f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire held mutex")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("cannot release free mutex")
            return Mutex(False)
        raise ValueError(f"mutex: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, Mutex) and self.locked == other.locked

    def __hash__(self):
        return hash(("mutex", self.locked))

    def __repr__(self):
        return f"Mutex({self.locked})"


def _mutex_step(state, f, args, ret, xp):
    locked = state[0]
    is_acq = f == F_ACQUIRE
    ok = xp.where(is_acq, locked == 0, locked == 1)
    new_state = xp.stack([xp.where(is_acq, 1, 0).astype(state.dtype)])
    return new_state, ok


def _mutex_encode(spec, intern, f, value, ret_value):
    return spec.f_codes[f], [], []


mutex_spec = register_model(ModelSpec(
    name="mutex",
    f_codes={"acquire": F_ACQUIRE, "release": F_RELEASE},
    arg_width=1,
    state_size=lambda e: 1,
    init_state=lambda e, s: np.zeros(1, np.int32),
    decode_state=lambda st: {"locked": bool(st[0])},
    step=_mutex_step,
    make_oracle=Mutex,
    encode_op=_mutex_encode,
))
