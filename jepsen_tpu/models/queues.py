"""Queue models (knossos.model fifo-queue / unordered-queue).

The tensor face uses *canonical* fixed-capacity buffers so that equal queue
contents always produce byte-equal state vectors -- this is what makes the
checker's configuration dedup effective (SURVEY.md section 7 "unbounded model
state under vmap"):

* fifo-queue: left-aligned ring -- the front is always slot 0; dequeue
  shifts the whole buffer left (one vectorized roll, no head pointer).
* unordered-queue: a multiset kept sorted ascending with empties (NIL,
  int32 min) first.

Capacity is chosen from the history: the number of enqueue operations
(worst case all enqueued before any dequeue). Overflow cannot occur under
that choice, but the ok-flag still guards it.
"""

from __future__ import annotations

import numpy as np

from ..history import NIL
from .base import Model, ModelSpec, inconsistent, register_model

F_ENQUEUE, F_DEQUEUE = 0, 1


class FIFOQueue(Model):
    def __init__(self, items=()):
        self.items = tuple(items)

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            head, rest = self.items[0], self.items[1:]
            if v is not None and v != head:
                return inconsistent(f"dequeued {v!r}, expected {head!r}")
            return FIFOQueue(rest)
        raise ValueError(f"fifo-queue: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, FIFOQueue) and self.items == other.items

    def __hash__(self):
        return hash(("fifo-queue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)!r})"


class UnorderedQueue(Model):
    """A multiset: dequeue may return any enqueued element
    (knossos.model/unordered-queue). A dequeue of unknown value cannot be
    linearized (mirrors knossos, whose step sees a nil value)."""

    def __init__(self, items=()):
        self.items = tuple(sorted(items))

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "enqueue":
            return UnorderedQueue(self.items + (v,))
        if f == "dequeue":
            if v is None:
                return inconsistent("dequeue of unknown value")
            if v not in self.items:
                return inconsistent(f"dequeued {v!r}, not in queue")
            items = list(self.items)
            items.remove(v)
            return UnorderedQueue(items)
        raise ValueError(f"unordered-queue: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, UnorderedQueue) and self.items == other.items

    def __hash__(self):
        return hash(("unordered-queue", self.items))

    def __repr__(self):
        return f"UnorderedQueue({list(self.items)!r})"


# -- tensor specs ------------------------------------------------------------

def _queue_capacity(e):
    return max(1, int((e.f == F_ENQUEUE).sum()))


def _pad_nil(state, s_pad):
    """Grow a queue state by appending empty (NIL) slots: for the left-
    aligned FIFO this is extra tail capacity; for the all-NIL initial
    unordered multiset it stays canonical (sorted)."""
    return np.concatenate(
        [state, np.full(s_pad - len(state), NIL, np.int32)])


def _fifo_step(state, f, args, ret, xp):
    # state = [count, buf[0..C-1]]; front at buf[0]
    C = state.shape[0] - 1
    count = state[0]
    buf = state[1:]
    idxs = xp.arange(C)
    is_enq = f == F_ENQUEUE
    # enqueue appends at index `count`
    enq_buf = xp.where(idxs == count, args[0], buf)
    enq_ok = count < C
    # dequeue pops buf[0], shifting left; last slot becomes empty
    front = buf[0]
    nonempty = count > 0
    deq_ok = nonempty & ((ret[0] == NIL) | (ret[0] == front))
    deq_buf = xp.where(idxs == C - 1, NIL, xp.roll(buf, -1))
    new_count = xp.where(is_enq, count + 1, count - 1).astype(state.dtype)
    new_buf = xp.where(is_enq, enq_buf, deq_buf)
    ok = xp.where(is_enq, enq_ok, deq_ok)
    return xp.concatenate([new_count[None], new_buf]), ok


def _queue_encode(spec, intern, f, value, ret_value):
    if f == "enqueue":
        return F_ENQUEUE, [intern.encode(value)], []
    if f == "dequeue":
        rv = ret_value if ret_value is not None else value
        return F_DEQUEUE, [], [intern.encode(rv)]
    raise ValueError(f"queue: unknown f {f!r}")


def _fifo_hint(e, inv32, ret32):
    """Search priority from the aspect plan: when the polynomial analysis
    can schedule the history (a full pop order including which crashed
    dequeue consumes which stuck value), an explicit witness
    linearization is constructed host-side and its positions become the
    priorities -- the device's greedy rollout then walks the witness end
    to end (depth += R per iteration) instead of reaching for info
    dequeues as a blind last resort, which pops values later ok dequeues
    still need: a mistake hundreds of levels beyond DFS backtracking
    range. Priorities are pure heuristics: soundness and completeness
    never depend on them, and the search still verifies every step
    through the model, so the verdict comes with a genuine linearization
    the aspect's existence proof alone does not provide."""
    verdict, plan = _fifo_plan(e, inv32, ret32, want_plan=True)
    if plan is None:
        return _fifo_hint_legacy(e, inv32, ret32)
    n = len(e)
    K = len(plan["pop"])
    # slot priorities: pop k's enqueue at 4k, its dequeue at 4k+2;
    # everything outside the pop schedule (never-consumed enqueues,
    # unmatched info dequeues) sorts after it, in original order
    pri = 4 * np.int64(K) + 8 + np.arange(n, dtype=np.int64)
    deq_val = np.full(n, NIL, np.int64)
    planned = np.zeros(n, bool)
    for k, (enq_i, deq_i) in enumerate(plan["pop"]):
        pri[enq_i] = 4 * k
        planned[enq_i] = True
        if deq_i is not None:
            pri[deq_i] = 4 * k + 2
            planned[deq_i] = True
            deq_val[deq_i] = int(e.args[enq_i][0])
    order = _witness_order(e, inv32, ret32, pri, deq_val, planned)
    if order is not None:
        pri = np.full(n, np.int64(n) + 8, np.int64) \
            + np.arange(n, dtype=np.int64)
        pri[order] = np.arange(len(order), dtype=np.int64)
    return np.clip(pri, -(2 ** 31), 2 ** 31 - 1).astype(np.int32)


def _witness_order(e, inv32, ret32, pri, deq_val, planned):
    """Simulate the plan schedule into an explicit witness linearization
    (list of op indices) or None when the simulation wedges (priorities
    then stay slot-based). The simulation respects the WGL eligibility
    rule, takes ops in slot-priority order, and -- unlike the device
    step, whose info dequeues accept any front -- only lets a matched
    info dequeue pop its ASSIGNED value, which stops it firing a slot
    early when an eligibility stall reorders neighbors."""
    import collections

    n = len(e)
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    args = np.asarray(e.args)[:, 0]
    rets = np.asarray(e.ret)[:, 0]
    srt = np.argsort(pri, kind="stable")
    # takeable: every ok op plus everything in the pop schedule (which
    # adds observed/forced info enqueues and matched info dequeues);
    # other info ops take no effect in the planned completion
    takeable = is_ok | planned
    ret_sorted = np.argsort(ret32, kind="stable")
    linearized = np.zeros(n, bool)
    q = collections.deque()
    order = []
    remaining_ok = int(is_ok.sum())
    start = rp = 0
    budget = 50 * n + 1000
    while remaining_ok:
        while start < n and (linearized[srt[start]]
                             or not takeable[srt[start]]):
            start += 1
        while rp < n and linearized[ret_sorted[rp]]:
            rp += 1
        rmin = int(ret32[ret_sorted[rp]]) if rp < n else 2 ** 31 - 1
        took = False
        j = start
        while j < n:
            budget -= 1
            if budget < 0:
                return None
            i = int(srt[j])
            j += 1
            if linearized[i] or not takeable[i] or \
                    int(inv32[i]) >= rmin:
                continue
            if f[i] == F_ENQUEUE:
                q.append(int(args[i]))
            else:
                want = int(deq_val[i]) if deq_val[i] != NIL \
                    else int(rets[i])
                if not q or q[0] != want:
                    continue
                q.popleft()
            linearized[i] = True
            order.append(i)
            remaining_ok -= bool(is_ok[i])
            took = True
            break
        if not took:
            return None
    return np.asarray(order, np.int64)


def _fifo_hint_legacy(e, inv32, ret32):
    """Fallback priority when no plan exists (NIL-valued ok dequeues or
    duplicate enqueue values): an enqueue must linearize before the
    dequeue returning its value, so cap each enqueue's priority at its
    dequeuer's deadline. This orders enqueues by dequeue order."""
    pri = ret32.astype(np.int64)
    enq_idx = {}
    for i in range(len(e)):
        if int(e.f[i]) == F_ENQUEUE:
            enq_idx[int(e.args[i][0])] = i
    for i in range(len(e)):
        if int(e.f[i]) == F_DEQUEUE and bool(e.is_ok[i]):
            j = enq_idx.get(int(e.ret[i][0]))
            if j is not None:
                # NOT min(own return, ...): an enqueue that completes
                # early but whose value is dequeued late must still sort
                # by its dequeuer, or concurrent enqueues linearize in
                # completion order instead of pop order. The WGL
                # eligibility rule (not priority) is what guarantees the
                # enqueue still linearizes before its return barrier.
                pri[j] = pri[i] - 1
    return np.clip(pri, -(2 ** 31), 2 ** 31 - 1).astype(np.int32)


def _per_value_scan(e, inv32, ret32):
    """Shared queue/bag pattern scan. Returns (enq_of, deq_of, verdict):
    verdict is None when the scan passes, a (False, witness) pair when a
    per-value bad pattern fires, or "skip" when the history is out of
    scope (unknown dequeue values, duplicate enqueue values)."""
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    ok_deq = (f == F_DEQUEUE) & is_ok
    if np.any(np.asarray(e.ret)[ok_deq, 0] == NIL):
        return None, None, "skip"
    enq_of = {}
    for i in np.flatnonzero(f == F_ENQUEUE):
        v = int(e.args[i][0])
        if v in enq_of:
            return None, None, "skip"
        enq_of[v] = i
    deq_of = {}
    for i in np.flatnonzero(ok_deq):
        v = int(e.ret[i][0])
        if v in deq_of:
            return None, None, (False, {"op_index": int(i),
                                        "pattern": "double-dequeue"})
        deq_of[v] = i
        j = enq_of.get(v)
        if j is None:
            return None, None, (
                False, {"op_index": int(i),
                        "pattern": "dequeue-of-unknown-value"})
        if ret32[i] < inv32[j]:
            return None, None, (
                False, {"op_index": int(i),
                        "pattern": "dequeue-before-enqueue"})
    return enq_of, deq_of, None


_FAR = np.int64(2) ** 62


def _fifo_fast_check(e, inv32, ret32):
    """Aspect-style polynomial decision for FIFO histories (after
    Henzinger/Sezgin/Vafeiadis-style bad patterns; values are unique and
    dequeues always return a value in this model).

    Certain-invalidity patterns (sound even with info ops):
      i.  an ok dequeue of a value nobody enqueued, or dequeued twice
      ii. a dequeue completing before its value's enqueue was invoked
      iii. FIFO order violation: enq(a) really-before enq(b), yet
           deq(b) really-before deq(a) (both dequeues ok)
      iv. enq(a) really-before enq(b), b ok-dequeued, a (ok-enqueued)
          never dequeued and not assignable to any crashed dequeue
          (the matching below).

    Crashed (info) ops are handled EXACTLY, not punted to the search:

    * A crashed enqueue either committed (observed by an ok dequeue: it
      is forced, with window [invoke, inf) -- infinite return already
      flows through the patterns) or is unobserved, in which case
      dropping it wholesale preserves linearizability both ways
      (removing a value and its dequeue from any valid FIFO run keeps
      the run valid, and it is never *needed* since every ok dequeue
      returns a known value here).
    * A crashed dequeue, if it took effect, consumed exactly one stuck
      value. Completing each info dequeue with a chosen stuck value (or
      dropping it) turns the history into a complete one, to which the
      bad-pattern theorem applies. Since a completed info dequeue never
      returns (window [invoke, inf)), the ONLY patterns it can enter are
      (a) membership: every stuck value really-enqueued-before a
      dequeued value must itself be consumed (the overtaken set is
      already closed under this relation, see _fifo_plan) -- and (b) a
      deadline: consuming value a is
      futile if the info dequeue was invoked after some ok dequeue of a
      later-enqueued value completed (pattern iii with the info dequeue
      as the late party). So validity reduces to a threshold matching:
      values (sorted by deadline) against info-dequeue invocation times,
      feasible iff the j-th smallest invocation is <= the j-th smallest
      deadline (Hall's condition; greedy smallest-first is exact).

    The only remaining out-of-scope histories ("skip" -> search):
    ok dequeues returning an unknown (NIL) value, and duplicate enqueue
    values.

    Returns True, None, or (False, {"op_index", "pattern"}) -- the
    offending op becomes the failure witness."""
    verdict, _ = _fifo_plan(e, inv32, ret32)
    return verdict


def _fifo_plan(e, inv32, ret32, want_plan=False):
    """The shared FIFO aspect analysis (see _fifo_fast_check for the
    theory). Returns (verdict, plan): verdict as _fifo_fast_check;
    plan (only built when ``want_plan``, on valid histories in scope)
    is a dict with "pop": [(enqueue_idx, dequeue_idx | None)] in a
    witness-consistent pop order (matched info dequeues included),
    consumed by the search hint."""
    n = len(e)
    if n == 0:
        return True, {"pop": []}
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    deq_mask = (f == F_DEQUEUE)
    enq_of, deq_of, status = _per_value_scan(e, inv32, ret32)
    if status == "skip":
        return None, None
    if status is not None:
        return status, None
    # (iii): order violations among dequeued values. A violating pair
    # (a, b) has enq(a) really-before enq(b) and deq(b) really-before
    # deq(a): for each a that is "is the earliest dequeue-completion,
    # among values whose enqueue began after a's enqueue returned,
    # before a's dequeue was invoked?" -- a suffix-min sweep over the
    # enqueue-invocation order, O(V log V) (the naive V x V boolean
    # matrices melt past ~50k dequeued values).
    vals = sorted(deq_of)
    ei_sorted = dr_sorted = dj_sorted = None
    if vals:
        ej = np.asarray([enq_of[v] for v in vals])
        dj = np.asarray([deq_of[v] for v in vals])
        enq_ret = ret32[ej].astype(np.int64)
        enq_inv = inv32[ej].astype(np.int64)
        deq_ret = ret32[dj].astype(np.int64)
        deq_inv = inv32[dj].astype(np.int64)
        order = np.argsort(enq_inv)
        ei_sorted = enq_inv[order]
        dr_sorted = deq_ret[order]
        dj_sorted = dj[order]
        suffix_min = np.minimum.accumulate(dr_sorted[::-1])[::-1]
        pos3 = np.searchsorted(ei_sorted, enq_ret, side="right")
        smin = np.where(pos3 < len(ei_sorted),
                        suffix_min[np.minimum(pos3, len(ei_sorted) - 1)],
                        _FAR)
        bad_a = smin < deq_inv
        if np.any(bad_a):
            ai = int(np.argmax(bad_a))
            k = int(pos3[ai])
            bi = int(np.argmin(dr_sorted[k:])) + k
            return (False, {"op_index": int(dj[ai]),
                            "pattern": "fifo-order-violation",
                            "own-enqueue": int(ej[ai]),
                            "overtaking-dequeue": int(dj_sorted[bi])}), \
                None
    # (iv) generalized: stuck values (ok-enqueued, never ok-dequeued)
    stuck_idx = np.asarray(
        sorted(enq_of[v] for v in enq_of
               if v not in deq_of and is_ok[enq_of[v]]), np.int64)
    assigned = []          # (stuck enqueue idx, info dequeue idx, eff_dl)
    if stuck_idx.size:
        sret = ret32[stuck_idx].astype(np.int64)   # enqueue completions
        sinv = inv32[stuck_idx].astype(np.int64)   # enqueue invocations
        if vals:
            # deadline(a) = earliest completion among ok dequeues of
            # values whose enqueue began after a's enqueue returned
            pos = np.searchsorted(ei_sorted, sret, side="right")
            in_range = pos < len(ei_sorted)
            deadline = np.where(
                in_range,
                suffix_min[np.minimum(pos, len(ei_sorted) - 1)], _FAR)
        else:
            deadline = np.full(stuck_idx.size, _FAR)
        # Must-consume membership: a stuck value overtaken by an ok
        # dequeue (finite deadline). This set is already closed under
        # "really-enqueued-before a consumed value": if c's enqueue
        # returned before member m's enqueue was invoked, then m's
        # deadline witness b (enq(m) returned before enq(b) began) also
        # overtakes c -- ret_c < inv_m <= ret_m < inv_b -- so c has a
        # finite deadline of its own. (Consumption through info
        # dequeues adds no further members: their pops never return, so
        # they real-time-precede nothing.)
        member = deadline < _FAR
        if member.any():
            info_idx = np.flatnonzero(deq_mask & ~is_ok)
            info_idx = info_idx[np.argsort(
                inv32[info_idx].astype(np.int64), kind="stable")]
            info_inv = inv32[info_idx].astype(np.int64)
            D_order = np.argsort(deadline[member], kind="stable")
            D = deadline[member][D_order]
            bad_j = None
            if len(D) > len(info_inv):
                bad_j = len(info_inv)
            else:
                over = np.flatnonzero(info_inv[:len(D)] > D)
                if over.size:
                    bad_j = int(over[0])
            if bad_j is not None:
                jj = min(bad_j, len(D) - 1)
                a = int(stuck_idx[member][D_order[jj]])
                wit = {"pattern": "dequeue-past-stuck-value",
                       "stuck-enqueue": a}
                # point at the overtaking dequeue when one exists
                if vals and D[jj] < _FAR:
                    k = int(np.searchsorted(
                        ei_sorted, sret[member][D_order[jj]],
                        side="right"))
                    sm = int(np.argmin(dr_sorted[k:])) + k
                    wit["op_index"] = int(dj_sorted[sm])
                else:
                    wit["op_index"] = a
                return (False, wit), None
            if want_plan:
                m_idx = stuck_idx[member]
                assigned = [(int(m_idx[D_order[j]]), int(info_idx[j]),
                             int(D[j]))
                            for j in range(len(D))]
    if not want_plan:        # fast-path verdicts skip plan construction
        return True, None
    # Valid. Build a witness-consistent pop order for the search hint:
    # a topological order of consumed values under the precedence union
    #   enq(u) really-before enq(v)   -> u pops before v  (queue order)
    #   deq(u) really-before deq(v)   -> u pops before v
    #   deq(u) really-before enq(v)   -> u pops before v
    # which the bad-pattern checks above prove acyclic (any cycle
    # reduces to a 2-cycle through interval-order transitivity, and
    # 2-cycles are exactly patterns ii/iii + the matching deadlines).
    pop = []
    rows = []          # (enq_idx, deq_idx, einv, eret, dinv, dret, edf)
    if vals:
        for v in vals:
            ei, di = int(enq_of[v]), int(deq_of[v])
            rows.append((ei, di, int(inv32[ei]), int(ret32[ei]),
                         int(inv32[di]), int(ret32[di]),
                         (int(ret32[di]), 1)))
    for enq_i, deq_i, dl in assigned:
        # a matched stuck value pops through its info dequeue: the pop
        # never returns (window [invoke, inf)), and should schedule just
        # before the ok dequeue that forces it out (its deadline)
        rows.append((enq_i, deq_i, int(inv32[enq_i]),
                     int(ret32[enq_i]), int(inv32[deq_i]), int(_FAR),
                     (dl, 0)))
    order = _value_topo_order(rows)
    if order is None:        # safety net: EDF-ish slot order
        order = sorted(range(len(rows)), key=lambda r: rows[r][6])
    pop = [(rows[r][0], rows[r][1]) for r in order]
    return True, {"pop": pop}


def _value_topo_order(rows):
    """Topological order of consumed values under the pop-precedence
    union (see _fifo_plan). Availability of a value is two monotone
    threshold tests (u-before-v edges all have the form ret_u < inv_v,
    and the mins only rise as values are emitted), so two pointers over
    inv-sorted lists feed an earliest-deadline heap; ties broken toward
    stuck values so they pop before the ok dequeue that forces them.
    Returns row indices, or None if the heap ever runs dry (a cycle --
    impossible after the pattern checks, kept as a safety net)."""
    import heapq

    V = len(rows)
    if V == 0:
        return []
    einv = [r[2] for r in rows]
    eret = [r[3] for r in rows]
    dinv = [r[4] for r in rows]
    dret = [r[5] for r in rows]
    edf = [r[6] for r in rows]
    eret_heap = [(eret[v], v) for v in range(V)]
    dret_heap = [(dret[v], v) for v in range(V)]
    heapq.heapify(eret_heap)
    heapq.heapify(dret_heap)
    by_einv = sorted(range(V), key=lambda v: einv[v])
    by_dinv = sorted(range(V), key=lambda v: dinv[v])
    emitted = [False] * V
    passed = [0] * V
    avail = []
    pe = pd = 0
    out = []
    for _ in range(V):
        while eret_heap and emitted[eret_heap[0][1]]:
            heapq.heappop(eret_heap)
        while dret_heap and emitted[dret_heap[0][1]]:
            heapq.heappop(dret_heap)
        m_e = eret_heap[0][0] if eret_heap else _FAR
        m_d = dret_heap[0][0] if dret_heap else _FAR
        # condition 1: no remaining enqueue or dequeue returned before
        # this value's enqueue was invoked; condition 2: no remaining
        # dequeue returned before this value's dequeue was invoked
        t1 = min(m_e, m_d)
        while pe < V and einv[by_einv[pe]] <= t1:
            v = by_einv[pe]
            pe += 1
            passed[v] += 1
            if passed[v] == 2 and not emitted[v]:
                heapq.heappush(avail, (edf[v], v))
        while pd < V and dinv[by_dinv[pd]] <= m_d:
            v = by_dinv[pd]
            pd += 1
            passed[v] += 1
            if passed[v] == 2 and not emitted[v]:
                heapq.heappush(avail, (edf[v], v))
        while avail and emitted[avail[0][1]]:
            heapq.heappop(avail)
        if not avail:
            return None
        _, v = heapq.heappop(avail)
        emitted[v] = True
        out.append(v)
    return out


def _queue_prune(e, inv32, ret32):
    """Sound+complete candidate prune for the search path: a crashed
    enqueue whose value no ok dequeue returned can be dropped wholesale
    (with it, any dequeue consuming it -- removing a value end to end
    from a valid queue run keeps the run valid, and the value is never
    *required* when every ok dequeue returns a known value). Without the
    prune, the greedy rollout linearizes these junk enqueues the moment
    a desired op fails once, wedging stuck values into the queue and
    forcing exponential backtracking (measured: the raw search ceiling
    on info-bearing FIFO histories roughly triples with the prune).
    Inapplicable (None) when an ok dequeue returns NIL -- it could be
    the one that consumed the junk value -- or when enqueue values
    repeat."""
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    rets = np.asarray(e.ret)[:, 0]
    args = np.asarray(e.args)[:, 0]
    ok_deq = (f == F_DEQUEUE) & is_ok
    if np.any(rets[ok_deq] == NIL):
        return None
    enq = f == F_ENQUEUE
    enq_vals = args[enq]
    if len(np.unique(enq_vals)) != len(enq_vals):
        return None
    observed = set(rets[ok_deq].tolist())
    keep = np.ones(len(e), bool)
    for i in np.flatnonzero(enq & ~is_ok):
        if int(args[i]) not in observed:
            keep[i] = False
    return keep


fifo_queue_spec = register_model(ModelSpec(
    name="fifo-queue",
    f_codes={"enqueue": F_ENQUEUE, "dequeue": F_DEQUEUE},
    arg_width=1,
    state_size=lambda e: _queue_capacity(e) + 1,
    init_state=lambda e, s: np.concatenate(
        [np.zeros(1, np.int32), np.full(s - 1, NIL, np.int32)]),
    step=_fifo_step,
    make_oracle=FIFOQueue,
    encode_op=_queue_encode,
    pad_state=_pad_nil,
    hint=_fifo_hint,
    fast_check=_fifo_fast_check,
    prune=_queue_prune,
    decode_state=lambda st: {
        "queue": [int(v) for v in st[1:1 + int(st[0])]]},
))


def _unordered_step(state, f, args, ret, xp):
    # state = sorted multiset; NIL (int32 min) slots sort first = empty
    C = state.shape[0]
    idxs = xp.arange(C)
    is_enq = f == F_ENQUEUE
    # enqueue: overwrite the first empty slot
    empty = state == NIL
    first_empty = xp.argmax(empty)
    enq_buf = xp.where(idxs == first_empty, args[0], state)
    enq_ok = xp.any(empty)
    # dequeue: clear the first slot equal to ret (value must be known)
    known = ret[0] != NIL
    match = state == ret[0]
    exists = xp.any(match)
    first_match = xp.argmax(match)
    deq_buf = xp.where(idxs == first_match, NIL, state)
    deq_ok = known & exists
    new_buf = xp.where(is_enq, enq_buf, deq_buf)
    ok = xp.where(is_enq, enq_ok, deq_ok)
    return xp.sort(new_buf), ok


def _unordered_fast_check(e, inv32, ret32):
    """Bag (unordered queue) polynomial decision. Without FIFO order,
    the only constraints are per-value: a dequeue of v needs an
    enqueue of v that STARTED before the dequeue finished, each value
    dequeued at most once, and nothing dequeued that was never
    enqueued. That's exact for complete histories; crashed ops change
    nothing: a crashed enqueue is forced iff observed (open window flows
    through the scan), a crashed dequeue can always be completed as
    taking no effect (a bag has no order, so an extra resident value
    never blocks any other dequeue -- unlike FIFO there is no
    overtaking pattern to repair). Witness: place each surviving
    enqueue at its invocation and each dequeue of v just after
    max(its invocation, v's enqueue invocation), which the per-value
    scan guarantees is within its interval."""
    n = len(e)
    if n == 0:
        return True
    _, _, status = _per_value_scan(e, inv32, ret32)
    if status == "skip":
        return None
    if status is not None:
        return status
    return True


unordered_queue_spec = register_model(ModelSpec(
    name="unordered-queue",
    f_codes={"enqueue": F_ENQUEUE, "dequeue": F_DEQUEUE},
    arg_width=1,
    state_size=_queue_capacity,
    init_state=lambda e, s: np.full(s, NIL, np.int32),
    step=_unordered_step,
    make_oracle=UnorderedQueue,
    encode_op=_queue_encode,
    pad_state=_pad_nil,
    fast_check=_unordered_fast_check,
    prune=_queue_prune,
    decode_state=lambda st: {
        "items": sorted(int(v) for v in st if int(v) != NIL)},
))
