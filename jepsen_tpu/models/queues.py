"""Queue models (knossos.model fifo-queue / unordered-queue).

The tensor face uses *canonical* fixed-capacity buffers so that equal queue
contents always produce byte-equal state vectors -- this is what makes the
checker's configuration dedup effective (SURVEY.md section 7 "unbounded model
state under vmap"):

* fifo-queue: left-aligned ring -- the front is always slot 0; dequeue
  shifts the whole buffer left (one vectorized roll, no head pointer).
* unordered-queue: a multiset kept sorted ascending with empties (NIL,
  int32 min) first.

Capacity is chosen from the history: the number of enqueue operations
(worst case all enqueued before any dequeue). Overflow cannot occur under
that choice, but the ok-flag still guards it.
"""

from __future__ import annotations

import numpy as np

from ..history import NIL
from .base import Model, ModelSpec, inconsistent, register_model

F_ENQUEUE, F_DEQUEUE = 0, 1


class FIFOQueue(Model):
    def __init__(self, items=()):
        self.items = tuple(items)

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            head, rest = self.items[0], self.items[1:]
            if v is not None and v != head:
                return inconsistent(f"dequeued {v!r}, expected {head!r}")
            return FIFOQueue(rest)
        raise ValueError(f"fifo-queue: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, FIFOQueue) and self.items == other.items

    def __hash__(self):
        return hash(("fifo-queue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)!r})"


class UnorderedQueue(Model):
    """A multiset: dequeue may return any enqueued element
    (knossos.model/unordered-queue). A dequeue of unknown value cannot be
    linearized (mirrors knossos, whose step sees a nil value)."""

    def __init__(self, items=()):
        self.items = tuple(sorted(items))

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "enqueue":
            return UnorderedQueue(self.items + (v,))
        if f == "dequeue":
            if v is None:
                return inconsistent("dequeue of unknown value")
            if v not in self.items:
                return inconsistent(f"dequeued {v!r}, not in queue")
            items = list(self.items)
            items.remove(v)
            return UnorderedQueue(items)
        raise ValueError(f"unordered-queue: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, UnorderedQueue) and self.items == other.items

    def __hash__(self):
        return hash(("unordered-queue", self.items))

    def __repr__(self):
        return f"UnorderedQueue({list(self.items)!r})"


# -- tensor specs ------------------------------------------------------------

def _queue_capacity(e):
    return max(1, int((e.f == F_ENQUEUE).sum()))


def _pad_nil(state, s_pad):
    """Grow a queue state by appending empty (NIL) slots: for the left-
    aligned FIFO this is extra tail capacity; for the all-NIL initial
    unordered multiset it stays canonical (sorted)."""
    return np.concatenate(
        [state, np.full(s_pad - len(state), NIL, np.int32)])


def _fifo_step(state, f, args, ret, xp):
    # state = [count, buf[0..C-1]]; front at buf[0]
    C = state.shape[0] - 1
    count = state[0]
    buf = state[1:]
    idxs = xp.arange(C)
    is_enq = f == F_ENQUEUE
    # enqueue appends at index `count`
    enq_buf = xp.where(idxs == count, args[0], buf)
    enq_ok = count < C
    # dequeue pops buf[0], shifting left; last slot becomes empty
    front = buf[0]
    nonempty = count > 0
    deq_ok = nonempty & ((ret[0] == NIL) | (ret[0] == front))
    deq_buf = xp.where(idxs == C - 1, NIL, xp.roll(buf, -1))
    new_count = xp.where(is_enq, count + 1, count - 1).astype(state.dtype)
    new_buf = xp.where(is_enq, enq_buf, deq_buf)
    ok = xp.where(is_enq, enq_ok, deq_ok)
    return xp.concatenate([new_count[None], new_buf]), ok


def _queue_encode(spec, intern, f, value, ret_value):
    if f == "enqueue":
        return F_ENQUEUE, [intern.encode(value)], []
    if f == "dequeue":
        rv = ret_value if ret_value is not None else value
        return F_DEQUEUE, [], [intern.encode(rv)]
    raise ValueError(f"queue: unknown f {f!r}")


def _fifo_hint(e, inv32, ret32):
    """Search priority: an enqueue must linearize before the dequeue
    returning its value, so cap each enqueue's priority at its dequeuer's
    deadline. This orders enqueues by dequeue order -- without it, a
    greedy enqueue-order mistake only manifests hundreds of ops later at
    the dequeue, far beyond DFS backtracking range."""
    pri = ret32.astype(np.int64)
    enq_idx = {}
    for i in range(len(e)):
        if int(e.f[i]) == F_ENQUEUE:
            enq_idx[int(e.args[i][0])] = i
    for i in range(len(e)):
        if int(e.f[i]) == F_DEQUEUE and bool(e.is_ok[i]):
            j = enq_idx.get(int(e.ret[i][0]))
            if j is not None:
                # NOT min(own return, ...): an enqueue that completes
                # early but whose value is dequeued late must still sort
                # by its dequeuer, or concurrent enqueues linearize in
                # completion order instead of pop order. The WGL
                # eligibility rule (not priority) is what guarantees the
                # enqueue still linearizes before its return barrier.
                pri[j] = pri[i] - 1
    return np.clip(pri, -(2 ** 31), 2 ** 31 - 1).astype(np.int32)


def _per_value_scan(e, inv32, ret32):
    """Shared queue/bag pattern scan. Returns (enq_of, deq_of, verdict):
    verdict is None when the scan passes, a (False, witness) pair when a
    per-value bad pattern fires, or "skip" when the history is out of
    scope (unknown dequeue values, duplicate enqueue values)."""
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    ok_deq = (f == F_DEQUEUE) & is_ok
    if np.any(np.asarray(e.ret)[ok_deq, 0] == NIL):
        return None, None, "skip"
    enq_of = {}
    for i in np.flatnonzero(f == F_ENQUEUE):
        v = int(e.args[i][0])
        if v in enq_of:
            return None, None, "skip"
        enq_of[v] = i
    deq_of = {}
    for i in np.flatnonzero(ok_deq):
        v = int(e.ret[i][0])
        if v in deq_of:
            return None, None, (False, {"op_index": int(i),
                                        "pattern": "double-dequeue"})
        deq_of[v] = i
        j = enq_of.get(v)
        if j is None:
            return None, None, (
                False, {"op_index": int(i),
                        "pattern": "dequeue-of-unknown-value"})
        if ret32[i] < inv32[j]:
            return None, None, (
                False, {"op_index": int(i),
                        "pattern": "dequeue-before-enqueue"})
    return enq_of, deq_of, None


def _fifo_fast_check(e, inv32, ret32):
    """Aspect-style polynomial decision for FIFO histories (after
    Henzinger/Sezgin/Vafeiadis-style bad patterns; values are unique and
    dequeues always return a value in this model).

    Certain-invalidity patterns (sound even with info ops):
      i.  an ok dequeue of a value nobody enqueued, or dequeued twice
      ii. a dequeue completing before its value's enqueue was invoked
      iii. FIFO order violation: enq(a) really-before enq(b), yet
           deq(b) really-before deq(a) (both dequeues ok)
      iv. enq(a) really-before enq(b), b ok-dequeued, a (ok-enqueued)
          never dequeued -- certain only when no info dequeues exist
          (one could have consumed a) and no info enq took a's value.

    Exact validity: an info-free complete history with none of the
    patterns is linearizable. With info ops, absence of patterns proves
    nothing -> None (search decides).

    Returns True, None, or (False, {"op_index", "pattern"}) -- the
    offending op becomes the failure witness."""
    n = len(e)
    if n == 0:
        return True
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    deq_mask = (f == F_DEQUEUE)
    enq_of, deq_of, status = _per_value_scan(e, inv32, ret32)
    if status == "skip":
        return None
    if status is not None:
        return status
    # (iii): order violations among dequeued values, vectorized
    vals = sorted(deq_of)
    if vals:
        ej = np.asarray([enq_of[v] for v in vals])
        dj = np.asarray([deq_of[v] for v in vals])
        enq_ret = ret32[ej].astype(np.int64)
        enq_inv = inv32[ej].astype(np.int64)
        deq_ret = ret32[dj].astype(np.int64)
        deq_inv = inv32[dj].astype(np.int64)
        a_before_b = enq_ret[:, None] < enq_inv[None, :]
        db_before_da = deq_ret[None, :] < deq_inv[:, None]
        bad = a_before_b & db_before_da
        if np.any(bad):
            ai, bi = np.argwhere(bad)[0]
            return False, {"op_index": int(dj[bi]),
                           "pattern": "fifo-order-violation",
                           "enqueued-after": int(ej[ai])}
    no_info_deq = not bool((deq_mask & ~is_ok).any())
    # (iv): a stuck ahead of a dequeued b
    if no_info_deq and vals:
        undeq_ok = [enq_of[v] for v in enq_of
                    if v not in deq_of and is_ok[enq_of[v]]]
        if undeq_ok:
            ua = np.asarray(undeq_ok)
            ej = np.asarray([enq_of[v] for v in vals])
            bad = (ret32[ua].astype(np.int64)[:, None]
                   < inv32[ej].astype(np.int64)[None, :])
            if np.any(bad):
                ai, bi = np.argwhere(bad)[0]
                return False, {"op_index": int(dj[bi]),
                               "pattern": "dequeue-past-stuck-value",
                               "stuck-enqueue": int(ua[ai])}
    # Exactness needs only info DEQUEUES absent: a crashed enqueue is
    # either observed (committed, with window [invoke, infinity) -- the
    # pattern checks above already treat its return as infinite) or
    # unobserved (never forced, never a pattern-iv stuck value: that set
    # is filtered to ok enqueues). A crashed dequeue, by contrast, may
    # have consumed an arbitrary value, which no pattern models.
    if no_info_deq:
        return True
    return None


fifo_queue_spec = register_model(ModelSpec(
    name="fifo-queue",
    f_codes={"enqueue": F_ENQUEUE, "dequeue": F_DEQUEUE},
    arg_width=1,
    state_size=lambda e: _queue_capacity(e) + 1,
    init_state=lambda e, s: np.concatenate(
        [np.zeros(1, np.int32), np.full(s - 1, NIL, np.int32)]),
    step=_fifo_step,
    make_oracle=FIFOQueue,
    encode_op=_queue_encode,
    pad_state=_pad_nil,
    hint=_fifo_hint,
    fast_check=_fifo_fast_check,
))


def _unordered_step(state, f, args, ret, xp):
    # state = sorted multiset; NIL (int32 min) slots sort first = empty
    C = state.shape[0]
    idxs = xp.arange(C)
    is_enq = f == F_ENQUEUE
    # enqueue: overwrite the first empty slot
    empty = state == NIL
    first_empty = xp.argmax(empty)
    enq_buf = xp.where(idxs == first_empty, args[0], state)
    enq_ok = xp.any(empty)
    # dequeue: clear the first slot equal to ret (value must be known)
    known = ret[0] != NIL
    match = state == ret[0]
    exists = xp.any(match)
    first_match = xp.argmax(match)
    deq_buf = xp.where(idxs == first_match, NIL, state)
    deq_ok = known & exists
    new_buf = xp.where(is_enq, enq_buf, deq_buf)
    ok = xp.where(is_enq, enq_ok, deq_ok)
    return xp.sort(new_buf), ok


def _unordered_fast_check(e, inv32, ret32):
    """Bag (unordered queue) polynomial decision. Without FIFO order,
    the only constraints are per-value: a dequeue of v needs an
    enqueue of v that STARTED before the dequeue finished, each value
    dequeued at most once, and nothing dequeued that was never
    enqueued. That's exact for info-free complete histories; the
    invalidity patterns are sound with info ops too (an observed info
    enqueue definitely happened)."""
    n = len(e)
    if n == 0:
        return True
    _, _, status = _per_value_scan(e, inv32, ret32)
    if status == "skip":
        return None
    if status is not None:
        return status
    f = np.asarray(e.f)
    is_ok = np.asarray(e.is_ok, bool)
    if not bool(((f == F_DEQUEUE) & ~is_ok).any()):
        # crashed enqueues never block a bag verdict (observed ones are
        # committed with open windows; unobserved ones are ignorable)
        return True
    return None


unordered_queue_spec = register_model(ModelSpec(
    name="unordered-queue",
    f_codes={"enqueue": F_ENQUEUE, "dequeue": F_DEQUEUE},
    arg_width=1,
    state_size=_queue_capacity,
    init_state=lambda e, s: np.full(s, NIL, np.int32),
    step=_unordered_step,
    make_oracle=UnorderedQueue,
    encode_op=_queue_encode,
    pad_state=_pad_nil,
    fast_check=_unordered_fast_check,
))
