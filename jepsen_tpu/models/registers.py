"""Register-family models: register, cas-register, multi-register.

Oracle semantics follow knossos.model (consumed by the reference at
checker.clj:233-234 and tests/linearizable_register.clj:37):

* register: write sets the value; read is consistent iff its value is nil
  (unknown) or equals the current value.
* cas-register: adds ``cas [old new]`` which applies iff current == old.
* multi-register: one value-map per op, reads/writes applied atomically.
"""

from __future__ import annotations

import numpy as np

from ..history import NIL
from .base import (Model, ModelSpec, inconsistent, register_model)

F_READ, F_WRITE, F_CAS = 0, 1, 2


# -- oracles -----------------------------------------------------------------

class Register(Model):
    def __init__(self, value=None):
        self.value = value

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "write":
            return Register(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"read {v!r}, expected {self.value!r}")
        raise ValueError(f"register: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, Register) and self.value == other.value

    def __hash__(self):
        return hash(("register", self.value))

    def __repr__(self):
        return f"Register({self.value!r})"


class CASRegister(Model):
    def __init__(self, value=None):
        self.value = value

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "write":
            return CASRegister(v)
        if f == "cas":
            old, new = v
            if old == self.value:
                return CASRegister(new)
            return inconsistent(f"cas {old!r}->{new!r} on {self.value!r}")
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"read {v!r}, expected {self.value!r}")
        raise ValueError(f"cas-register: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, CASRegister) and self.value == other.value

    def __hash__(self):
        return hash(("cas-register", self.value))

    def __repr__(self):
        return f"CASRegister({self.value!r})"


class MultiRegister(Model):
    """Value maps: {:f :read, :value {k v ...}} applies all reads/writes
    atomically (knossos.model/multi-register)."""

    def __init__(self, values=None):
        self.values = dict(values or {})

    def step(self, op):
        f, v = op["f"], op.get("value")
        if f == "write":
            nv = dict(self.values)
            nv.update(v)
            return MultiRegister(nv)
        if f == "read":
            for k, x in (v or {}).items():
                if x is not None and self.values.get(k) != x:
                    return inconsistent(
                        f"read {k}={x!r}, expected {self.values.get(k)!r}")
            return self
        raise ValueError(f"multi-register: unknown f {f!r}")

    def __eq__(self, other):
        return isinstance(other, MultiRegister) and self.values == other.values

    def __hash__(self):
        return hash(("multi-register", tuple(sorted(self.values.items()))))

    def __repr__(self):
        return f"MultiRegister({self.values!r})"


# -- tensor specs ------------------------------------------------------------

def _register_step(state, f, args, ret, xp):
    v = state[0]
    is_write = f == F_WRITE
    new_v = xp.where(is_write, args[0], v)
    read_ok = (ret[0] == NIL) | (ret[0] == v)
    ok = is_write | read_ok
    return xp.stack([new_v]), ok


def _register_encode(spec, intern, f, value, ret_value):
    if f == "write":
        return F_WRITE, [intern.encode(value)], []
    if f == "read":
        # after history/complete, reads may carry their value in the invoke
        rv = ret_value if ret_value is not None else value
        return F_READ, [], [intern.encode(rv)]
    raise ValueError(f"register: unknown f {f!r}")


def _reg_decode(st):
    return {"value": None if int(st[0]) == NIL else int(st[0])}


register_spec = register_model(ModelSpec(
    name="register",
    f_codes={"read": F_READ, "write": F_WRITE},
    arg_width=1,
    state_size=lambda e: 1,
    init_state=lambda e, s: np.full(1, NIL, np.int32),
    step=_register_step,
    make_oracle=Register,
    encode_op=_register_encode,
    decode_state=_reg_decode,
    pure_fs=frozenset({"read"}),
    seal_fs=frozenset({"write"}),
))


def _cas_step(state, f, args, ret, xp):
    v = state[0]
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    is_read = f == F_READ
    cas_ok = v == args[0]
    new_v = xp.where(is_write, args[0],
                     xp.where(is_cas & cas_ok, args[1], v))
    read_ok = (ret[0] == NIL) | (ret[0] == v)
    ok = (is_write | (is_cas & cas_ok) | (is_read & read_ok))
    return xp.stack([new_v]), ok


def _cas_encode(spec, intern, f, value, ret_value):
    if f == "write":
        return F_WRITE, [intern.encode(value)], []
    if f == "cas":
        old, new = value
        return F_CAS, [intern.encode(old), intern.encode(new)], []
    if f == "read":
        rv = ret_value if ret_value is not None else value
        return F_READ, [], [intern.encode(rv)]
    raise ValueError(f"cas-register: unknown f {f!r}")


cas_register_spec = register_model(ModelSpec(
    name="cas-register",
    f_codes={"read": F_READ, "write": F_WRITE, "cas": F_CAS},
    arg_width=2,
    state_size=lambda e: 1,
    init_state=lambda e, s: np.full(1, NIL, np.int32),
    step=_cas_step,
    make_oracle=CASRegister,
    encode_op=_cas_encode,
    decode_state=_reg_decode,
    # cas is state-oblivious when it succeeds but NOT total (it fails
    # from a mismatched state), so only write seals a quiescent cut
    pure_fs=frozenset({"read"}),
    seal_fs=frozenset({"write"}),
))


def _multi_step(state, f, args, ret, xp):
    is_write = f == F_WRITE
    new_state = xp.where(is_write & (args != NIL), args, state)
    read_ok = xp.all((ret == NIL) | (ret == state))
    ok = is_write | read_ok
    return new_state, ok


def multi_register_spec(keys):
    """Build a ModelSpec over a fixed, ordered set of register keys."""
    keys = list(keys)
    k_index = {k: i for i, k in enumerate(keys)}
    K = len(keys)

    def encode(spec, intern, f, value, ret_value):
        vec = [NIL] * K
        if f == "write":
            for k, v in (value or {}).items():
                vec[k_index[k]] = intern.encode(v)
            return F_WRITE, vec, []
        if f == "read":
            rv = ret_value if ret_value is not None else value
            for k, v in (rv or {}).items():
                vec[k_index[k]] = intern.encode(v)
            return F_READ, [], vec
        raise ValueError(f"multi-register: unknown f {f!r}")

    return ModelSpec(
        name=f"multi-register-{K}",
        f_codes={"read": F_READ, "write": F_WRITE},
        arg_width=K,
        state_size=lambda e: K,
        init_state=lambda e, s: np.full(K, NIL, np.int32),
        step=_multi_step,
        make_oracle=MultiRegister,
        encode_op=encode,
        decode_state=lambda st: {
            "values": {k: (None if int(st[i]) == NIL else int(st[i]))
                       for k, i in k_index.items()}},
        # a multi-register write only touches the keys it names, so it
        # is NOT state-oblivious: reads are pure, nothing seals
        pure_fs=frozenset({"read"}),
    )
