"""Consistency models: knossos.model equivalents with both Python oracle and
JAX tensor faces. See base.py for the design."""

from .base import (Inconsistent, Interner, Model, ModelSpec, inconsistent,
                   is_inconsistent, known_models, model_spec, register_model)
from .registers import (CASRegister, MultiRegister, Register,
                        cas_register_spec, multi_register_spec, register_spec)
from .mutex import Mutex, mutex_spec
from .queues import (FIFOQueue, UnorderedQueue, fifo_queue_spec,
                     unordered_queue_spec)

# knossos.model constructor-style aliases
def register(value=None):
    return Register(value)


def cas_register(value=None):
    return CASRegister(value)


def mutex():
    return Mutex()


def fifo_queue(*items):
    return FIFOQueue(items)


def unordered_queue(*items):
    return UnorderedQueue(items)


def multi_register(values=None):
    return MultiRegister(values)


__all__ = [
    "Inconsistent", "Interner", "Model", "ModelSpec", "inconsistent",
    "is_inconsistent", "known_models", "model_spec", "register_model",
    "CASRegister", "MultiRegister", "Register", "Mutex", "FIFOQueue",
    "UnorderedQueue", "register_spec", "cas_register_spec",
    "multi_register_spec", "mutex_spec", "fifo_queue_spec",
    "unordered_queue_spec", "register", "cas_register", "mutex",
    "fifo_queue", "unordered_queue", "multi_register",
]
