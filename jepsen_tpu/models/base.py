"""Consistency models.

Two faces of the same model, differential-tested against each other:

1. **Oracle face** (knossos.model surface, reference checker.clj:233-234,
   jepsen/src/jepsen/tests.clj:8): immutable Python objects with
   ``step(op) -> model' | Inconsistent``. Used by host-side checkers
   (queue checker's model fold) and as the ground truth in tests.

2. **Tensor face** (the TPU path): a ``ModelSpec`` describing a fixed-width
   int32 state vector and a *branch-free* transition
   ``step(state, f, args, ret, xp) -> (state', ok)`` written against an
   array namespace ``xp`` -- the same code runs eagerly under numpy (the
   sequential WGL oracle) and vmapped under jax.numpy on device (the
   batched B&B frontier expansion). Branch-free means where/one-hot only:
   no data-dependent Python control flow, so XLA traces it once.

Value encoding: history values must become int32. Integers pass through;
other hashables are interned per-encoding via Interner.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from ..history import NIL, encode_history


class Inconsistent:
    """Marker for an invalid transition (knossos.model/inconsistent)."""

    def __init__(self, msg=""):
        self.msg = msg

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"

    def __bool__(self):
        return False


def inconsistent(msg=""):
    return Inconsistent(msg)


def is_inconsistent(x) -> bool:
    return isinstance(x, Inconsistent)


class Model:
    """Immutable state machine: ``step(op) -> Model | Inconsistent``."""

    def step(self, op):  # pragma: no cover - interface
        raise NotImplementedError


class Interner:
    """Maps arbitrary hashable values to dense non-negative int32 codes.
    Integers that fit int32 map to themselves (so arithmetic-flavored tests
    stay readable); everything else is interned."""

    INT_LO = -(2**30)
    INT_HI = 2**30

    def __init__(self):
        self._codes = {}
        self._next = 2**30  # interned codes live above the passthrough range

    def encode(self, v):
        if v is None:
            return NIL
        if isinstance(v, bool):
            return int(v)
        if isinstance(v, (int, np.integer)) and self.INT_LO < v < self.INT_HI:
            return int(v)
        code = self._codes.get(v)
        if code is None:
            code = self._next
            self._next += 1
            self._codes[v] = code
        return code


@dataclasses.dataclass
class ModelSpec:
    """Tensor-face description of a model (see module docstring).

    Attributes:
      name: model name (matches the oracle class).
      f_codes: map op-f (str) -> int code.
      arg_width: A, width of the args/ret vectors.
      state_size: fn(EncodedHistory) -> S, the int32 state-vector length
        (history-dependent for queues: capacity = #enqueues).
      init_state: fn(EncodedHistory, S) -> np.int32[S].
      step: fn(state, f, args, ret, xp) -> (state', ok). All arrays from
        namespace xp; state (S,), f scalar, args/ret (A,), ok scalar bool.
      make_oracle: fn() -> Model for the same initial state.
    """

    name: str
    f_codes: dict
    arg_width: int
    state_size: Callable
    init_state: Callable
    step: Callable
    make_oracle: Callable
    # encode one op: (f, invoke_value, completion_value|None)
    #   -> (fcode, args_list, ret_list)
    encode_op: Callable = None
    # optional fn(init_state, S_pad) -> padded init state, for models whose
    # state size is history-dependent (queues). Padding must preserve state
    # canonicalization so the checker's dedup still sees equal states as
    # byte-equal. None = state size is fixed, never padded.
    pad_state: Callable = None
    # optional fn(e, invoke32, ret32) -> int32[n] linearization priority
    # for the device search (lower = try earlier). Purely a heuristic --
    # soundness never depends on it. None = earliest-deadline-first
    # (order by return index). Queues use this to order enqueues by
    # their values' dequeue order (an enqueue must linearize before the
    # dequeue that returns its value).
    hint: Callable = None
    # optional fn(e, invoke32, ret32) -> True | False | None: an EXACT
    # polynomial-time decision procedure for the subclass of histories it
    # understands (None = can't decide, fall back to search). Queues use
    # aspect-style bad-pattern detection, which scales where the NP-hard
    # search cannot.
    fast_check: Callable = None
    # optional fn(state_vec) -> jsonable: human-readable rendering of a
    # state vector for failure witnesses (knossos shows e.g.
    # #knossos.model.CASRegister{:value 3}); None = raw int list
    decode_state: Callable = None
    # optional frozenset of op :f names that never change state (pure
    # reads) AND always step ok when args/ret are entirely unknown.
    # The search planner (analysis/searchplan.py) elides unconstrained
    # non-ok pure ops and lets pure ops float across quiescent cuts.
    # None = no op is known pure; planning degrades, never misjudges.
    pure_fs: frozenset = None
    # optional frozenset of op :f names that are TOTAL (steppable from
    # every state) and STATE-OBLIVIOUS (the post-state depends only on
    # the op, e.g. a register write; NOT cas — it isn't total). The
    # planner's sealed quiescent cuts replay such an op as the next
    # segment's state seed. None = no cuts for this model.
    seal_fs: frozenset = None
    # optional fn(e, invoke32, ret32) -> bool[n] keep mask | None: ops
    # whose mask is False are removed from the search's candidate set
    # entirely. Must be validity-preserving BOTH ways (the check with and
    # without the pruned ops must agree) -- only provably-droppable
    # non-ok ops qualify (e.g. crashed enqueues of never-observed
    # values). None = no pruning applies to this history.
    prune: Callable = None

    def encode(self, hist):
        """Encode an event history for this model. Returns (EncodedHistory,
        init_state np.int32[S])."""
        interner = Interner()
        enc = self.encode_op or self.default_encode_op
        e = encode_history(
            hist, lambda f, v, rv: enc(self, interner, f, v, rv),
            self.arg_width)
        s = self.state_size(e)
        return e, np.asarray(self.init_state(e, s), np.int32)

    @staticmethod
    def default_encode_op(spec, interner, f, value, ret_value):
        """Default encoder: f by f_codes; invoke value -> args[0];
        completion value -> ret[0]."""
        fcode = spec.f_codes[f]
        return fcode, [interner.encode(value)], [interner.encode(ret_value)]


_REGISTRY = {}


def register_model(spec: ModelSpec):
    # codelint: ok -- import-time registration, serialized by Python's
    # module import lock; never called from worker threads
    _REGISTRY[spec.name] = spec
    return spec


def model_spec(name_or_spec) -> ModelSpec:
    if isinstance(name_or_spec, ModelSpec):
        return name_or_spec
    try:
        return _REGISTRY[name_or_spec]
    except KeyError:
        raise KeyError(f"Unknown model {name_or_spec!r}; known: "
                       f"{sorted(_REGISTRY)}") from None


def known_models():
    return dict(_REGISTRY)
