"""Pure-functional operation generators (reference jepsen/src/jepsen/
generator.clj, 1452 LoC).

A generator is asked for operations by the interpreter:

    gen_op(gen, test, ctx)     -> None                 (exhausted)
                                | (op_dict, gen')      (emit op)
                                | (PENDING, gen')      (nothing *yet*)
    gen_update(gen, test, ctx, event) -> gen'          (react to event)

Plain data are generators too (generator.clj:545-590): a dict is a one-shot
op (fields filled from context); a callable is invoked (with (test, ctx) if
it takes two args) and its result generates, forever; a list/tuple chains
its members. None is the exhausted generator.

Contexts are immutable maps {time (ns), free_threads, workers
(thread->process)} (generator.clj:453-464); threads are ints 0..n-1 plus
"nemesis". The interpreter owns context bookkeeping; combinators restrict
contexts (reserve, on_threads) or merge alternatives by soonest op
(soonest_op_map, generator.clj:885-927).

Randomness flows through the module-level ``rng`` so the simulated-time
harness (generator/testing.py) can pin seeds like the reference's
with-fixed-rand-int (generator/test.clj:31-48).
"""

from __future__ import annotations

import builtins
import inspect
import logging
import random as _random
from dataclasses import dataclass, replace

from .. import obs
from ..util import secs_to_nanos

logger = logging.getLogger(__name__)

NEMESIS = "nemesis"


class _Pending:
    def __repr__(self):
        return "PENDING"


#: "a process may become free later, but nothing can run now"
PENDING = _Pending()

#: module randomness; rebind via fixed_rand for deterministic tests
rng = _random.Random()


class fixed_rand:
    """Context manager pinning generator randomness to a seed (reference
    generator/test.clj:31-48, seed 45100)."""

    def __init__(self, seed=45100):
        self.seed = seed

    def __enter__(self):
        self.saved = rng.getstate()
        rng.seed(self.seed)
        return self

    def __exit__(self, *exc):
        rng.setstate(self.saved)


# ---------------------------------------------------------------------------
# Context

@dataclass(frozen=True)
class Context:
    """Immutable generator context (generator.clj:453-464)."""

    time: int                      # ns, relative
    free_threads: tuple            # threads not running an op (ordered)
    workers: dict                  # thread -> process

    def free_processes(self):
        return [self.workers[t] for t in self.free_threads]

    def some_free_process(self):
        """A uniformly random free process (generator.clj:480-487 uses a
        bifurcan set for fair O(1) nth; a tuple does the same here)."""
        if not self.free_threads:
            return None
        t = self.free_threads[rng.randrange(len(self.free_threads))]
        return self.workers[t]

    def all_threads(self):
        return list(self.workers.keys())

    def all_processes(self):
        return list(self.workers.values())

    def process_to_thread(self, process):
        for t, p in self.workers.items():
            if p == process:
                return t
        return None

    def thread_to_process(self, thread):
        return self.workers.get(thread)

    def next_process(self, thread):
        """Process id to assign a thread whose process crashed: bump by the
        number of client processes (generator.clj:519-527)."""
        if isinstance(thread, int):
            clients = len([p for p in self.workers.values()
                           if isinstance(p, int)])
            return self.workers[thread] + clients
        return thread

    def restrict(self, pred):
        """Context restricted to threads satisfying pred (on-threads-context,
        generator.clj:844-863)."""
        return Context(
            time=self.time,
            free_threads=tuple(t for t in self.free_threads if pred(t)),
            workers={t: p for t, p in self.workers.items() if pred(t)})

    def with_time(self, time):
        return replace(self, time=time)

    def busy(self, thread):
        """Mark a thread busy (its op was dispatched)."""
        return replace(self, free_threads=tuple(
            t for t in self.free_threads if t != thread))

    def free(self, thread):
        """Mark a thread free again (its op completed)."""
        if thread in self.free_threads:
            return self
        return replace(self, free_threads=self.free_threads + (thread,))

    def with_worker(self, thread, process):
        w = dict(self.workers)
        w[thread] = process
        return replace(self, workers=w)


def context(test):
    """Fresh context for a test map: nemesis + concurrency client threads
    (generator.clj:453-464)."""
    threads = (NEMESIS,) + tuple(range(test.get("concurrency", 1)))
    return Context(time=0, free_threads=threads,
                   workers={t: t for t in threads})


def fill_in_op(op, ctx):
    """Fill missing type/process/time from context; PENDING if no process is
    free (generator.clj:531-543)."""
    p = ctx.some_free_process()
    if p is None:
        return PENDING
    op = dict(op)
    op.setdefault("time", ctx.time)
    op.setdefault("process", p)
    op.setdefault("type", "invoke")
    return op


# ---------------------------------------------------------------------------
# protocol dispatch (generator.clj extend-protocol, :545-620)

class Generator:
    """Base class for combinator generators."""

    def op(self, test, ctx):  # pragma: no cover - interface
        raise NotImplementedError

    def update(self, test, ctx, event):
        return self


def gen_op(gen, test, ctx):
    """Ask any generator-like value for [op, gen'] / [PENDING, gen'] /
    None."""
    while True:
        if gen is None:
            return None
        if isinstance(gen, Generator):
            return gen.op(test, ctx)
        if isinstance(gen, dict):
            op = fill_in_op(gen, ctx)
            return (PENDING, gen) if op is PENDING else (op, None)
        if callable(gen):
            x = gen(test, ctx) if _arity2(gen) else gen()
            if x is None:
                return None
            # the function result generates once, then the fn is re-invoked
            return gen_op([x, gen], test, ctx)
        if isinstance(gen, (list, tuple)):
            if not gen:
                return None
            res = gen_op(gen[0], test, ctx)
            if res is not None:
                op, g2 = res
                rest = list(gen[1:])
                return (op, [g2] + rest if rest else g2)
            gen = list(gen[1:])
            continue
        raise TypeError(f"not a generator: {gen!r}")


def gen_update(gen, test, ctx, event):
    """Propagate an event (invoke/complete) to a generator-like value."""
    if gen is None or isinstance(gen, dict) or callable(gen):
        return gen
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, (list, tuple)):
        if not gen:
            return None
        return [gen_update(gen[0], test, ctx, event)] + list(gen[1:])
    raise TypeError(f"not a generator: {gen!r}")


def _arity2(f):
    try:
        sig = inspect.signature(f)
    except (TypeError, ValueError):
        return False
    params = list(sig.parameters.values())
    # NB: builtins.any — this module's `any` combinator shadows the builtin
    if builtins.any(p.kind == p.VAR_POSITIONAL for p in params):
        return True   # *args accepts (test, ctx); prefer the 2-arg call
    positional = [p for p in params
                  if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(positional) >= 2


# ---------------------------------------------------------------------------
# validation / debugging combinators

class InvalidOp(Exception):
    pass


@dataclass(frozen=True)
class Validate(Generator):
    """Rejects malformed [op, gen'] tuples (generator.clj:622-676);
    installed automatically by the interpreter."""

    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        if op is not PENDING:
            problems = []
            if not isinstance(op, dict):
                problems.append("should be either PENDING or a dict")
            else:
                if op.get("type") not in ("invoke", "info", "sleep", "log"):
                    problems.append(
                        "type should be invoke, info, sleep, or log")
                if not isinstance(op.get("time"), (int, float)):
                    problems.append("time should be a number")
                if op.get("process") is None:
                    problems.append("no process")
                elif op["process"] not in ctx.free_processes():
                    problems.append(
                        f"process {op['process']!r} is not free")
            if problems:
                raise InvalidOp(f"Generator produced invalid op {op!r}: "
                                + "; ".join(problems))
        return op, Validate(gen2)

    def update(self, test, ctx, event):
        return Validate(gen_update(self.gen, test, ctx, event))


@dataclass(frozen=True)
class FriendlyExceptions(Generator):
    """Wraps generator exceptions with generator/context info
    (generator.clj:678-717)."""

    gen: object

    def op(self, test, ctx):
        try:
            res = gen_op(self.gen, test, ctx)
        except Exception as e:  # noqa: BLE001 - rethrown with context
            raise RuntimeError(
                f"Generator threw {type(e).__name__} when asked for an "
                f"operation. Generator: {self.gen!r}; context: {ctx!r}") \
                from e
        if res is None:
            return None
        op, gen2 = res
        return op, FriendlyExceptions(gen2)

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(
                gen_update(self.gen, test, ctx, event))
        except Exception as e:  # noqa: BLE001 - rethrown with context
            raise RuntimeError(
                f"Generator threw {type(e).__name__} during update. "
                f"Event: {event!r}; context: {ctx!r}") from e


@dataclass(frozen=True)
class Trace(Generator):
    """Logs ops and updates with a tag (generator.clj:720-762), and
    routes the same stream through the obs tracer when one is bound —
    one unified event stream, not a second ad-hoc one (the log lines
    stay for grep parity with the reference)."""

    k: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        logger.info("%s op -> %r", self.k,
                    res[0] if res else None)
        obs.gen_event(self.k, "op", res[0] if res else None)
        if res is None:
            return None
        op, gen2 = res
        return op, Trace(self.k, gen2)

    def update(self, test, ctx, event):
        logger.info("%s update <- %r", self.k, event)
        obs.gen_event(self.k, "update", event)
        return Trace(self.k, gen_update(self.gen, test, ctx, event))


def validate(gen):
    return Validate(gen)


def friendly_exceptions(gen):
    return FriendlyExceptions(gen)


def trace(k, gen):
    return Trace(k, gen)


# ---------------------------------------------------------------------------
# transformation combinators

@dataclass(frozen=True)
class Map(Generator):
    """Transforms emitted ops with f; PENDING/None bypass
    (generator.clj:765-789)."""

    f: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        return (op if op is PENDING else self.f(op)), Map(self.f, gen2)

    def update(self, test, ctx, event):
        return Map(self.f, gen_update(self.gen, test, ctx, event))


def map(f, gen):  # noqa: A001 - mirrors gen/map
    return Map(f, gen)


def f_map(fm, gen):
    """Renames :f values via mapping fm (generator.clj:791-796); used to
    namespace composed nemesis generators."""
    def transform(op):
        # ops without :f (sleep/log) pass through unchanged, as do :f
        # values the mapping doesn't know (reference `(update op :f fm)`
        # maps a missing key to nil rather than crashing)
        if "f" not in op:
            return op
        op = dict(op)
        op["f"] = fm.get(op["f"], op["f"]) if isinstance(fm, dict) \
            else fm(op["f"])
        return op
    return Map(transform, gen)


@dataclass(frozen=True)
class Filter(Generator):
    """Only ops matching pred pass; PENDING bypasses
    (generator.clj:798-817)."""

    pred: object
    gen: object

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = gen_op(gen, test, ctx)
            if res is None:
                return None
            op, gen2 = res
            if op is PENDING or self.pred(op):
                return op, Filter(self.pred, gen2)
            gen = gen2

    def update(self, test, ctx, event):
        return Filter(self.pred, gen_update(self.gen, test, ctx, event))


def filter(pred, gen):  # noqa: A001 - mirrors gen/filter
    return Filter(pred, gen)


@dataclass(frozen=True)
class IgnoreUpdates(Generator):
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        return op, IgnoreUpdates(gen2)

    def update(self, test, ctx, event):
        return self


def ignore_updates(gen):
    return IgnoreUpdates(gen)


@dataclass(frozen=True)
class OnUpdate(Generator):
    """Calls (f this test ctx event) on update (generator.clj:827-842)."""

    f: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        return op, OnUpdate(self.f, gen2)

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


# ---------------------------------------------------------------------------
# thread routing

@dataclass(frozen=True)
class OnThreads(Generator):
    """Restricts a generator to threads satisfying pred; updates from other
    threads don't propagate (generator.clj:864-883)."""

    pred: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx.restrict(self.pred))
        if res is None:
            return None
        op, gen2 = res
        return op, OnThreads(self.pred, gen2)

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        if thread is not None and self.pred(thread):
            return OnThreads(self.pred, gen_update(
                self.gen, test, ctx.restrict(self.pred), event))
        return self


def on_threads(pred, gen):
    return OnThreads(_as_pred(pred), gen)


on = on_threads   # backwards-compat alias, generator.clj:884


def _as_pred(p):
    if callable(p) and not isinstance(p, (set, frozenset)):
        return p
    s = builtins.set(p) if not isinstance(p, (set, frozenset)) else p
    return lambda t: t in s


def soonest_op_map(m1, m2):
    """Merge two {op, weight, ...} candidates, preferring the earlier op;
    ties break randomly proportional to weight (generator.clj:885-927)."""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    op1, op2 = m1["op"], m2["op"]
    if op1 is PENDING:
        return m2
    if op2 is PENDING:
        return m1
    t1, t2 = op1["time"], op2["time"]
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        chosen = m1 if rng.randrange(w1 + w2) < w1 else m2
        chosen = dict(chosen)
        chosen["weight"] = w1 + w2
        return chosen
    return m1 if t1 < t2 else m2


@dataclass(frozen=True)
class Any(Generator):
    """Ops from whichever sub-generator is soonest; updates go to all
    (generator.clj:929-953)."""

    gens: tuple

    def op(self, test, ctx):
        soonest = None
        for i, gen in enumerate(self.gens):
            res = gen_op(gen, test, ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i})
        if soonest is None:
            return None
        gens = builtins.list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return soonest["op"], Any(tuple(gens))

    def update(self, test, ctx, event):
        return Any(tuple(gen_update(g, test, ctx, event)
                         for g in self.gens))


def any(*gens):  # noqa: A001 - mirrors gen/any
    if not gens:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(tuple(gens))


@dataclass(frozen=True)
class EachThread(Generator):
    """Independent copy of the generator per thread
    (generator.clj:955-1007)."""

    fresh_gen: object
    gens: tuple    # ((thread, gen), ...) as a hashable mapping

    def _gen_for(self, thread):
        for t, g in self.gens:
            if t == thread:
                return g
        return self.fresh_gen

    def _assoc(self, thread, gen):
        pairs = [(t, g) for t, g in self.gens if t != thread]
        pairs.append((thread, gen))
        return tuple(pairs)

    def op(self, test, ctx):
        soonest = None
        for thread in ctx.free_threads:
            gen = self._gen_for(thread)
            tctx = ctx.restrict(lambda t, thread=thread: t == thread)
            res = gen_op(gen, test, tctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1],
                              "thread": thread})
        if soonest is not None:
            return soonest["op"], EachThread(
                self.fresh_gen,
                self._assoc(soonest["thread"], soonest["gen"]))
        if len(ctx.free_threads) != len(ctx.workers):
            return PENDING, self   # busy threads may still have ops
        return None                # every thread exhausted

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        if thread is None:
            return self
        tctx = ctx.restrict(lambda t: t == thread)
        gen2 = gen_update(self._gen_for(thread), test, tctx, event)
        return EachThread(self.fresh_gen, self._assoc(thread, gen2))


def each_thread(gen):
    return EachThread(gen, ())


@dataclass(frozen=True)
class Reserve(Generator):
    """Dedicates thread ranges to generators, remainder to a default
    (generator.clj:1009-1089)."""

    ranges: tuple        # tuple of frozensets of threads
    gens: tuple          # len(ranges)+1 generators; last is the default

    def op(self, test, ctx):
        soonest = None
        union = frozenset().union(*self.ranges) if self.ranges \
            else frozenset()
        for i, threads in enumerate(self.ranges):
            rctx = ctx.restrict(lambda t, s=threads: t in s)
            res = gen_op(self.gens[i], test, rctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i,
                              "weight": len(threads)})
        dctx = ctx.restrict(lambda t: t not in union)
        res = gen_op(self.gens[-1], test, dctx)
        if res is not None:
            soonest = soonest_op_map(
                soonest, {"op": res[0], "gen": res[1],
                          "i": len(self.ranges),
                          "weight": len(dctx.workers)})
        if soonest is None:
            return None
        gens = builtins.list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return soonest["op"], Reserve(self.ranges, tuple(gens))

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        i = len(self.ranges)
        for j, threads in enumerate(self.ranges):
            if thread in threads:
                i = j
                break
        if i < len(self.ranges):
            rctx = ctx.restrict(lambda t, s=self.ranges[i]: t in s)
        else:
            union = frozenset().union(*self.ranges) if self.ranges \
                else frozenset()
            rctx = ctx.restrict(lambda t: t not in union)
        gens = builtins.list(self.gens)
        gens[i] = gen_update(gens[i], test, rctx, event)
        return Reserve(self.ranges, tuple(gens))


def reserve(*args):
    """reserve(5, write_gen, 10, cas_gen, read_gen): first 5 threads run
    write_gen, next 10 cas_gen, the rest read_gen."""
    *pairs, default = args
    assert len(pairs) % 2 == 0 and default is not None
    ranges = []
    n = 0
    gens = []
    for i in range(0, len(pairs), 2):
        count, gen = pairs[i], pairs[i + 1]
        ranges.append(frozenset(range(n, n + count)))
        gens.append(gen)
        n += count
    gens.append(default)
    return Reserve(tuple(ranges), tuple(gens))


def clients(client_gen, nemesis_gen=None):
    """Restrict to client threads; two-arity combines with a nemesis
    generator (generator.clj:1093-1103)."""
    if nemesis_gen is None:
        return on_threads(lambda t: t != NEMESIS, client_gen)
    return any(clients(client_gen), nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    """Restrict to the nemesis thread (generator.clj:1105-1115)."""
    if client_gen is None:
        return on_threads(lambda t: t == NEMESIS, nemesis_gen)
    return any(nemesis(nemesis_gen), clients(client_gen))


# ---------------------------------------------------------------------------
# scheduling combinators

@dataclass(frozen=True)
class Mix(Generator):
    """Uniform random mixture; ignores updates (generator.clj:1124-1154)."""

    i: int
    gens: tuple

    def op(self, test, ctx):
        if not self.gens:
            return None
        res = gen_op(self.gens[self.i], test, ctx)
        if res is not None:
            op, gen2 = res
            gens = builtins.list(self.gens)
            gens[self.i] = gen2
            return op, Mix(rng.randrange(len(gens)), tuple(gens))
        gens = builtins.list(self.gens)
        del gens[self.i]
        if not gens:
            return None
        return Mix(rng.randrange(len(gens)), tuple(gens)).op(test, ctx)


def mix(gens):
    gens = builtins.list(gens)
    if not gens:
        return None
    return Mix(rng.randrange(len(gens)), tuple(gens))


@dataclass(frozen=True)
class Limit(Generator):
    remaining: int
    gen: object

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        # NB the reference decrements even on PENDING (generator.clj:1158)
        return op, Limit(self.remaining - 1, gen2)

    def update(self, test, ctx, event):
        return Limit(self.remaining,
                     gen_update(self.gen, test, ctx, event))


def limit(remaining, gen):
    return Limit(remaining, gen)


def once(gen):
    return Limit(1, gen)


def log(msg):
    """One log op (generator.clj:1177-1181)."""
    return {"type": "log", "value": msg}


@dataclass(frozen=True)
class Repeat(Generator):
    """Re-emits from an unchanging generator; -1 = forever
    (generator.clj:1183-1210)."""

    remaining: int
    gen: object

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, _ = res
        # underlying gen state does NOT advance; count does (clj:1186-1192)
        return op, Repeat(self.remaining - 1, self.gen)

    def update(self, test, ctx, event):
        return Repeat(self.remaining,
                      gen_update(self.gen, test, ctx, event))


def repeat(*args):
    if len(args) == 1:
        return Repeat(-1, args[0])
    n, gen = args
    assert n >= 0
    return Repeat(n, gen)


@dataclass(frozen=True)
class ProcessLimit(Generator):
    """Emits ops for at most n distinct processes
    (generator.clj:1212-1237)."""

    n: int
    procs: frozenset
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        if op is PENDING:
            return op, ProcessLimit(self.n, self.procs, gen2)
        procs = self.procs | frozenset(ctx.all_processes())
        if len(procs) > self.n:
            return None
        return op, ProcessLimit(self.n, procs, gen2)

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.procs,
                            gen_update(self.gen, test, ctx, event))


def process_limit(n, gen):
    return ProcessLimit(n, frozenset(), gen)


@dataclass(frozen=True)
class TimeLimit(Generator):
    """Emits ops for dt nanoseconds after its first op
    (generator.clj:1239-1263)."""

    limit: int
    cutoff: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        if op is PENDING:
            return op, TimeLimit(self.limit, self.cutoff, gen2)
        cutoff = self.cutoff if self.cutoff is not None \
            else op["time"] + self.limit
        if op["time"] >= cutoff:
            return None
        return op, TimeLimit(self.limit, cutoff, gen2)

    def update(self, test, ctx, event):
        return TimeLimit(self.limit, self.cutoff,
                         gen_update(self.gen, test, ctx, event))


def time_limit(dt_seconds, gen):
    return TimeLimit(secs_to_nanos(dt_seconds), None, gen)


@dataclass(frozen=True)
class Stagger(Generator):
    """Schedules ops at uniformly random intervals in [0, 2*dt), globally
    (generator.clj:1265-1306)."""

    dt: int
    next_time: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        if op is PENDING:
            return op, self
        next_time = self.next_time if self.next_time is not None \
            else ctx.time
        nxt = next_time + int(rng.random() * self.dt)
        if next_time <= op["time"]:
            return op, Stagger(self.dt, nxt, gen2)
        op = dict(op)
        op["time"] = next_time
        return op, Stagger(self.dt, nxt, gen2)

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time,
                       gen_update(self.gen, test, ctx, event))


def stagger(dt_seconds, gen):
    """Roughly one op per dt seconds across all threads."""
    return Stagger(secs_to_nanos(2 * dt_seconds), None, gen)


@dataclass(frozen=True)
class Delay(Generator):
    """Ops exactly dt apart (catching up if behind)
    (generator.clj:1344-1370)."""

    dt: int
    next_time: object
    gen: object

    def op(self, test, ctx):
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        if op is PENDING:
            return op, Delay(self.dt, self.next_time, gen2)
        next_time = self.next_time if self.next_time is not None \
            else op["time"]
        op = dict(op)
        op["time"] = max(op["time"], next_time)
        return op, Delay(self.dt, next_time + self.dt, gen2)

    def update(self, test, ctx, event):
        return Delay(self.dt, self.next_time,
                     gen_update(self.gen, test, ctx, event))


def delay(dt_seconds, gen):
    return Delay(secs_to_nanos(dt_seconds), None, gen)


def sleep(dt_seconds):
    """One special op making its process sleep dt seconds
    (generator.clj:1372-1376)."""
    return {"type": "sleep", "value": dt_seconds}


@dataclass(frozen=True)
class Synchronize(Generator):
    """Waits for all workers free before starting
    (generator.clj:1378-1398)."""

    gen: object

    def op(self, test, ctx):
        if len(ctx.free_threads) == len(ctx.workers) and \
                builtins.set(ctx.free_threads) == \
                builtins.set(ctx.workers.keys()):
            return gen_op(self.gen, test, ctx)
        return PENDING, self

    def update(self, test, ctx, event):
        return Synchronize(gen_update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Each generator runs to completion, with barriers between
    (generator.clj:1400-1405)."""
    return [synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronize a). Args backwards for pipeline composition
    (generator.clj:1407-1416)."""
    return [b, synchronize(a)]


@dataclass(frozen=True)
class UntilOk(Generator):
    """Emits until one op completes ok (generator.clj:1418-1436)."""

    gen: object
    done: bool = False

    def op(self, test, ctx):
        if self.done:
            return None
        res = gen_op(self.gen, test, ctx)
        if res is None:
            return None
        op, gen2 = res
        return op, UntilOk(gen2, self.done)

    def update(self, test, ctx, event):
        if event.get("type") == "ok":
            return UntilOk(self.gen, True)
        return UntilOk(gen_update(self.gen, test, ctx, event), self.done)


def until_ok(gen):
    return UntilOk(gen)


@dataclass(frozen=True)
class FlipFlop(Generator):
    """Alternates between generators; stops when one is exhausted; ignores
    updates (generator.clj:1438-1452)."""

    gens: tuple
    i: int

    def op(self, test, ctx):
        res = gen_op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        op, gen2 = res
        gens = builtins.list(self.gens)
        gens[self.i] = gen2
        return op, FlipFlop(tuple(gens), (self.i + 1) % len(gens))


def flip_flop(a, b):
    return FlipFlop((a, b), 0)


def concat(*gens):
    """Chain arbitrary generators (generator.clj:776-781)."""
    return builtins.list(gens)


@dataclass(frozen=True)
class Cycle(Generator):
    """Endless repetition of a SEQUENCE of generators: the chain
    advances through its elements and restarts fresh when exhausted --
    the analogue of driving a generator with Clojure's (cycle [...])
    lazy seq (e.g. zookeeper.clj:121-124's sleep/start/sleep/stop
    nemesis schedule). Contrast `repeat`, which never advances the
    underlying generator and so re-emits its FIRST op forever."""

    template: tuple
    current: object = None

    def op(self, test, ctx):
        cur = self.current if self.current is not None \
            else builtins.list(self.template)
        res = gen_op(cur, test, ctx)
        if res is None:
            res = gen_op(builtins.list(self.template), test, ctx)
            if res is None:   # template yields nothing at all
                return None
        op, g2 = res
        return op, Cycle(self.template, g2)

    def update(self, test, ctx, event):
        if self.current is None:
            return self
        return Cycle(self.template,
                     gen_update(self.current, test, ctx, event))


def cycle(*gens):
    return Cycle(tuple(gens))
