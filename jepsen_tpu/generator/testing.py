"""Deterministic simulated-time execution of generators (reference
jepsen/src/jepsen/generator/test.clj -- shipped in src, not test/, because
consumers test their own generators with it).

``simulate(test, gen, completion_fn)`` runs a generator against a synthetic
scheduler: ops are dispatched to virtual threads, completed by
``completion_fn(op) -> completion op (with :time advanced)``, and the
emitted history (invocations + completions, in time order) is returned.
No wall clock, no threads; with ``fixed_rand`` the result is fully
deterministic (fixed seed 45100, test.clj:31-48).
"""

from __future__ import annotations

import heapq

from . import (PENDING, context, fixed_rand, gen_op,
               gen_update, validate)

#: latency applied by the `perfect` completion functions: 10 ns
#: (generator/test.clj:110-120)
PERFECT_LATENCY = 10


def default_test():
    """A tiny test map for generator tests (test.clj: n=2)."""
    return {"concurrency": 2, "nodes": ["n1", "n2"]}


def simulate(test, gen, completion_fn, limit=100_000):
    """Simulate the full execution of ``gen`` (test.clj:50-108).

    completion_fn: (completed-invocation) -> completion op or None (op never
    completes; its thread stays busy forever).

    Returns the history: all emitted invocations and completions sorted by
    dispatch order.
    """
    gen = validate(gen)
    ctx = context(test)
    # pending completions: heap of (time, seq, thread, completion-op)
    completions = []
    seq = 0
    history = []

    for _ in range(limit):
        # complete anything due before the generator's next op
        res = gen_op(gen, test, ctx)
        if res is None:
            if not completions:
                return history
            op = None
        else:
            op = res[0]

        if completions and (
                op is None or op is PENDING
                or completions[0][0] <= op["time"]):
            # process the earliest completion first
            t, _, thread, comp = heapq.heappop(completions)
            ctx = ctx.with_time(max(ctx.time, t)).free(thread)
            if comp["type"] in ("ok", "fail", "info"):
                if comp["type"] == "info" and isinstance(
                        comp.get("process"), int):
                    # crashed process: bump to a fresh process id
                    ctx = ctx.with_worker(
                        thread, ctx.next_process(thread))
                history.append(comp)
                gen = gen_update(gen, test, ctx, comp)
            continue

        if op is None:
            return history
        if op is PENDING:
            # do NOT commit the pending generator state: state advances
            # only on dispatch (mirrors test.clj:62-71 recurring with gen,
            # not gen', when completing instead of dispatching)
            if not completions:
                # deadlock: nothing pending can ever complete
                return history
            continue

        # dispatch the op
        gen = res[1]
        ctx = ctx.with_time(max(ctx.time, op["time"]))
        thread = ctx.process_to_thread(op["process"])
        history.append(op)
        gen = gen_update(gen, test, ctx, op)
        if op["type"] in ("invoke",):
            ctx = ctx.busy(thread)
            comp = completion_fn(op)
            if comp is not None:
                seq += 1
                heapq.heappush(
                    completions, (comp["time"], seq, thread, comp))
        elif op["type"] == "sleep":
            # thread sleeps: busy until time + value seconds
            ctx = ctx.busy(thread)
            seq += 1
            wake = {"type": "wake", "process": op["process"],
                    "time": op["time"] + int(op["value"] * 1e9)}
            heapq.heappush(completions, (wake["time"], seq, thread, wake))
        # log ops take no time and leave the thread free
    raise RuntimeError(f"simulate exceeded {limit} steps")


def perfect(op):
    """Completion fn: everything succeeds in 10 ns (test.clj `perfect`)."""
    comp = dict(op)
    comp["type"] = "ok"
    comp["time"] = op["time"] + PERFECT_LATENCY
    return comp


def perfect_info(op):
    """Completion fn: everything crashes (:info) in 10 ns."""
    comp = dict(op)
    comp["type"] = "info"
    comp["time"] = op["time"] + PERFECT_LATENCY
    return comp


class imperfect:
    """Rotating fail/info/ok completions, 10/20/30 ns latencies
    (test.clj `imperfect`)."""

    def __init__(self):
        self.i = 0

    def __call__(self, op):
        kinds = [("fail", 10), ("info", 20), ("ok", 30)]
        kind, latency = kinds[self.i % 3]
        self.i += 1
        comp = dict(op)
        comp["type"] = kind
        comp["time"] = op["time"] + latency
        return comp


def quick(gen, test=None, seed=45100, limit=100_000):
    """Simulate with perfect completions and a fixed seed; returns the
    history (test.clj `quick`)."""
    test = test or default_test()
    with fixed_rand(seed):
        return simulate(test, gen, perfect, limit=limit)


def invocations(history):
    return [op for op in history if op["type"] == "invoke"]


def ops_by_f(history):
    out = {}
    for op in history:
        out.setdefault(op.get("f"), []).append(op)
    return out
