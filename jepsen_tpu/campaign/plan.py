"""Sweep matrices -> test cells.

A campaign plan is a declarative matrix::

    {"base": {"time-limit": 5},                 # shared cell params
     "axes": {"workload": ["register", "bank"],
              "concurrency": [2, 4],
              "seed": [0, 1, 2]}}

``expand`` takes the cartesian product of the axes (in sorted axis
order, so cell order is deterministic) and merges each combination
over ``base`` into a *cell*: ``{"id": "concurrency=2,seed=0,"
"workload=register", "params": {...}}``. Cell ids are the campaign's
unit of identity -- the journal keys resume on them, the report groups
flakes by them -- so they are derived purely from the axis values,
never from wall clock or ordering.

Validation is the planlint PL012 pass (analysis/planlint.py):
empty matrices, duplicate cell ids, seed collisions, and per-cell
robustness-knob inconsistencies (the PL011 rules applied per cell) all
surface before any cell runs.
"""

from __future__ import annotations

import itertools

__all__ = ["CampaignPlanError", "normalize", "cell_id", "group_id",
           "expand", "lint", "validate"]


class CampaignPlanError(ValueError):
    """A campaign matrix failed PL012 validation."""

    def __init__(self, diags):
        from ..analysis import render_text
        self.diagnostics = diags
        super().__init__(render_text(diags,
                                     title="campaign plan invalid:"))


def normalize(matrix):
    """Canonical {"base": {...}, "axes": {name: [values...]}} form.

    Accepts the canonical form, or a plain ``{name: values}`` dict
    (every list-valued entry becomes an axis, scalars go to base), and
    the ``"seeds": N`` shorthand for ``axes["seed"] = [0..N-1]``."""
    matrix = dict(matrix or {})
    base = dict(matrix.pop("base", None) or {})
    axes = matrix.pop("axes", None)
    if axes is None:
        axes = {}
        for k, v in matrix.items():
            if k == "seeds":
                continue
            if isinstance(v, (list, tuple)):
                axes[k] = list(v)
            else:
                base[k] = v
    else:
        axes = {k: list(v) for k, v in dict(axes).items()}
    seeds = matrix.get("seeds")
    if seeds and "seed" not in axes:
        axes["seed"] = list(range(int(seeds)))
    return {"base": base, "axes": axes}


def _fmt(v):
    """Compact, filesystem/journal-safe value rendering for cell ids."""
    s = str(v)
    return "".join(c if c.isalnum() or c in "._+-" else "_" for c in s)


def cell_id(params, axis_names):
    """Deterministic id from the cell's axis values alone (base params
    are shared, so they carry no identity)."""
    return ",".join(f"{a}={_fmt(params[a])}" for a in sorted(axis_names)
                    if a in params)


def group_id(params, axis_names):
    """The cell id with the seed axis stripped: cells sharing a group
    differ only by seed, which is exactly the population flake
    detection compares (report.py)."""
    return ",".join(f"{a}={_fmt(params[a])}" for a in sorted(axis_names)
                    if a in params and a != "seed") or "<all>"


def expand(matrix):
    """Expand a matrix into an ordered list of cells:
    ``[{"id", "group", "params"}, ...]``. Never raises on semantic
    problems -- run ``lint``/``validate`` for those -- but the result
    is [] for an empty matrix."""
    norm = normalize(matrix)
    axes = norm["axes"]
    names = sorted(axes)
    if not names or any(not axes[a] for a in names):
        return []
    cells = []
    for combo in itertools.product(*(axes[a] for a in names)):
        params = dict(norm["base"])
        params.update(dict(zip(names, combo)))
        cells.append({"id": cell_id(params, names),
                      "group": group_id(params, names),
                      "params": params})
    return cells


def lint(matrix):
    """PL012 diagnostics for a matrix (see analysis/planlint.py)."""
    from ..analysis import planlint
    norm = normalize(matrix)
    return planlint.lint_campaign(norm, expand(norm))


def validate(matrix):
    """Expand + lint; raise CampaignPlanError on PL012 errors, return
    (cells, diagnostics) otherwise."""
    from ..analysis import errors
    cells = expand(matrix)
    diags = lint(matrix)
    if errors(diags):
        raise CampaignPlanError(diags)
    return cells, diags
