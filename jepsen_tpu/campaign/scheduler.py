"""The campaign scheduler: a bounded, resumable fleet of test cells.

Execution model:

* **Worker pool.** Cells run on ``parallel`` threads. The CPU-side
  harness phases (db setup, generator, interpreter) of different cells
  overlap freely -- that is where wall clock goes in a sweep of short
  tests.
* **Device slots.** Each cell's checker is wrapped so the expensive
  check phase -- the device WGL search -- holds one of
  ``device_slots`` semaphore slots. One accelerator gets one slot so
  searches serialize instead of fighting over HBM; sharded checkers
  (parallel/keyshard) or CPU-only sweeps can raise it.
* **Abort latch.** The whole campaign shares one
  ``robust.AbortLatch``, wired to SIGINT/SIGTERM on the main thread
  and injected as every cell's ``test["abort"]``: the first signal
  stops new cells AND gracefully drains the running ones (their
  partial histories are salvaged and checked by the normal robust
  machinery); a second signal hard-aborts. Either way the journal is
  left resumable.
* **Journal.** Every finished cell is appended to ``cells.jsonl``
  (flush+fsync) the moment it completes; ``resume=True`` skips cells
  whose latest record is terminal and re-runs aborted/missing ones.
* **Telemetry.** The scheduler keeps its OWN Tracer/Registry (per-cell
  spans, outcome counters, wall/wait histograms) dumped into the
  campaign directory -- deliberately not the process-global `obs`
  binding, which cells rebind per run (overlapping core.runs
  cross-attribute the global pair; instance handles don't). Compile
  reuse is bracketed via compile_cache.stats() deltas.

Cells are ``{"id": str, "test": <test map>}`` or ``{"id": str,
"build": callable(params) -> test map, "params": {...}}``; lazy builds
keep a malformed cell's crash contained to that cell.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor

from .. import core, robust, store
from ..checker import core as checker_core
from ..obs import Registry, Tracer
from . import compile_cache
from . import report as creport
from .journal import CampaignJournal

logger = logging.getLogger(__name__)

__all__ = ["CampaignError", "run_cells", "new_campaign_id"]


class CampaignError(RuntimeError):
    """Campaign-level wiring failure (bad resume target, no cells)."""


def new_campaign_id():
    return "campaign-" + store.local_time()


_stamp_lock = threading.Lock()
_stamps = set()


def _unique_start_time(name):
    """A start-time stamp no other cell of this process holds for the
    same test name. The store path is base_dir/<name>/<start-time>;
    same-workload cells share a name, and two pool threads stamping in
    the same microsecond would silently share (and corrupt) one run
    directory."""
    import datetime
    with _stamp_lock:
        t = datetime.datetime.now().astimezone()
        while (name, store.local_time(t)) in _stamps:
            t += datetime.timedelta(microseconds=1)
        stamp = store.local_time(t)
        _stamps.add((name, stamp))
        return stamp


class _DeviceSlotChecker(checker_core.Checker):
    """Serializes the check phase through the campaign's device-slot
    semaphore; the wait is observed so a slot-starved campaign is
    visible in metrics rather than just slow."""

    def __init__(self, inner, sem, reg):
        self.inner = checker_core.as_checker(inner)
        # keep the wrapped checker's name: spans/metrics must read
        # "jax-wgl"/"Compose", not the wrapper class, so campaign and
        # single-run telemetry stay comparable
        self.name = checker_core.checker_name(self.inner)
        self.sem = sem
        self.reg = reg

    def check(self, test, hist, opts=None):
        t0 = time.monotonic()
        with self.sem:
            self.reg.observe("campaign.device_wait_s",
                             time.monotonic() - t0)
            return self.inner.check(test, hist, opts or {})


def _outcome_of(test, latch):
    """(outcome, valid): test_all-compatible outcomes plus "aborted"
    for CAMPAIGN-latched runs (their salvaged verdict covers only a
    prefix because the sweep was interrupted, so resume runs them
    again). A cell that aborted on its OWN deadline (per-cell
    ``time-limit-s`` sets ``test["aborted"] = "time-limit"`` with no
    latch involved) ran exactly as planned: it keeps its decided
    outcome, or resume would re-run it to the same deadline forever."""
    valid = (test.get("results") or {}).get("valid")
    if test.get("aborted") and latch.is_set() \
            and str(test["aborted"]) == str(latch.reason):
        return "aborted", valid
    # a MONITOR-aborted cell ("monitor-violation" on the cell's own
    # chained latch, never the campaign latch) falls through here on
    # purpose: its salvaged prefix was checked, so its verdict is a
    # TERMINAL outcome (normally False) that --resume must not re-run
    if valid is True or valid is False:
        return valid, valid
    return "unknown", valid


def run_cells(cells, *, campaign_id=None, parallel=1, device_slots=1,
              resume=False, latch=None, run_fn=None, ledger=True,
              backends=None, fleetlint=True, capacity_plan=None,
              certify=True):
    """Run a campaign; returns the aggregated report dict (also
    persisted as report.json in the campaign directory).

    ``resume=True`` requires an existing campaign: pass its id, or
    leave ``campaign_id`` None to pick the most recently touched one.

    ``ledger=True`` (default) attaches the disk-persistent compile
    ledger (fleet.ledger, ``store/compile_ledger/``) so compile-cache
    hits survive restarts and are shared across concurrent campaign
    processes; the campaign's hit/miss delta is appended to the ledger
    at finalize and the aggregate appears in the report.

    ``backends`` (fleet.backends.Failover or a tier list) enables
    per-cell backend failover: before each cell runs, the healthiest
    tier is chosen and applied (a dead accelerator degrades the cell
    to the CPU oracle instead of crashing it); the chosen tier is
    journaled on the cell record.

    ``capacity_plan`` (an analysis.capplan plan dict, built by the
    CLI from the matrix + base options) is persisted as
    ``capacity_plan.json`` in the campaign directory, and at finalize
    the plan's predicted (model, bucket) shapes are diffed against
    the compile shapes this campaign actually noted
    (``compile_cache.noted_keys`` bracket) into
    ``report["capacity"]`` -- the prediction oracle. CONTAINED both
    ends: a crashing planner/oracle never changes a cell outcome or
    the campaign exit code (the searchplan rule).

    ``certify=True`` (default) re-certifies a deterministic sample of
    the cells' persisted runs at finalize from their own artifacts
    (analysis.certify: witness replay + certificate/results
    agreement) into ``report["certification"]``. CONTAINED the same
    way: sampled findings are reported, never outcome-bearing."""
    cells = list(cells)
    ids = [c["id"] for c in cells]
    if len(set(ids)) != len(ids):
        raise CampaignError(f"duplicate cell ids: "
                            f"{sorted({i for i in ids if ids.count(i) > 1})}")
    run_fn = run_fn or core.run
    if resume and campaign_id is None:
        campaign_id = store.latest_campaign()
        if campaign_id is None:
            raise CampaignError("--resume: no campaign found in the store")
    campaign_id = campaign_id or new_campaign_id()
    jr = CampaignJournal(campaign_id)
    prior = jr.load_meta()
    if resume and prior is None:
        raise CampaignError(f"--resume: campaign {campaign_id!r} was "
                            "never started")
    if prior is not None and not resume:
        # starting fresh over an existing journal would append a second
        # run's records onto the first's (duplicate rows, counts off)
        raise CampaignError(
            f"campaign {campaign_id!r} already exists: pass --resume "
            "to continue it, or pick a new --campaign-id")
    if resume and fleetlint:
        # fleetlint preflight before TRUSTING the journal (PL018):
        # the skip-terminal resume fold is only sound over a journal
        # with one writer and one terminal record per cell
        from ..analysis import fleetlint as flint
        from ..analysis import planlint, render_text
        from ..analysis import errors as diag_errors
        pf = planlint.lint_fleetlint({
            "resume?": True,
            "journal-diags": flint.preflight(campaign_id,
                                             records=jr.records())})
        if diag_errors(pf):
            raise CampaignError(render_text(
                diag_errors(pf),
                title="--resume refused: journal fails the fleetlint "
                      "preflight:"))
    if resume:
        # an HA (fleet.ha) journal must be resumed through the FLEET
        # path: the scheduler has no coordinator lease, so its appends
        # would carry no epoch stamp and no fencing -- a live standby
        # could take over mid-resume and both would write
        from ..fleet import ha as fha
        cur_epoch = fha.current_epoch(jr.records())
        if cur_epoch:
            raise CampaignError(
                f"--resume: campaign {campaign_id!r} is coordinator-HA "
                f"(epoch {cur_epoch}): resume it in fleet mode "
                "(--workers ...) so the prior epoch is fenced with a "
                "journaled takeover record first")
    done = jr.completed() if resume else {}
    if resume:
        # compare EVERY journaled cell (terminal or aborted) against
        # the plan: a stale non-terminal record for a cell the matrix
        # no longer contains would otherwise poison the final report
        # and exit code forever
        unknown = {r.get("cell") for r in jr.records()} - set(ids)
        if unknown:
            raise CampaignError(
                f"--resume: journal has cells not in this plan "
                f"{sorted(unknown)} -- same campaign id, different "
                "matrix?")
    # spread the prior meta first: a resume must not strip keys a
    # prior (possibly newer) coordinator recorded alongside ours
    jr.write_meta({
        **(prior or {}),
        "status": "running",
        "created": (prior or {}).get("created") or store.local_time(),
        "updated": store.local_time(),
        "cells": ids,
        "parallel": parallel,
        "device-slots": device_slots,
        "resumes": ((prior or {}).get("resumes") or 0) + (1 if resume
                                                          else 0),
    })

    latch = latch or robust.AbortLatch()
    sem = threading.BoundedSemaphore(max(1, int(device_slots)))
    tr = Tracer(context={"campaign": campaign_id,
                         "role": "coordinator"})
    reg = Registry()
    # crash-safe campaign telemetry: a kill -9'd coordinator leaves
    # its scheduling trace + counters readable next to cells.jsonl
    try:
        tr.attach_journal(
            store.campaign_path(campaign_id, store.TRACE_JOURNAL_FILE))
        reg.attach_journal(
            store.campaign_path(campaign_id,
                                store.METRICS_JOURNAL_FILE))
    except Exception:  # noqa: BLE001 - journals are insurance
        logger.warning("couldn't attach campaign telemetry journals",
                       exc_info=True)
    led = None
    if ledger:
        try:
            from ..fleet import ledger as fledger
            led = fledger.attach()
        except Exception:  # noqa: BLE001 - persistence is optional
            logger.warning("couldn't attach the persistent compile "
                           "ledger; in-memory counting only",
                           exc_info=True)
    if backends is not None:
        from ..fleet import backends as fbackends
        backends = fbackends.as_failover(backends)
    cc_before = compile_cache.stats()
    cap_before = None
    if capacity_plan is not None:
        # persist the plan next to the journal and open the oracle
        # bracket; contained -- the plan is advisory, never a gate
        try:
            from ..analysis import capplan
            capplan.dump_plan(
                capacity_plan,
                store.campaign_path(campaign_id, capplan.PLAN_FILE))
            cap_before = compile_cache.noted_keys()
        except Exception:  # noqa: BLE001 - planning is advisory
            logger.warning("couldn't persist the capacity plan "
                           "(contained)", exc_info=True)
            capacity_plan = None
    pending = [c for c in cells if c["id"] not in done]
    reg.set_gauge("campaign.cells_total", len(cells))
    reg.set_gauge("campaign.cells_resumed", len(done))
    if done:
        logger.info("campaign %s: resuming, %d/%d cells already done",
                    campaign_id, len(done), len(cells))

    def run_one(cell):
        if latch.is_set():
            return None          # never started: no record, resume runs it
        cid = cell["id"]
        t0 = time.monotonic()
        rec = {"cell": cid, "group": cell.get("group") or cid,
               "params": cell.get("params") or {}}
        # per-cell compile-reuse delta: exact at --parallel 1; under a
        # wider pool, concurrent cells' counters cross-attribute, but
        # the SUM stays right and a cell with misses > 0 definitely
        # overlapped a compile -- good enough for the cold/warm wall
        # fold the ledger stats event carries
        cc_cell = compile_cache.stats()
        test = None
        with tr.span("campaign.cell", cat="campaign",
                     args={"cell": cid}):
            try:
                build = cell.get("build")
                test = build(cell.get("params") or {}) if build \
                    else cell["test"]
                if isinstance(test, dict) and test.get("name") \
                        and not test.get("start-time"):
                    test["start-time"] = _unique_start_time(
                        str(test["name"]))
                test = core.prepare_test(test)
                test.setdefault("campaign", {}).update(
                    {"id": campaign_id, "cell": cid,
                     "params": cell.get("params") or {}})
                # trace-context propagation: the cell's own run-scope
                # tracer/registry stamp every span and metric with
                # {campaign, cell}, so obs.merge can fold the run's
                # trace into the campaign timeline
                test.setdefault("obs-context",
                                {"campaign": campaign_id, "cell": cid})
                test["abort"] = latch
                if backends is not None:
                    # failover tiering: a down accelerator degrades
                    # this cell to a slower tier instead of crashing it
                    tier = backends.choose()
                    backends.apply(test, tier)
                    rec["backend"] = tier
                    reg.inc("fleet.backend_cells", tier=str(tier))
                if test.get("checker") is not None:
                    test["checker"] = _DeviceSlotChecker(
                        test["checker"], sem, reg)
                if test.get("monitor"):
                    # monitored cells count against the device slots:
                    # the monitor's device-engine chunk checks acquire
                    # the same semaphore as offline searches, so a
                    # fleet can't oversubscribe the accelerator by
                    # monitoring every cell at once
                    test["monitor-device-sem"] = sem
                finished = run_fn(test)
                outcome, valid = _outcome_of(finished, latch)
                rec["outcome"], rec["valid"] = outcome, valid
                if finished.get("aborted"):
                    rec["abort-reason"] = str(finished["aborted"])
                err = (finished.get("results") or {}).get("error")
                if err:
                    rec["error"] = str(err)
            except Exception:  # noqa: BLE001 - contained per cell
                logger.warning("campaign cell %s crashed\n%s", cid,
                               traceback.format_exc())
                rec["outcome"] = "crashed"
                rec["error"] = traceback.format_exc(limit=8)
        try:
            rec["path"] = store.path(test) if test else None
        except (AssertionError, AttributeError, KeyError, TypeError):
            # a crashed build may have left a non-test on `test`; path
            # recovery must never take down the campaign loop
            rec["path"] = None
        rec["wall_s"] = round(time.monotonic() - t0, 3)
        rec["compile-cache"] = compile_cache.delta(cc_cell)
        jr.append_cell(rec)
        reg.inc("campaign.cells", outcome=str(rec["outcome"]))
        reg.observe("campaign.cell_s", rec["wall_s"])
        return rec

    hard_abort = None
    try:
        with robust.signal_scope(latch):
            with tr.span("campaign.run", cat="campaign",
                         args={"id": campaign_id,
                               "cells": len(pending)}):
                if parallel <= 1:
                    for cell in pending:
                        run_one(cell)
                else:
                    pool = ThreadPoolExecutor(
                        max_workers=int(parallel),
                        thread_name_prefix="jepsen campaign")
                    try:
                        for f in [pool.submit(run_one, c)
                                  for c in pending]:
                            f.result()
                        pool.shutdown(wait=True)
                    except BaseException:
                        # hard abort (second SIGINT raises
                        # KeyboardInterrupt in the main thread): stop
                        # waiting HERE so finalize below runs and the
                        # exception propagates promptly. Pool threads
                        # are non-daemon — a plain interpreter exit
                        # still joins any cell wedged past the latch
                        # drain; the CLI is immune because hard_main
                        # exits via os._exit once artifacts are down
                        pool.shutdown(wait=False, cancel_futures=True)
                        raise
    except BaseException as e:  # noqa: BLE001 - finalize, then rethrow
        hard_abort = e
        if not latch.is_set():
            latch.set(repr(e))
        logger.warning("campaign %s hard-aborted (%r); journal is "
                       "resumable with --resume", campaign_id, e)

    try:
        cc = compile_cache.delta(cc_before)
        reg.set_gauge("campaign.compile_cache.hits", cc["hits"])
        reg.set_gauge("campaign.compile_cache.misses", cc["misses"])
        if led is not None:
            # persist this campaign's reuse delta, then surface the
            # cross-process aggregate: hits observed across SEPARATE
            # scheduler processes are the ledger's whole point. The
            # cold/warm wall split is the persistent jax compile
            # cache's before/after evidence (fleet.ledger's
            # enable_jax_cache)
            from ..fleet.ledger import fold_walls
            # THIS run's cells only: resumed cells' walls already
            # landed in the prior process's stats event, and
            # Ledger.stats sums events -- re-folding them would
            # inflate cold/warm per resume
            cold, warm = fold_walls([r for r in jr.latest()
                                     if str(r.get("cell"))
                                     not in done])
            led.note_stats(cc["hits"], cc["misses"], cold_wall_s=cold,
                           warm_wall_s=warm)
            try:
                cc = dict(cc, ledger=led.stats())
            except Exception:  # noqa: BLE001 - bookkeeping only
                logger.warning("couldn't aggregate compile-ledger "
                               "stats", exc_info=True)
        aborted = latch.is_set()
        # the journal is the source of truth, latest record per cell:
        # on a hard abort, pool threads may have journaled cells whose
        # futures were never drained
        report = creport.summarize(
            jr.latest(),
            meta={"id": campaign_id}, compile_cache=cc,
            aborted=aborted, abort_reason=latch.reason,
            skipped=len(done))
        jr.write_report(report)
        try:
            tr.dump(store.campaign_path(campaign_id, "trace.jsonl"))
            tr.close_journal(remove=True)
            store._dump_json(reg.snapshot(),
                             store.campaign_path(campaign_id,
                                                 "metrics.json"))
            reg.close_journal(remove=True)
        except Exception:  # noqa: BLE001 - telemetry is a byproduct
            logger.warning("couldn't write campaign obs artifacts",
                           exc_info=True)
        jr.write_meta({**(jr.load_meta() or {}),
                       "status": "aborted" if aborted else "complete",
                       "updated": store.local_time()})
        if capacity_plan is not None:
            # the prediction oracle: predicted (model, bucket) shapes
            # vs the compile shapes this campaign actually noted.
            # CONTAINED -- a crashing oracle costs the report block,
            # never an outcome or the exit code
            try:
                from ..analysis import capplan
                actual = compile_cache.noted_keys() \
                    - (cap_before or set())
                report["capacity"] = capplan.report_section(
                    capacity_plan, actual,
                    path=store.campaign_path(campaign_id,
                                             capplan.PLAN_FILE))
                jr.write_report(report)
            except Exception:  # noqa: BLE001 - oracle is contained
                logger.warning("capacity oracle crashed (contained)",
                               exc_info=True)
        if fleetlint:
            try:
                # control-plane audit (analysis.fleetlint): scheduler
                # campaigns have no leases, but the terminal-guard
                # and single-writer invariants hold here too.
                # CONTAINED -- findings are reported, never allowed
                # to change an outcome or the exit code
                from ..analysis import fleetlint as flint
                fa, _diags = flint.audit(campaign_id)
                report["fleet_analysis"] = {"counts": fa["counts"],
                                            "checks": fa["checks"],
                                            "path": fa.get("path")}
                jr.write_report(report)
            except Exception:  # noqa: BLE001 - audit is contained
                logger.warning("fleetlint audit of campaign %s "
                               "crashed (contained)", campaign_id,
                               exc_info=True)
        if certify:
            try:
                # proof-carrying verdicts, campaign grain: replay a
                # deterministic sample of the cells' persisted
                # certificates against their own run artifacts.
                # CONTAINED -- findings are reported, never allowed
                # to change an outcome or the exit code
                from ..analysis import certify as jcertify
                report["certification"] = \
                    jcertify.certify_campaign(jr.latest())
                jr.write_report(report)
            except Exception:  # noqa: BLE001 - certifier is contained
                logger.warning("campaign certification crashed "
                               "(contained)", exc_info=True)
        if hard_abort is not None:
            raise hard_abort
        return report
    finally:
        # stop the journal flusher threads on EVERY exit path: on the
        # happy path the dumps above already closed them (remove=True)
        # and these are no-ops; on an exceptional exit the journal
        # files are kept -- they are the crash evidence
        tr.close_journal()
        reg.close_journal()
