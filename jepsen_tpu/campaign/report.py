"""Campaign outcome aggregation: summary, flakes, triage.

A finished (or aborted) campaign is a list of cell records::

    {"cell": "seed=1,workload=register", "group": "workload=register",
     "outcome": True|False|"unknown"|"crashed"|"aborted",
     "valid": ..., "path": "store/...", "wall_s": 1.2,
     "error": "...", "abort-reason": "...", "params": {...}}

``summarize`` folds them into one report dict with three derived
views:

* **summary** -- outcome counts (the exit-code inputs).
* **flakes** -- cells that share a *group* (same params minus seed)
  but disagree on validity across seeds: the classic seed-sensitive
  test. Only decided outcomes (True/False/"unknown") participate;
  aborted cells say nothing about the workload.
* **triage** -- every non-passing cell bucketed by its failure
  signature (outcome + first line of error / abort reason), so a sweep
  that crashed forty cells the same way reads as one line, not forty.
"""

from __future__ import annotations

__all__ = ["summarize", "results_map", "render_text"]

#: outcomes that represent a full run with a verdict
DECIDED = (True, False, "unknown")


def _signature(rec):
    """One-line failure signature for triage grouping."""
    outcome = rec.get("outcome")
    reason = rec.get("abort-reason") if outcome == "aborted" \
        else rec.get("error")
    if reason:
        reason = str(reason).strip().splitlines()[-1][:160]
        return f"{outcome}: {reason}"
    return str(outcome)


def flakes(records):
    """Groups (same params minus seed) whose decided cells disagree on
    validity across seeds."""
    groups = {}
    for rec in records:
        if rec.get("outcome") not in DECIDED:
            continue
        groups.setdefault(rec.get("group") or rec.get("cell"),
                          []).append(rec)
    out = []
    for gid, recs in sorted(groups.items()):
        if len(recs) < 2:
            continue
        validities = sorted({str(r.get("valid")) for r in recs})
        if len(validities) > 1:
            out.append({
                "group": gid,
                "validities": validities,
                "cells": [{"cell": r.get("cell"),
                           "valid": r.get("valid"),
                           "path": r.get("path")} for r in recs],
            })
    return out


def triage(records):
    """{signature: [cell ids]} over every non-passing cell."""
    out = {}
    for rec in records:
        if rec.get("outcome") is True:
            continue
        out.setdefault(_signature(rec), []).append(rec.get("cell"))
    return {k: sorted(v) for k, v in sorted(out.items())}


def results_map(records):
    """cli.test_all_* shaped results: outcome -> [{"cell", "path"}].
    Keys are str() outcomes ("True"/"False"/"unknown"/...) so the map
    survives a report.json round trip unchanged -- json.dump would
    silently lowercase raw bool keys to "true"/"false", and a consumer
    reloading the report would then compute the wrong exit code. The
    cli group/exit helpers accept both spellings."""
    out = {}
    for rec in records:
        out.setdefault(str(rec.get("outcome")), []).append(
            {"cell": rec.get("cell"), "path": rec.get("path")})
    return out


def summarize(records, meta=None, compile_cache=None, aborted=False,
              abort_reason=None, skipped=0):
    """The aggregate campaign report dict (persisted as
    report.json)."""
    records = list(records)
    counts = {}
    for rec in records:
        key = str(rec.get("outcome"))
        counts[key] = counts.get(key, 0) + 1
    return {
        "campaign": (meta or {}).get("id"),
        "status": "aborted" if aborted else "complete",
        **({"abort-reason": str(abort_reason)} if abort_reason else {}),
        "summary": {"cells": len(records), "skipped-resumed": skipped,
                    "outcomes": counts},
        "flakes": flakes(records),
        "triage": triage(records),
        **({"compile_cache": compile_cache} if compile_cache is not None
           else {}),
        "cells": records,
        "results": results_map(records),
    }


def render_text(report):
    """Human-readable campaign summary for the CLI."""
    lines = [f"# Campaign {report.get('campaign')}: "
             f"{report.get('status')}"]
    if report.get("abort-reason"):
        lines.append(f"  abort reason: {report['abort-reason']}")
    s = report.get("summary") or {}
    lines.append(f"  cells: {s.get('cells', 0)} "
                 f"({s.get('skipped-resumed', 0)} of them from a "
                 "previous run)")
    for outcome, n in sorted((s.get("outcomes") or {}).items()):
        lines.append(f"    {outcome}: {n}")
    cc = report.get("compile_cache")
    if cc is not None:
        lines.append(f"  compile cache: {cc.get('hits', 0)} hits / "
                     f"{cc.get('misses', 0)} misses")
    if report.get("flakes"):
        lines.append("  flaky groups (validity differs across seeds):")
        for fl in report["flakes"]:
            lines.append(f"    {fl['group']}: "
                         f"{' vs '.join(fl['validities'])}")
    if report.get("triage"):
        lines.append("  triage:")
        for sig, cells in report["triage"].items():
            lines.append(f"    {sig} ({len(cells)}): "
                         f"{', '.join(c or '?' for c in cells[:6])}"
                         + (" ..." if len(cells) > 6 else ""))
    return "\n".join(lines)
