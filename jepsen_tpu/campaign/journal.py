"""Resumable campaign state on disk.

Layout (under ``store/campaigns/<campaign-id>/``, see store.py):

* ``campaign.json`` -- the campaign's identity: id, planned cell ids,
  scheduling knobs, created/updated stamps, status. Written atomically
  (tmp + rename) at start and finalize.
* ``cells.jsonl`` -- the outcome journal: one JSON line per finished
  cell, appended and flushed the moment the cell completes (the same
  crash-only discipline as store.HistoryJournal), so SIGKILL loses at
  most the line being written. A torn final line is dropped on read.
* ``report.json`` -- the aggregated report (report.py), written when
  the campaign finishes or aborts.

Resume contract: a cell is *completed* when its latest journal record
has any outcome other than ``"aborted"`` (an aborted cell's history
was salvaged, but the cell never got its full run, so ``--resume``
executes it again). Cells with no record never started. The journal is
integrated with ``robust.AbortLatch`` by the scheduler: a latched
abort stops new cells, records in-flight cells as aborted, and leaves
everything here ready for ``--resume``.
"""

from __future__ import annotations

import json
import os
import socket
import threading

from .. import store

__all__ = ["CampaignJournal", "writer_id"]

META_FILE = "campaign.json"
CELLS_FILE = "cells.jsonl"
REPORT_FILE = "report.json"


def writer_id():
    """THIS process's journal-writer identity (``host:pid``). Every
    appended record is stamped with it, which is what lets the
    fleetlint auditor prove the single-writer invariant from the
    journal alone: two coordinators appending concurrently leave
    interleaved writer identities (FL004) -- the oracle the planned
    coordinator-HA handoff will be soaked against. A resumed campaign
    legitimately has a NEW writer; its records form a contiguous run."""
    return f"{socket.gethostname()}:{os.getpid()}"


class CampaignJournal:
    """Owner of one campaign's on-disk state."""

    def __init__(self, campaign_id):
        assert campaign_id, "campaign needs an id"
        self.campaign_id = str(campaign_id)
        self.dir = store.campaign_path(self.campaign_id)
        os.makedirs(self.dir, exist_ok=True)
        self.writer = writer_id()
        #: coordinator epoch (fleet.ha): when set, every appended
        #: record is stamped with it so the FL016 chain audit can
        #: prove post hoc that no fenced (pre-takeover) coordinator's
        #: append slipped in after the takeover record
        self.epoch = None
        self._lock = threading.Lock()

    # -- paths ----------------------------------------------------------

    @property
    def meta_path(self):
        return os.path.join(self.dir, META_FILE)

    @property
    def cells_path(self):
        return os.path.join(self.dir, CELLS_FILE)

    @property
    def report_path(self):
        return os.path.join(self.dir, REPORT_FILE)

    # -- campaign.json --------------------------------------------------

    def write_meta(self, meta):
        """Atomically persist campaign.json (tmp + rename: a campaign
        killed mid-write keeps the previous consistent copy)."""
        store._dump_json(dict(meta, id=self.campaign_id),
                         self.meta_path)

    def load_meta(self):
        """The campaign.json dict, or None when this campaign was
        never started."""
        try:
            with open(self.meta_path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    # -- cells.jsonl ----------------------------------------------------

    def append_cell(self, record):
        """Append one finished cell's record and flush+fsync: the
        journal must survive whatever kills the process next.

        If the previous process died MID-append the file ends in a torn
        line without a newline; appending straight onto it would merge
        this record into the fragment and corrupt both, so the torn
        tail is terminated first (the read path skips the fragment)."""
        assert not record.get("event"), \
            "outcome records must not carry an 'event' key"
        self._append_line(record)

    def append_event(self, record):
        """Append one bookkeeping event (fleet lease grant/failure):
        same crash-only discipline as outcomes, but the record carries
        an ``"event"`` key so the latest-per-cell outcome fold
        (store.latest_campaign_records) skips it -- the journal stays
        the single source of truth for BOTH who holds a cell and what
        finally happened to it."""
        assert record.get("event"), "event records need an 'event' key"
        self._append_line(record)

    def _append_line(self, record):
        # stamp the writer identity unless the caller already chose one
        # (golden-journal test fixtures forge foreign writers on
        # purpose); setdefault on a copy -- the caller's dict is theirs
        record = dict(record)
        record.setdefault("writer", self.writer)
        if self.epoch is not None:
            record.setdefault("epoch", self.epoch)
        line = json.dumps(record, cls=store._Encoder)
        with self._lock:
            torn = False
            try:
                with open(self.cells_path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    torn = f.read(1) != b"\n"
            except (FileNotFoundError, OSError):
                pass        # absent or empty: nothing to terminate
            with open(self.cells_path, "a") as f:
                if torn:
                    f.write("\n")
                f.write(line + "\n")
                f.flush()
                try:
                    os.fsync(f.fileno())
                except OSError:  # pragma: no cover - exotic fs
                    pass

    def records(self):
        """All journal records in append order (outcomes AND events); a
        torn final line (killed mid-append) is dropped rather than
        fatal."""
        return store.load_campaign_records(self.campaign_id)

    def events(self):
        """Bookkeeping event records only (store's shared filter)."""
        return store.campaign_events(self.campaign_id)

    def latest(self):
        """One record per cell, latest wins (store's shared fold)."""
        return store.latest_campaign_records(self.campaign_id)

    def completed(self):
        """{cell_id: record} for cells whose latest record is terminal
        (anything but "aborted") -- the set ``--resume`` skips."""
        return {rec.get("cell"): rec for rec in self.latest()
                if rec.get("outcome") != "aborted"}

    # -- report.json ----------------------------------------------------

    def write_report(self, report):
        store._dump_json(report, self.report_path)

    def load_report(self):
        try:
            with open(self.report_path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
