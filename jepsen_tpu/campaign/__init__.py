"""Campaigns: many test runs as one schedulable, resumable unit.

A *campaign* turns the one-test-at-a-time harness into a fleet
scheduler. Its pieces:

* **plan** -- expands a declarative sweep matrix (workload x nemesis x
  concurrency x time-limit x seed, or any axes you like) into *test
  cells* with deterministic ids, validated by the planlint PL012 pass.
* **scheduler** -- runs cells on a bounded worker pool so CPU-side
  harness phases (db setup, generator, interpreter) overlap, while a
  device-slot semaphore serializes the expensive device checker
  searches per accelerator.
* **compile_cache** -- process-wide bookkeeping for cross-run compile
  reuse: shape-identical cells hit jax's jit cache instead of
  recompiling the WGL search, and the hit/miss counters prove it
  (surfaced through `obs` and the campaign report).
* **journal** -- persistent campaign state under
  ``store/campaigns/<id>/`` (``campaign.json`` + an append-only
  ``cells.jsonl``), so SIGINT/SIGKILL leaves a resumable campaign and
  ``--resume`` skips completed cells.
* **report** -- outcome aggregation: summary counts, flake detection
  (same cell params, different seeds, differing validity), and triage
  grouping by abort-reason/error.

The CLI front doors are ``python -m jepsen_tpu campaign ...`` and
``test-all --parallel N [--resume]`` (cli.py); see doc/campaign.md.

Submodules that pull in the full harness (scheduler -> core -> checker
-> jax) load lazily, so lightweight consumers -- in particular
checker.jax_wgl's compile-cache hook -- can import
``jepsen_tpu.campaign.compile_cache`` without the heavy chain.
"""

from __future__ import annotations

from . import compile_cache  # noqa: F401  (dependency-light, eager)

_LAZY = ("plan", "scheduler", "journal", "report")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in ("run_cells", "CampaignError"):
        from . import scheduler
        return getattr(scheduler, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["compile_cache", "plan", "scheduler", "journal", "report",
           "run_cells", "CampaignError"]
